"""The compiled-program contract auditor (pass 1 of ``sgcn_tpu.analysis``).

For every supported configuration of the mode matrix (``modes``), lower
the REAL program — the trainer's step via ``FullBatchTrainer.lower_step``
(both the stale and full-sync programs for pipelined modes), the
mini-batch shared-envelope step via ``MiniBatchTrainer.lower_step``, the
serve bucket program via ``ServeEngine.lower_bucket`` — on the virtual
8-device mesh (``.lower()`` only: no compile, no execution) and check the
module text against the plan-derived :class:`~.expect.Expectation`:

  * **collective census** — exactly one ``all_to_all`` per dense exchange;
    exactly one ``collective_permute`` per LIVE ragged round (empty rounds
    elided, pinned on a banded fixture whose ring keeps 2 of k−1 rounds);
    one full-mesh grad-psum per parameter leaf; one logit-gather psum per
    serve program; the GAT per-layer softmax ``pmax``; nothing else — no
    ``all_gather``/``reduce_scatter``, no sub-mesh replica groups;
  * **wire dtype** — bf16 actually ON the wire when ``--halo-dtype
    bfloat16`` (or the GAT packed form) was requested, and the full f32
    wire on ``--halo-delta`` sync-step re-bases;
  * **wire shape** — buffers match ``CommPlan.wire_buffer_shapes`` ×
    the model's lane widths (the ``(k, S, f)`` pad / per-round ``S_d``);
  * **host callbacks** — no python-callback custom calls, no
    infeed/outfeed/send/recv, no unknown custom-call targets;
  * **donation** — params, optimizer state and stale carries carry
    ``jax.buffer_donor`` (the lowering-time face of ``donate_argnums``);
    plan arrays and batch data do NOT; serve programs donate NOTHING.

A violation names its rule (``collective-census`` / ``wire-dtype`` /
``wire-shape`` / ``host-callback`` / ``donation`` /
``halo-materialization`` — the ragged-Pallas modes' "no HBM halo table"
contract) so the tier-1 mutation checks (``tests/test_analysis.py``,
``tests/test_pallas_ragged.py``) can prove each rule class fails on a
seeded violation.

A second, COMPILING pass (``run_memory_audit`` / ``memory_audit_mode``)
shares the same program builders via ``lower_mode_programs`` and joins
``compiled.memory_analysis()`` against the owner's analytic per-chip
footprint model (``sgcn_tpu.obs.memory``) — the ``memory-model`` rule:
measured peak within tolerance of the analytic total, argument bytes a
subset of the modeled residency, and donation aliasing at least the
params+opt floor (zero for serve).  Mutation-checked by seeding a
stripped ``donate_argnums`` (``tests/test_memory_obs.py``).
"""

from __future__ import annotations

import contextlib
import os
import re
from collections import Counter
from functools import lru_cache

import numpy as np

from . import expect
from .hlo import (HOST_TRANSFER_KINDS, collective_ops, host_callback_targets,
                  main_args, unknown_custom_calls)
from .modes import Mode, fast_modes, supported_modes

# audit fixture dimensions: small enough that a full-matrix run is tens of
# seconds of pure lowering, structured enough that nothing degenerates
# (k=8 chips, every chip has real halo traffic, widths hit both the
# aggregate-first order and an even fout for the GAT packed form)
AUDIT_K = 8
AUDIT_N = 96
AUDIT_FIN = 8
AUDIT_WIDTHS = (8, 4)
# replica-mode audits run at this fixed budget: large enough that the
# shrunken nrep pads differ from the full ones on the ER fixture (the
# wire-shape rule sees real shrinkage), small enough that every chip
# keeps non-replica traffic (all rounds stay live)
AUDIT_REPLICA_B = 12


@lru_cache(maxsize=None)
def audit_plan(kind: str = "er"):
    """The audit's graph fixtures.

    ``'er'``: an Erdős–Rényi graph under a balanced random partition —
    every chip pair exchanges rows, so all k−1 ragged rounds are live (the
    dense census).  ``'banded'``: a ±2-ring graph under a CONTIGUOUS
    partition — each part talks only to its neighbors, so exactly rounds
    d ∈ {1, k−1} are live and the other k−3 must be ELIDED from the traced
    program (the empty-round census).
    """
    import scipy.sparse as sp

    from ..io.datasets import er_graph
    from ..parallel import build_comm_plan
    from ..partition import balanced_random_partition
    from ..prep import normalize_adjacency

    if kind == "er":
        ahat = normalize_adjacency(er_graph(AUDIT_N, 6, seed=0))
        pv = balanced_random_partition(AUDIT_N, AUDIT_K, seed=1)
    elif kind == "banded":
        n = AUDIT_N
        rows = np.concatenate([np.arange(n), np.arange(n)])
        cols = np.concatenate([(np.arange(n) + 1) % n,
                               (np.arange(n) + 2) % n])
        a = sp.coo_matrix((np.ones(2 * n, np.float32),
                           (rows, cols)), shape=(n, n))
        ahat = normalize_adjacency(((a + a.T) > 0).astype(np.float32))
        pv = np.arange(n) * AUDIT_K // n           # contiguous parts
    else:
        raise ValueError(f"unknown audit fixture {kind!r}")
    plan = build_comm_plan(ahat, pv, AUDIT_K)
    return plan


@contextlib.contextmanager
def _pallas_env(on: bool):
    """Pin the kernel-family selection for the duration of a trace:
    ``use_pallas_spmm`` reads ``$SGCN_PALLAS_SPMM`` at call time, and the
    audit must be deterministic BOTH ways — a pallas mode forces the
    kernel on, every other mode forces it off (an ambient =1 in the
    operator's shell must not flip the non-pallas census)."""
    old = os.environ.get("SGCN_PALLAS_SPMM")
    os.environ["SGCN_PALLAS_SPMM"] = "1" if on else "0"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("SGCN_PALLAS_SPMM", None)
        else:
            os.environ["SGCN_PALLAS_SPMM"] = old


@contextlib.contextmanager
def _gat_form_env(form: str | None):
    """Pin the GAT table form for the duration of a trace: the forward
    reads ``$SGCN_GAT_FUSED`` at call time (``models.gat._fused_form``),
    so the env must hold while ``.lower()`` traces."""
    if form is None or form == "packed":
        # packed is selected by compute_dtype, not env
        yield
        return
    old = os.environ.get("SGCN_GAT_FUSED")
    os.environ["SGCN_GAT_FUSED"] = {"fused": "2", "split": "0"}[form]
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("SGCN_GAT_FUSED", None)
        else:
            os.environ["SGCN_GAT_FUSED"] = old


# ------------------------------------------------------------------ checks
def _viol(rule: str, detail: str) -> dict:
    return {"rule": rule, "detail": detail}


def _multiset_diff(expected, observed):
    e, o = Counter(expected), Counter(observed)
    missing = list((e - o).elements())
    extra = list((o - e).elements())
    return missing, extra


def _full_mesh_groups(op, k: int) -> bool:
    """True iff the op reduces over ONE group of all ``k`` devices.  A
    sub-mesh reduction prints as MULTIPLE groups (``dense<[[0, 1, 2, 3],
    [4, 5, 6, 7]]>``) — the realistic regression shape — or as one group
    smaller than ``k``; both must fail."""
    m = re.search(r"replica_groups\s*=\s*dense<\[(.*?)\]>\s*:", op.text,
                  re.S)
    if not m:
        return True        # unusual print form: do not false-positive
    groups = re.findall(r"\[([0-9,\s]*)\]", m.group(1))
    if not groups:
        # a 1-group form may print without inner brackets
        groups = [m.group(1)]
    if len(groups) != 1:
        return False
    return len([x for x in groups[0].split(",") if x.strip()]) == k


def check_program(text: str, exp: "expect.Expectation", k: int) -> tuple:
    """Audit one lowered module against its expectation; returns
    ``(violations, census)``."""
    ops = collective_ops(text)
    violations: list[dict] = []

    # ---- census of exchange collectives (count + shape + dtype)
    ex_ops = [op for op in ops
              if op.kind in ("all_to_all", "collective_permute")]
    observed = [(op.kind, op.wire[0], op.wire[1]) for op in ex_ops]
    if Counter(observed) != Counter(exp.exchanges):
        by_kind_o = Counter(kind for kind, _, _ in observed)
        by_kind_e = Counter(kind for kind, _, _ in exp.exchanges)
        if by_kind_o != by_kind_e:
            violations.append(_viol(
                "collective-census",
                f"exchange dispatch counts {dict(by_kind_o)} != expected "
                f"{dict(by_kind_e)} (one all_to_all per dense exchange, "
                "one collective_permute per LIVE ragged round)"))
        shp_o = Counter((kk, s) for kk, s, _ in observed)
        shp_e = Counter((kk, s) for kk, s, _ in exp.exchanges)
        if shp_o != shp_e:
            miss, extra = _multiset_diff(
                [(kk, s) for kk, s, _ in exp.exchanges],
                [(kk, s) for kk, s, _ in observed])
            violations.append(_viol(
                "wire-shape",
                f"wire buffer shapes drifted from the plan pads: "
                f"missing={miss} unexpected={extra}"))
        dt_o = Counter((kk, d) for kk, _, d in observed)
        dt_e = Counter((kk, d) for kk, _, d in exp.exchanges)
        if dt_o != dt_e:
            miss, extra = _multiset_diff(
                [(kk, d) for kk, _, d in exp.exchanges],
                [(kk, d) for kk, _, d in observed])
            violations.append(_viol(
                "wire-dtype",
                f"wire operand dtypes != requested: missing={miss} "
                f"unexpected={extra}"))
        if by_kind_o == by_kind_e and shp_o == shp_e and dt_o == dt_e:
            violations.append(_viol(
                "wire-dtype",
                "exchange (shape, dtype) pairing drifted: "
                f"observed={sorted(map(str, observed))} "
                f"expected={sorted(map(str, exp.exchanges))}"))

    # ---- census of reductions
    reduces = [op for op in ops if op.kind == "all_reduce"]
    grad_like, scalar_adds, maxes, other = [], 0, 0, []
    tensor_expected = Counter(exp.grad_shapes) + Counter(
        exp.gather_shapes)
    for op in reduces:
        shape, _dt = op.wire
        if op.reducer == "maximum":
            maxes += 1
        elif op.reducer == "add" and shape == ():
            scalar_adds += 1
        elif op.reducer == "add":
            grad_like.append(shape)
        else:
            other.append((op.reducer, shape))
        if not _full_mesh_groups(op, k):
            violations.append(_viol(
                "collective-census",
                f"all_reduce at line {op.line} reduces over a sub-mesh "
                "replica group — every psum in these programs is "
                "full-mesh"))
    if Counter(grad_like) != tensor_expected:
        miss, extra = _multiset_diff(list(tensor_expected.elements()),
                                     grad_like)
        violations.append(_viol(
            "collective-census",
            "grad-sync/logit-gather psum census: one full-mesh add-"
            f"allreduce per tensor expected; missing={miss} "
            f"unexpected={extra}"))
    if scalar_adds != exp.scalar_psums:
        violations.append(_viol(
            "collective-census",
            f"{scalar_adds} scalar add-allreduces, expected "
            f"{exp.scalar_psums} (the masked-loss machinery — "
            "expect.XENT_SCALAR_PSUMS)"))
    if maxes != exp.max_psums:
        violations.append(_viol(
            "collective-census",
            f"{maxes} max-allreduces, expected {exp.max_psums} (the GAT "
            "per-layer softmax stabilizer pmax)"))
    if other:
        violations.append(_viol(
            "collective-census", f"unclassifiable all_reduce ops: {other}"))
    stray = [op.kind for op in ops
             if op.kind in ("all_gather", "reduce_scatter")]
    if stray:
        violations.append(_viol(
            "collective-census",
            f"unexpected collective kinds {Counter(stray)} — these "
            "programs ship halos by all_to_all/ppermute and reduce by "
            "psum only"))

    # ---- halo materialization (ragged-Pallas modes): the ring's receive
    # buffers must feed the kernel directly — a scatter producing the
    # (R, f_ℓ) halo-table signature means the program assembled the HBM
    # halo table first (expect.pallas_ragged_forbidden_scatters; shapes
    # colliding with legitimate scatters were dropped at build time)
    if exp.forbidden_scatters:
        from .hlo import scatter_result_types

        seen = {tuple(s) for s, _d in scatter_result_types(text)}
        hits = [s for s in exp.forbidden_scatters if tuple(s) in seen]
        if hits:
            violations.append(_viol(
                "halo-materialization",
                f"scatter(s) with halo-table result shape(s) {hits} — "
                "the ragged-Pallas program must fold ring receives "
                "inside the VMEM tile accumulator, never assemble the "
                "(R, f) halo table in HBM"))

    # ---- host transfers / callbacks
    transfers = [op.kind for op in ops if op.kind in HOST_TRANSFER_KINDS]
    if transfers:
        violations.append(_viol(
            "host-callback",
            f"host-transfer ops {Counter(transfers)} inside a step "
            "program"))
    cbs = host_callback_targets(text)
    if cbs:
        violations.append(_viol(
            "host-callback",
            f"python-callback custom calls {cbs} inside a step program — "
            "a host round-trip on the hot path"))
    unknown = unknown_custom_calls(text)
    if unknown:
        violations.append(_viol(
            "host-callback",
            f"unrecognized custom-call targets {sorted(set(unknown))} — "
            "extend hlo.BENIGN_CUSTOM_CALLS only after establishing the "
            "target stays on-device"))

    # ---- donation / aliasing (ONE parse of the argument list — a printer
    # change that breaks @main parsing must land as a reported violation,
    # never as an uncaught exception aborting the whole audit)
    try:
        args = main_args(text)
    except ValueError as e:
        args = None
        violations.append(_viol("donation", str(e)))
    if args is not None:
        violations += check_donation(args, exp)

    census = {
        "all_to_all": sum(1 for o in observed if o[0] == "all_to_all"),
        "collective_permute": sum(1 for o in observed
                                  if o[0] == "collective_permute"),
        "all_reduce": {"tensor_add": len(grad_like),
                       "scalar_add": scalar_adds, "max": maxes},
        "wire_dtypes": sorted({d for _, _, d in observed}),
        "donated_args": (None if args is None
                         else sum(1 for a in args if a.donated)),
    }
    return violations, census


def check_donation(args, exp: "expect.Expectation") -> list[dict]:
    """Align the module's arguments with the expected (shape, dtype, class)
    layout and verify ``jax.buffer_donor`` markers: every surviving
    donate-class argument (params, optimizer state, stale carries) must
    carry one; no keep-class argument (plan arrays, batch data, serve
    inputs) may.  Arguments jit pruned as unused (e.g. the non-delta base
    placeholders, a dead ghalo) show up as skips in the order-preserving
    alignment — donation of a DEAD buffer is not a contract.  ``args`` is
    the module's parsed ``hlo.main_args`` list (the caller parses once,
    shared with the census)."""
    violations = []
    ei = 0
    for a in args:
        while ei < len(exp.args) and \
                (exp.args[ei][0], exp.args[ei][1]) != a.type:
            ei += 1                    # expected arg pruned from the module
        if ei == len(exp.args):
            violations.append(_viol(
                "donation",
                f"%arg{a.index} tensor<{a.type}> does not align with the "
                "expected argument layout (params, opt state, carries, "
                "plan arrays, data) — argument-order drift"))
            return violations
        shape, dt, klass = exp.args[ei]
        ei += 1
        if klass == "donate" and not a.donated:
            violations.append(_viol(
                "donation",
                f"%arg{a.index} tensor{shape}x{dt} (params/opt-state/"
                "stale-carry class) carries no jax.buffer_donor — "
                "donate_argnums dropped; the step would double-buffer "
                "every update"))
        elif klass == "keep" and a.donated:
            violations.append(_viol(
                "donation",
                f"%arg{a.index} tensor{shape}x{dt} (plan-array/data "
                "class) is donated — reused buffers must not be"))
    return violations


# -------------------------------------------------------------- mode audit
def lower_mode_programs(mode: Mode, plan=None) -> tuple:
    """Build the real trainer/engine for ``mode`` and lower its program(s)
    WITHOUT rendering; returns ``(owner, [(label, lowered, expectation)])``.

    ``owner`` is the trainer/engine that built the programs — it carries
    the analytic per-chip footprint model as ``.memory`` — and each
    ``lowered`` is the un-compiled jax AOT lowering: the text audit renders
    it (``.as_text()``), the memory audit compiles it (``.compile()``) and
    joins ``compiled.memory_analysis()`` against ``owner.memory``.  Both
    passes share the SAME builders so they can never audit divergent
    programs."""
    from ..train import FullBatchTrainer

    plan = audit_plan() if plan is None else plan
    if mode.workload == "train":
        kw: dict = {"comm_schedule": mode.schedule}
        if mode.model == "gcn":
            kw.update(halo_dtype=mode.halo_dtype,
                      halo_staleness=mode.staleness,
                      halo_delta=mode.delta,
                      sync_every=2 if (mode.staleness or mode.replica)
                      else 0,
                      replica_budget=AUDIT_REPLICA_B if mode.replica
                      else 0)
        else:
            kw.update(compute_dtype=mode.compute_dtype)
        with _gat_form_env(mode.gat_form), \
                _pallas_env(getattr(mode, "pallas", False)):
            tr = FullBatchTrainer(plan, fin=AUDIT_FIN,
                                  widths=list(AUDIT_WIDTHS),
                                  model=mode.model, **kw)
            # the audit must never silently check the WRONG aggregator:
            # a pallas mode that fell back to the slot-pass path would
            # share its census and pass vacuously
            if getattr(mode, "pallas", False) != \
                    ("pallas_tb" in tr._fwd_static):
                raise RuntimeError(
                    f"mode {mode.mode_id}: Pallas selection "
                    f"{'did not fire' if mode.pallas else 'fired'} "
                    "(fwd_static keys "
                    f"{sorted(tr._fwd_static)})")
            if mode.staleness:
                return tr, [
                    ("stale", tr.lower_step(kind="stale"),
                     expect.train_expectation(tr, mode, fresh=False)),
                    ("sync", tr.lower_step(kind="sync"),
                     expect.train_expectation(tr, mode, fresh=True)),
                ]
            if mode.replica:
                # both programs of a replica mode are audited: the replica
                # step must ship the SHRUNKEN wire shapes, the refresh step
                # the full exact exchange (with every backward exchange
                # kept alive by the gradient-replica refresh)
                return tr, [
                    ("rep", tr.lower_step(kind="rep"),
                     expect.train_expectation(tr, mode, fresh=False)),
                    ("sync", tr.lower_step(kind="rep_sync"),
                     expect.train_expectation(tr, mode, fresh=True)),
                ]
            return tr, [("step", tr.lower_step(),
                         expect.train_expectation(tr, mode))]
    if mode.workload == "minibatch":
        from ..train.minibatch import MiniBatchTrainer

        if plan is not None and plan is not audit_plan():
            raise ValueError(
                "the minibatch audit entry builds its own per-batch plans "
                "from the ER fixture graph; a custom plan would be "
                "silently ignored here — extend lower_mode_programs "
                "instead")
        with _pallas_env(False):
            mb = MiniBatchTrainer(
                _audit_ahat(), np.asarray(audit_plan().owner), AUDIT_K,
                fin=AUDIT_FIN, widths=list(AUDIT_WIDTHS),
                batch_size=AUDIT_N // 2, nbatches=2,
                comm_schedule=mode.schedule)
            return mb, [("envelope-step", mb.lower_step(),
                         expect.train_expectation(mb.inner, mode))]
    if mode.workload == "serve":
        from ..serve.engine import ServeEngine

        bucket = 8
        with _gat_form_env(mode.gat_form), _pallas_env(False):
            eng = ServeEngine(plan, fin=AUDIT_FIN,
                              widths=list(AUDIT_WIDTHS), model=mode.model,
                              comm_schedule=mode.schedule,
                              halo_dtype=mode.halo_dtype,
                              max_batch=bucket, buckets=(bucket,),
                              precompile=False)
            return eng, [(f"bucket{bucket}",
                          eng.lower_bucket(bucket),
                          expect.serve_expectation(eng, mode, bucket))]
    if mode.workload == "serve_subgraph":
        from ..serve.engine import ServeEngine

        with _gat_form_env(mode.gat_form), _pallas_env(False):
            eng = ServeEngine(plan, fin=AUDIT_FIN,
                              widths=list(AUDIT_WIDTHS), model=mode.model,
                              comm_schedule=mode.schedule,
                              halo_dtype=mode.halo_dtype,
                              max_batch=8, buckets=(8,),
                              precompile=False, mode="subgraph")
            from ..serve.subgraph import representative_key

            key = representative_key(eng.sgindex)
            return eng, [("subgraph",
                          eng.lower_subgraph(key),
                          expect.serve_subgraph_expectation(eng, mode, key))]
    raise ValueError(f"unknown workload {mode.workload!r}")


def lower_mode(mode: Mode, plan=None) -> list[tuple]:
    """Build the real trainer/engine for ``mode`` and lower its program(s);
    returns ``[(program_label, module_text, expectation)]``."""
    _owner, programs = lower_mode_programs(mode, plan=plan)
    return [(label, lowered.as_text(), exp)
            for label, lowered, exp in programs]


@lru_cache(maxsize=1)
def _audit_ahat():
    from ..io.datasets import er_graph
    from ..prep import normalize_adjacency

    return normalize_adjacency(er_graph(AUDIT_N, 6, seed=0))


def audit_mode(mode: Mode, plan=None) -> dict:
    """Lower and audit one mode; returns its report entry."""
    programs = lower_mode(mode, plan=plan)
    entry: dict = {"ok": True, "programs": {}}
    for label, text, exp in programs:
        violations, census = check_program(text, exp, AUDIT_K)
        entry["programs"][label] = {
            "ok": not violations,
            "violations": violations,
            "census": census,
        }
        entry["ok"] = entry["ok"] and not violations
    return entry


def run_audit(modes=None, fast: bool = False) -> dict:
    """Audit the mode matrix; returns the ``hlo`` block of the analysis
    report.  ``fast`` audits the 2-mode smoke subset; the full run also
    audits the banded fixture's ragged modes (the empty-round-elision
    census: only 2 of k−1 rounds may appear in the program)."""
    if modes is None:
        modes = fast_modes() if fast else supported_modes()
    out: dict = {"modes": {}, "ok": True}
    for mode in modes:
        entry = audit_mode(mode)
        out["modes"][mode.mode_id] = entry
        out["ok"] = out["ok"] and entry["ok"]
    if not fast:
        from ..ops.pspmm import ragged_live_rounds

        banded = audit_plan("banded")
        live = ragged_live_rounds(banded.ragged_round_sizes())
        assert len(live) < AUDIT_K - 1, (
            "banded fixture lost its empty rounds — the elision census "
            "checks nothing")
        for mode in (Mode("train", "gcn", "ragged"),
                     Mode("train", "gcn", "ragged", staleness=1),
                     # the composed replica × stale ring: the SHRUNKEN
                     # nrep ring's empty rounds must elide too
                     Mode("train", "gcn", "ragged", staleness=1,
                          replica=True),
                     # the ragged-Pallas ring rides the same elision rule
                     # (pallas_ring_concat skips S_d = 0 rounds at trace
                     # time) — and the halo-materialization rule must
                     # hold on a partially-live ring too
                     Mode("train", "gcn", "ragged", pallas=True)):
            entry = audit_mode(mode, plan=banded)
            out["modes"][mode.mode_id + "@banded"] = entry
            out["ok"] = out["ok"] and entry["ok"]
    out["n_modes"] = len(out["modes"])
    return out


# ------------------------------------------------------------ memory audit
def memory_audit_mode(mode: Mode, plan=None,
                      tol: float | None = None) -> dict:
    """COMPILE every program of ``mode`` and reconcile XLA's own
    ``memory_analysis()`` figures against the owner's analytic footprint
    model (``trainer.memory`` / ``engine.memory``); returns the mode's
    report entry.  Violations carry the ``memory-model`` rule:

      * measured peak must stay within ``MEM_MODEL_TOL`` × the analytic
        total (the model is the residency upper envelope);
      * measured argument bytes must not exceed the modeled resident
        arguments (jit prunes inputs, it never invents them);
      * aliased (donated) bytes must cover the params+opt floor on train
        programs and be exactly zero on serve programs — a stripped
        ``donate_argnums`` trips this deterministically (the mutation
        check of ``tests/test_memory_obs.py``).

    Unlike the text audit this pass compiles (~1 s/program on the CPU
    mesh), so callers subset the matrix: the tier-1 test pins family
    representatives, the full sweep rides ``python -m sgcn_tpu.analysis
    --memory``.
    """
    from ..obs.memory import MEM_MODEL_TOL, measure_compiled, reconcile

    owner, programs = lower_mode_programs(mode, plan=plan)
    model = owner.memory
    entry: dict = {"ok": True, "model_bytes": model.total_bytes,
                   "programs": {}}
    for label, lowered, _exp in programs:
        measured = measure_compiled(lowered.compile())
        if measured is None:
            # the backend exposes no memory_analysis(): the measured side
            # is unverifiable here — surface that, don't fail (every CI
            # backend exposes it; the analytic side still gates budgets)
            entry["programs"][label] = {"ok": True, "skipped": True,
                                        "violations": [], "measured": None}
            continue
        rec = reconcile(model, measured,
                        tol=MEM_MODEL_TOL if tol is None else tol)
        violations = [_viol("memory-model", v) for v in rec["violations"]]
        entry["programs"][label] = {
            "ok": not violations,
            "violations": violations,
            "measured": measured,
            "ratio": rec["block"]["total"]["ratio"],
        }
        entry["ok"] = entry["ok"] and not violations
    return entry


def run_memory_audit(modes=None, fast: bool = False) -> dict:
    """Memory-reconcile the mode matrix; returns the ``memory`` block of
    the analysis report.  Same shape contract as :func:`run_audit`
    (``{modes: {mode_id: entry}, ok, n_modes, tol}``) so the report
    renderer and the gate logic treat both passes uniformly."""
    from ..obs.memory import MEM_MODEL_TOL

    if modes is None:
        modes = fast_modes() if fast else supported_modes()
    out: dict = {"modes": {}, "ok": True, "tol": MEM_MODEL_TOL}
    for mode in modes:
        entry = memory_audit_mode(mode)
        out["modes"][mode.mode_id] = entry
        out["ok"] = out["ok"] and entry["ok"]
    out["n_modes"] = len(out["modes"])
    return out
