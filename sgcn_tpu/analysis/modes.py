"""The mode-matrix enumerator — ONE source of truth for "which
configurations does this repo support", shared by the HLO auditor, the
tests, and the composition matrix in ``docs/comm_schedule.md``.

A :class:`Mode` names one point of the support matrix:

    {train, serve} × {gcn, gat} × {a2a, ragged} × staleness {0, 1}
    × halo-dtype {f32, bf16} × delta {off, on} × GAT table form

``supported_modes()`` enumerates exactly the combinations the trainers and
the serve engine accept — the same gates ``FullBatchTrainer.__init__`` and
``ServeEngine.__init__`` enforce at construction time, encoded ONCE more
here so the auditor cannot silently skip a supported mode and the doc
matrix cannot drift (``tests/test_analysis.py`` cross-checks the table).

``MODE_FLAGS`` maps every mode-selecting CLI flag to its matrix axis; the
AST hygiene pass (``ast_rules``) asserts every ``--comm-*`` / ``--halo-*``
flag any CLI defines appears here, so a new transport/wire knob cannot
land without extending the enumerator (and therefore the audit).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

# mode-selecting CLI flags → matrix axis.  The AST pass enforces the
# reverse direction too: every MODE_FLAGS key must exist on the trainer
# CLI (no dead axes).
MODE_FLAGS = {
    "--model": "model",
    "--comm-schedule": "schedule",
    "--halo-staleness": "staleness",
    "--halo-dtype": "halo_dtype",
    "--halo-delta": "delta",
    "--replica-budget": "replica",
}

# knobs that look mode-like but are deliberately NOT matrix axes — named
# here so the exclusion is a recorded decision, not an oversight
NON_AXIS_FLAGS = {
    "--sync-every": "continuous schedule knob — audited via the stale/sync "
                    "program PAIR every stale mode lowers, not as an axis",
    "--refresh-band": "continuous refresh-policy knob of the replica mode "
                      "(drift-banded partial refresh) — its program is "
                      "exercised by tests/test_replica_stale.py; deferred "
                      "as an audit axis",
}

GAT_FORMS = ("fused", "split", "packed")


@dataclass(frozen=True)
class Mode:
    """One point of the supported configuration matrix."""

    workload: str                  # 'train' | 'serve' | 'serve_subgraph'
    #                                | 'minibatch'; 'serve_subgraph' is the
    #                                query-proportional serving program
    #                                (docs/serving.md phase 2): no
    #                                per-layer exchange, one logit psum
    model: str                     # 'gcn' | 'gat'
    schedule: str                  # 'a2a' | 'ragged'
    staleness: int = 0             # 0 exact | 1 pipelined
    halo_dtype: str | None = None  # None (f32 wire) | 'bfloat16'
    delta: bool = False            # halo-delta cache (stale GCN only)
    gat_form: str | None = None    # 'fused' | 'split' | 'packed' (GAT only)
    replica: bool = False          # hot-halo replication, B > 0 (GCN only;
    #                                the axis is binary — the audit runs at
    #                                a fixed small B, hlo_audit.AUDIT_REPLICA_B)
    pallas: bool = False           # VMEM Pallas aggregator (exact mode,
    #                                both models × both schedules — the
    #                                env-selected kernel family,
    #                                ops/pallas_spmm.py::use_pallas_spmm;
    #                                the audit pins SGCN_PALLAS_SPMM per
    #                                mode)

    @property
    def mode_id(self) -> str:
        parts = [self.workload, self.model, self.schedule]
        if self.model == "gat":
            parts.append(self.gat_form or "fused")
        else:
            parts.append(f"s{self.staleness}")
            parts.append("bf16" if self.halo_dtype == "bfloat16" else "f32")
            if self.delta:
                parts.append("delta")
            if self.replica:
                parts.append("rep")
        if self.pallas:
            parts.append("pallas")
        return "/".join(parts)

    @property
    def compute_dtype(self) -> str | None:
        """The trainer-level lever that selects the GAT packed wire form
        (``models.gat.gat_table_form``); GCN modes never set it — their
        narrow-wire lever is ``halo_dtype``."""
        return "bfloat16" if self.gat_form == "packed" else None


def is_supported(mode: Mode) -> tuple[bool, str]:
    """(supported?, reason) — the construction-time gates of the trainers
    and the serve engine, restated.  The reason strings mirror the errors
    the constructors raise, so a drift shows up as a wording mismatch in
    review, not a silent matrix hole."""
    m = mode
    if m.workload not in ("train", "serve", "serve_subgraph", "minibatch"):
        return False, f"unknown workload {m.workload!r}"
    if m.model not in ("gcn", "gat"):
        return False, f"unknown model {m.model!r}"
    if m.schedule not in ("a2a", "ragged"):
        return False, f"unknown schedule {m.schedule!r}"
    if m.model == "gat":
        if m.staleness:
            return False, ("the GAT exchange ships per-layer attention "
                           "tables whose staleness is not supported")
        if m.halo_dtype is not None:
            return False, ("halo_dtype is a GCN lever; GAT narrows via its "
                           "table forms (compute_dtype)")
        if m.delta:
            return False, "halo_delta requires halo_staleness=1 (GCN only)"
        if m.replica:
            return False, ("the GAT exchange ships per-layer attention "
                           "tables whose replication is not supported")
        if m.gat_form not in GAT_FORMS:
            return False, f"unknown GAT table form {m.gat_form!r}"
    else:
        if m.gat_form is not None:
            return False, "gat_form is a GAT axis"
    if m.delta and not m.staleness:
        return False, "halo_delta accumulates into the stale halo carry"
    if m.replica and m.delta:
        return False, ("replica_budget composed with halo_delta is "
                       "deferred: the delta baseline and the replica "
                       "carry would disagree on what a stale step ships")
    if m.workload in ("serve", "serve_subgraph", "minibatch") and (
            m.staleness or m.delta or m.replica):
        return False, ("staleness/delta/replication are full-batch "
                       "TRAINING levers; serving always runs the exact "
                       "forward and the mini-batch trainer re-plans per "
                       "batch (replica carries have no stable identity "
                       "across batch plans)")
    if m.workload == "serve_subgraph" and m.schedule != "a2a":
        return False, ("the sub-graph serve program ships NO per-layer "
                       "exchange — its per-row fold is schedule-"
                       "independent by construction (the hedge family is "
                       "(dst, round, pos)-sorted), so the matrix audits "
                       "it once under the a2a-constructed engine")
    if m.workload == "serve_subgraph" and m.gat_form not in (None, "fused"):
        return False, ("the sub-graph engine is f32 (no compute_dtype "
                       "lever) and audits the compact table forms at the "
                       "plan's natural width — one GAT entry")
    if m.workload == "minibatch" and m.model == "gat":
        # supported by the trainer, but the audit covers the mini-batch
        # envelope once (GCN) — the GAT program is the same per-layer
        # structure already audited full-batch
        return False, "mini-batch audit entry covers the GCN envelope"
    if m.workload == "serve" and m.gat_form == "packed":
        return False, ("the serve engine has no compute_dtype lever — the "
                       "packed form is a training-side wire shape")
    if m.pallas:
        if m.workload != "train":
            return False, ("the Pallas kernel family is audited on the "
                           "train step programs; serving rides the "
                           "identical resolve_forward_setup branch (and "
                           "the sub-graph engine refuses it outright — "
                           "its compact mirror reproduces the ELL fold), "
                           "while the mini-batch envelope passes "
                           "allow_pallas=False (one compiled step, many "
                           "per-batch plans — no shared tile layout)")
        if m.staleness or m.delta or m.replica:
            return False, ("the stale/replica carry contracts are built "
                           "around the ELL + hedge fold; the Pallas "
                           "aggregator is an exact-mode lever")
        if m.gat_form == "packed":
            return False, ("the packed bf16 table bit-pairs lanes into "
                           "f32 words the kernel's f32 accumulate cannot "
                           "consume without an in-kernel unpack — "
                           "deferred (use_pallas_spmm gates it)")
    return True, "supported"


def supported_modes() -> list[Mode]:
    """Every supported configuration, audited by ``hlo_audit.run_audit``.

    Enumerates the FULL cross product per workload and filters through
    ``is_supported`` — so adding an axis value here automatically widens
    the audit, and a combination silently missing from the output is a
    bug in ``is_supported``, not in a hand-maintained list.
    """
    modes: list[Mode] = []
    # train / GCN: schedule × staleness × halo-dtype × delta × replica
    # (is_supported filters the deferred stale × replica composition)
    for sched, stale, hd, delta, rep in itertools.product(
            ("a2a", "ragged"), (0, 1), (None, "bfloat16"), (False, True),
            (False, True)):
        modes.append(Mode("train", "gcn", sched, stale, hd, delta,
                          replica=rep))
    # train / GCN / Pallas: schedule × halo-dtype at exact mode — the
    # schedule-agnostic VMEM kernel family (pspmm_pallas_sym/_ragged)
    for sched, hd in itertools.product(("a2a", "ragged"),
                                       (None, "bfloat16")):
        modes.append(Mode("train", "gcn", sched, halo_dtype=hd,
                          pallas=True))
    # train / GAT: schedule × table form (× the Pallas slot pass for the
    # f32 fused/split forms — is_supported filters packed+pallas)
    for sched, form, pal in itertools.product(("a2a", "ragged"), GAT_FORMS,
                                              (False, True)):
        modes.append(Mode("train", "gat", sched, gat_form=form,
                          pallas=pal))
    # serve: model × schedule (× halo-dtype for GCN, × form for GAT)
    for sched, hd in itertools.product(("a2a", "ragged"),
                                       (None, "bfloat16")):
        modes.append(Mode("serve", "gcn", sched, halo_dtype=hd))
    for sched in ("a2a", "ragged"):
        modes.append(Mode("serve", "gat", sched, gat_form="fused"))
    # sub-graph serving (docs/serving.md phase 2): the query-proportional
    # program — no per-layer exchange (schedule-independent fold, audited
    # once), GCN × wire-cast {f32, bf16} + the GAT compact table form
    for hd in (None, "bfloat16"):
        modes.append(Mode("serve_subgraph", "gcn", "a2a", halo_dtype=hd))
    modes.append(Mode("serve_subgraph", "gat", "a2a", gat_form="fused"))
    # the mini-batch shared-envelope program (one entry: the envelope padding
    # and forced ragged round sizes are what differ from full-batch)
    modes.append(Mode("minibatch", "gcn", "ragged"))
    return [m for m in modes if is_supported(m)[0]]


def fast_modes() -> list[Mode]:
    """The ``--fast`` subset: one exact mode, one composed mode — enough to
    smoke the whole lower-and-check pipeline in a couple of lowers."""
    return [
        Mode("train", "gcn", "a2a"),
        Mode("train", "gcn", "ragged", staleness=1,
             halo_dtype="bfloat16"),
    ]


def train_matrix_verdicts() -> dict:
    """The ``docs/comm_schedule.md`` composition-matrix rows (schedule ×
    staleness × delta × replicas × model) as enumerator verdicts — the
    machine-readable face of that table.  ``tests/test_analysis.py`` pins
    the two against each other."""
    out = {}
    for sched, stale, delta, rep, model in itertools.product(
            ("a2a", "ragged"), (0, 1), (False, True), (False, True),
            ("gcn", "gat")):
        mode = Mode("train", model, sched, stale, None, delta,
                    gat_form="fused" if model == "gat" else None,
                    replica=rep)
        ok, reason = is_supported(mode)
        out[(sched, stale, delta, rep, model)] = (ok, reason)
    return out
