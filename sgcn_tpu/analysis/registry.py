"""The ONE registry of ``CommPlan`` consumer contract tuples.

Every module-level ``*_FIELDS*`` tuple that names ``CommPlan`` fields for
shipping/slicing must be registered here — the plan-contract lint
(``tests/test_plan_contract.py``) validates each entry against the
dataclass and the shard proxy, and the AST hygiene pass
(``ast_rules.rule_consumer_registered``) fails the commit that introduces
a new ``*_FIELDS*`` tuple anywhere in the package without registering it.
Moved here from the test module so the test, the AST rule and any future
consumer read one registry (PR-9 consolidation; the entries themselves
are unchanged since their introducing PRs).

The registry proper is PURE DATA (name → defining module attribute) so
the AST pass never imports the SCANNED modules: resolving the tuple
VALUES imports the consumers (models/ops/serve — heavy, side-effectful),
and the AST rules must never be defeated by a scanned module's
import-time behavior.  (The ``sgcn_tpu`` package itself installs the
jaxlib compat shims at import — ``utils/compat.py`` — so a bare ``jax``
module import still occurs on any ``sgcn_tpu.*`` import; what the AST
pass avoids is backend work and the scanned modules' own import graphs.)
``resolve_consumer_tuples()`` does the imports for the consumers that
need values (the plan-contract lint).
"""

from __future__ import annotations

import importlib

# every tuple that names CommPlan fields for shipping/slicing, in one
# place: registered name → "defining.module:attribute" (pure strings — no
# imports at module load)
CONSUMER_TUPLE_SOURCES = {
    "PALLAS_PLAN_FIELDS": "sgcn_tpu.ops.pallas_spmm:PALLAS_PLAN_FIELDS",
    "PALLAS_PLAN_FIELDS_RAGGED":
        "sgcn_tpu.ops.pallas_spmm:PALLAS_PLAN_FIELDS_RAGGED",
    "GAT_PLAN_FIELDS_PALLAS":
        "sgcn_tpu.models.gat:GAT_PLAN_FIELDS_PALLAS",
    "GAT_PLAN_FIELDS_PALLAS_RAGGED":
        "sgcn_tpu.models.gat:GAT_PLAN_FIELDS_PALLAS_RAGGED",
    "GAT_PLAN_FIELDS": "sgcn_tpu.models.gat:GAT_PLAN_FIELDS",
    "GAT_PLAN_FIELDS_RAGGED":
        "sgcn_tpu.models.gat:GAT_PLAN_FIELDS_RAGGED",
    "GCN_PLAN_FIELDS_SYM": "sgcn_tpu.models.gcn:GCN_PLAN_FIELDS_SYM",
    "GCN_PLAN_FIELDS_GEN": "sgcn_tpu.models.gcn:GCN_PLAN_FIELDS_GEN",
    "GCN_PLAN_FIELDS_RAGGED":
        "sgcn_tpu.models.gcn:GCN_PLAN_FIELDS_RAGGED",
    "STALE_PLAN_FIELDS_RAGGED":
        "sgcn_tpu.parallel.plan:STALE_PLAN_FIELDS_RAGGED",
    "REPLICA_PLAN_FIELDS": "sgcn_tpu.parallel.plan:REPLICA_PLAN_FIELDS",
    "REPLICA_PLAN_FIELDS_RAGGED":
        "sgcn_tpu.parallel.plan:REPLICA_PLAN_FIELDS_RAGGED",
    "REPLICA_STALE_PLAN_FIELDS":
        "sgcn_tpu.parallel.plan:REPLICA_STALE_PLAN_FIELDS",
    "REPLICA_STALE_PLAN_FIELDS_RAGGED":
        "sgcn_tpu.parallel.plan:REPLICA_STALE_PLAN_FIELDS_RAGGED",
    "REPLICA_PARTIAL_PLAN_FIELDS":
        "sgcn_tpu.parallel.plan:REPLICA_PARTIAL_PLAN_FIELDS",
    "SERVE_ROUTER_FIELDS": "sgcn_tpu.serve.router:SERVE_ROUTER_FIELDS",
    "SERVE_SUBGRAPH_FIELDS":
        "sgcn_tpu.serve.subgraph:SERVE_SUBGRAPH_FIELDS",
}

# the two CLASSIFICATION tuples (parallel/plan.py) — not consumer tuples
# (they classify rather than ship), but legitimate *_FIELDS* names the AST
# rule must accept
CLASSIFICATION_TUPLES = ("PER_CHIP_ARRAY_FIELDS", "_GLOBAL_ARRAY_FIELDS")


def known_fields_names() -> frozenset:
    """Every ``*_FIELDS*`` name the AST rule accepts — names only, no
    consumer imports."""
    return (frozenset(CONSUMER_TUPLE_SOURCES)
            | frozenset(CLASSIFICATION_TUPLES))


def resolve_consumer_tuples() -> dict:
    """name → the live tuple, imported from its defining module — for
    consumers that validate VALUES (``tests/test_plan_contract.py``).
    Raises loudly if a registered name no longer exists (a stale registry
    entry is its own lint failure)."""
    out = {}
    for name, src in CONSUMER_TUPLE_SOURCES.items():
        mod, _, attr = src.partition(":")
        out[name] = getattr(importlib.import_module(mod), attr)
    return out
