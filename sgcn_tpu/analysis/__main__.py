"""Analysis CLI — ``python -m sgcn_tpu.analysis``.

Runs the AST hygiene pass and the compiled-program audit over the
supported mode matrix on a FORCED virtual 8-device CPU mesh (lowering
only — deterministic on any host, no accelerator needed), and emits the
JSON report.  ``--fast`` audits the 2-mode smoke subset (the CI smoke in
``tests/test_cli.py``); the full run is the one whose report is committed
as ``bench_artifacts/analysis_report.json`` and re-validated by
``scripts/validate_bench.py``.

Exit code 1 on any violation — wire this wherever a lint belongs.
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    p = argparse.ArgumentParser(
        description="sgcn_tpu static analysis: HLO contract audit + AST "
                    "hygiene")
    p.add_argument("--fast", action="store_true",
                   help="audit the 2-mode smoke subset instead of the "
                        "full matrix")
    p.add_argument("--json", action="store_true",
                   help="print the full report as ONE JSON line on stdout")
    p.add_argument("--out", default=None, metavar="FILE",
                   help="also write the report JSON to FILE")
    p.add_argument("--no-hlo", action="store_true",
                   help="skip the HLO audit (AST pass only; no jax)")
    p.add_argument("--no-ast", action="store_true",
                   help="skip the AST pass (HLO audit only)")
    p.add_argument("--memory", action="store_true",
                   help="also COMPILE every mode's programs and reconcile "
                        "XLA memory_analysis() against the analytic "
                        "footprint model (the memory-model rule; ~1 s per "
                        "program)")
    args = p.parse_args()

    if args.memory and args.no_hlo:
        p.error("--memory needs the jax mesh; drop --no-hlo")

    if not args.no_hlo:
        # the audit's programs are lowered against the virtual 8-chip mesh;
        # force it BEFORE jax initializes a backend (same mechanism as the
        # trainer CLI's `-b cpu`)
        from ..utils.backend import use_cpu_devices
        from .hlo_audit import AUDIT_K

        use_cpu_devices(AUDIT_K)

    from . import build_report

    report = build_report(fast=args.fast, hlo=not args.no_hlo,
                          ast_pass=not args.no_ast, memory=args.memory)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1, sort_keys=True)
            fh.write("\n")
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        _human(report)
    return 0 if report["ok"] else 1


def _human(report: dict) -> None:
    if "ast" in report:
        for name, entry in sorted(report["ast"]["rules"].items()):
            print(f"ast  {name:24s} "
                  f"{'ok' if entry['ok'] else 'FAIL'}")
            for v in entry["violations"]:
                print(f"     - {v}")
    if "hlo" in report:
        for mode_id, entry in sorted(report["hlo"]["modes"].items()):
            print(f"hlo  {mode_id:32s} "
                  f"{'ok' if entry['ok'] else 'FAIL'}")
            for label, prog in sorted(entry["programs"].items()):
                for v in prog["violations"]:
                    print(f"     - [{label}] {v['rule']}: {v['detail']}")
    if "memory" in report:
        for mode_id, entry in sorted(report["memory"]["modes"].items()):
            ratios = ", ".join(
                f"{label} {prog['ratio']:.2f}"
                for label, prog in sorted(entry["programs"].items())
                if prog.get("ratio") is not None)
            print(f"mem  {mode_id:32s} "
                  f"{'ok' if entry['ok'] else 'FAIL'}"
                  f"  model={entry['model_bytes']:,}B"
                  f"{'  peak/model: ' + ratios if ratios else ''}")
            for label, prog in sorted(entry["programs"].items()):
                for v in prog["violations"]:
                    print(f"     - [{label}] {v['rule']}: {v['detail']}")
    print(f"analysis: {'clean' if report['ok'] else 'VIOLATIONS'}")


if __name__ == "__main__":
    sys.exit(main())
