"""The AST hot-path hygiene pass (pass 2 of ``sgcn_tpu.analysis``).

A registry of repo-source rules run over the package (plus ``bench.py``)
with ``ast`` — no imports of the scanned modules, so a rule can never be
defeated by import-time side effects, and every rule function takes
``(relpath, src)`` so the tier-1 mutation checks can feed it a seeded
violation directly (``tests/test_analysis.py``).

Rules (see ``docs/static_analysis.md`` for the table):

  * ``traced-host-free`` — no ``time.*`` / ``np.random.*`` calls in the
    traced-code modules (``ops/``, ``models/``): a host clock or host RNG
    inside per-chip shard_map code either burns at trace time (silently
    constant-folded into the program — a frozen "random" number) or forces
    a host callback;
  * ``sanctioned-sync-only`` — no direct ``block_until_ready`` /
    ``device_get`` in the trainer/serve/op/model/obs/utils layers: every
    sync point goes through the ``sync=`` callables of ``PhaseTimer`` /
    ``SpanTimer`` (``utils/timers.py``, the one allowlisted home) so
    measured-time accounting cannot silently bypass the span machinery;
  * ``consumer-registered`` — every module-level ``*_FIELDS*`` tuple of
    strings is registered in ``registry.CONSUMER_TUPLE_SOURCES`` (or is one of
    the two classification tuples): an unregistered consumer tuple is a
    plan-shipping contract the plan-contract lint cannot see;
  * ``mode-flag-enumerated`` — every ``--comm-*`` / ``--halo-*`` flag any
    CLI defines maps to a mode-matrix axis (``modes.MODE_FLAGS``) or is a
    recorded non-axis (``modes.NON_AXIS_FLAGS``), and every axis flag
    exists on the trainer CLI: a new transport/wire knob cannot land
    outside the audited matrix.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

# modules whose function bodies are (almost entirely) traced per-chip code
TRACED_PREFIXES = ("sgcn_tpu/ops/", "sgcn_tpu/models/")
# layers where a raw sync call would bypass the span accounting (utils/
# included — that is what makes the allowlist below LIVE rather than
# documentation)
SYNC_SCOPED_PREFIXES = ("sgcn_tpu/train/", "sgcn_tpu/serve/",
                        "sgcn_tpu/ops/", "sgcn_tpu/models/",
                        "sgcn_tpu/obs/", "sgcn_tpu/utils/")
# the ONE sanctioned home of jax.block_until_ready (PhaseTimer's sync=
# hook — every other module in scope must route through it)
SYNC_ALLOWLIST = ("sgcn_tpu/utils/timers.py",)

# the CLIs whose mode-like flags must be enumerator-covered
MODE_FLAG_FILES = ("sgcn_tpu/train/__main__.py",
                   "sgcn_tpu/serve/__main__.py", "bench.py")
_MODE_LIKE_RE = re.compile(r"^--(comm|halo)-")

_FIELDS_NAME_RE = re.compile(r"^_?[A-Z][A-Z0-9_]*_FIELDS[A-Z0-9_]*$")

# PREFIX roots (the whole dotted name starts with these — bare "random."
# must not be a containment match or jax.random.* would false-positive)
_HOST_TIME_ROOTS = ("time.", "random.")
# CONTAINMENT roots (numpy's RNG namespace, wherever it is reached from)
_HOST_RNG_ROOTS = ("np.random.", "numpy.random.")
_SYNC_ATTRS = ("block_until_ready", "device_get")


def _dotted(node: ast.AST) -> str:
    """Dotted name of a call target anchored at a plain Name
    ('np.random.default_rng'); '' for chains rooted in a call/subscript —
    a method on a computed object is not a module-qualified call and must
    not resolve to a bare root like 'random.'."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    parts.append(node.id)
    return ".".join(reversed(parts))


@dataclass(frozen=True)
class Rule:
    name: str
    scope: str          # human-readable scope description (docs table)
    fn: object          # (relpath, src) -> list[str]

    def applies(self, relpath: str) -> bool:
        return _SCOPES[self.name](relpath)


def _import_aliases(tree: ast.AST) -> dict:
    """Local name → dotted origin for every import binding, so aliased
    spellings (``import time as t``, ``from numpy.random import
    default_rng``) resolve to the canonical dotted name before matching —
    the natural spellings of a violation must not slip the rule."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                else:
                    top = a.name.split(".")[0]
                    aliases[top] = top
        elif isinstance(node, ast.ImportFrom) and node.module \
                and not node.level:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def rule_traced_host_free(relpath: str, src: str) -> list[str]:
    tree = ast.parse(src)
    aliases = _import_aliases(tree)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if not name:
            continue
        head, _, rest = name.partition(".")
        resolved = aliases.get(head, head) + (f".{rest}" if rest else "")
        dn = resolved + "."
        if dn.startswith(_HOST_TIME_ROOTS) or any(
                r in dn for r in _HOST_RNG_ROOTS):
            out.append(f"{relpath}:{node.lineno}: call to {name}() "
                       f"(= {resolved}) in a traced-code module — host "
                       "clocks/RNG inside per-chip code freeze at trace "
                       "time or force a host callback; compute it offline "
                       "and pass it in")
    return out


def rule_sanctioned_sync_only(relpath: str, src: str) -> list[str]:
    out = []
    for node in ast.walk(ast.parse(src)):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        attr = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if attr in _SYNC_ATTRS:
            out.append(f"{relpath}:{node.lineno}: direct {attr}() — sync "
                       "points go through the sync= callables of "
                       "PhaseTimer/SpanTimer (utils/timers.py) so the "
                       "measured-time accounting sees them")
    return out


def rule_consumer_registered(relpath: str, src: str) -> list[str]:
    from .registry import known_fields_names

    known = known_fields_names()
    out = []
    tree = ast.parse(src)
    for node in tree.body:                      # module level only
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target,
                                                            ast.Name):
            targets = [node.target]
        for t in targets:
            if not _FIELDS_NAME_RE.match(t.id):
                continue
            val = node.value
            if not (isinstance(val, ast.Tuple) and val.elts and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in val.elts)):
                continue                        # not a field-name tuple
            if t.id not in known:
                out.append(
                    f"{relpath}:{node.lineno}: {t.id} is a *_FIELDS* "
                    "string tuple not registered in analysis/registry.py "
                    "CONSUMER_TUPLE_SOURCES — the plan-contract lint "
                    "cannot validate what it does not know about")
    return out


def rule_mode_flag_enumerated(sources: dict) -> list[str]:
    """Cross-file rule over ``MODE_FLAG_FILES``: takes ``{relpath: src}``."""
    from .modes import MODE_FLAGS, NON_AXIS_FLAGS

    out = []
    train_flags: set = set()
    for relpath, src in sources.items():
        for node in ast.walk(ast.parse(src)):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument" and node.args):
                continue
            for arg in node.args:
                if not (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    continue
                flag = arg.value
                if relpath.endswith("train/__main__.py"):
                    train_flags.add(flag)
                if _MODE_LIKE_RE.match(flag) and flag not in MODE_FLAGS \
                        and flag not in NON_AXIS_FLAGS:
                    out.append(
                        f"{relpath}:{node.lineno}: mode-like flag {flag} "
                        "is neither a mode-matrix axis (modes.MODE_FLAGS) "
                        "nor a recorded non-axis (modes.NON_AXIS_FLAGS) — "
                        "a transport/wire knob outside the audited matrix")
    if train_flags:
        for flag in MODE_FLAGS:
            if flag not in train_flags:
                out.append(
                    f"modes.MODE_FLAGS names {flag}, which the trainer CLI "
                    "does not define — a dead matrix axis")
    return out


_SCOPES = {
    "traced-host-free":
        lambda p: p.startswith(TRACED_PREFIXES),
    "sanctioned-sync-only":
        lambda p: (p.startswith(SYNC_SCOPED_PREFIXES)
                   and p not in SYNC_ALLOWLIST),
    "consumer-registered":
        lambda p: p.startswith("sgcn_tpu/"),
    "mode-flag-enumerated":
        lambda p: p in MODE_FLAG_FILES,
}

RULES = (
    Rule("traced-host-free", "sgcn_tpu/{ops,models}/",
         rule_traced_host_free),
    Rule("sanctioned-sync-only",
         "sgcn_tpu/{train,serve,ops,models,obs,utils}/ minus "
         "utils/timers.py",
         rule_sanctioned_sync_only),
    Rule("consumer-registered", "sgcn_tpu/**", rule_consumer_registered),
    Rule("mode-flag-enumerated",
         "train/serve CLIs + bench.py (cross-file)",
         rule_mode_flag_enumerated),
)


def _iter_sources(root: str):
    pkg = os.path.join(root, "sgcn_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for name in sorted(files):
            if name.endswith(".py"):
                full = os.path.join(dirpath, name)
                yield os.path.relpath(full, root).replace(os.sep, "/"), full
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        yield "bench.py", bench


def run_ast_pass(root: str | None = None) -> dict:
    """Run every rule over the repo; returns the ``ast`` block of the
    analysis report: ``{rules: {name: {ok, violations}}, ok}``."""
    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    per_file_rules = [r for r in RULES if r.name != "mode-flag-enumerated"]
    results = {r.name: [] for r in RULES}
    mode_sources: dict = {}
    for relpath, full in _iter_sources(root):
        with open(full) as fh:
            src = fh.read()
        for r in per_file_rules:
            if r.applies(relpath):
                results[r.name] += r.fn(relpath, src)
        if relpath in MODE_FLAG_FILES:
            mode_sources[relpath] = src
    results["mode-flag-enumerated"] = rule_mode_flag_enumerated(mode_sources)
    return {
        "rules": {name: {"ok": not v, "violations": v}
                  for name, v in results.items()},
        "ok": all(not v for v in results.values()),
    }
