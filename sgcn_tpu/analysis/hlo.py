"""Shared HLO/StableHLO text parsing — ONE implementation for every
compiled-program check in the repo.

Two dialects, two consumers:

  * **StableHLO MLIR** (``jax.jit(...).lower(...).as_text()``): what the
    mode-matrix auditor (``hlo_audit``) reads — collective ops with operand
    dtypes/shapes, main-function argument donation attributes
    (``jax.buffer_donor``), custom-call targets.  Ops may span many lines
    (``all_reduce`` carries a reduction region), so extraction scans from
    the op head to its ``: (operand types) -> result types`` signature.
  * **scheduled HLO** (``lowered.compile().as_text()`` on a real backend):
    what the overlap evidence test reads — async collective
    ``-start``/``-done`` pairs and the compute scheduled inside each
    window (``tests/test_overlap_hlo.py``).

Nothing here imports jax: parsing is pure text, so the AST/CLI paths can
load it without touching a backend.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# StableHLO ops the audit inventories.  ``send``/``recv``/``infeed``/
# ``outfeed`` are collected so their PRESENCE can be flagged (a step
# program must never carry host-transfer ops).
COLLECTIVE_KINDS = ("all_to_all", "all_reduce", "collective_permute",
                    "all_gather", "reduce_scatter")
HOST_TRANSFER_KINDS = ("infeed", "outfeed", "send", "recv")

_OP_HEAD_RE = re.compile(
    r'"?stablehlo\.(' + "|".join(COLLECTIVE_KINDS + HOST_TRANSFER_KINDS)
    + r')"?\b')

# the plumbing custom-call targets SPMD partitioning itself emits — always
# legitimate inside a step program
BENIGN_CUSTOM_CALLS = frozenset({
    "Sharding", "SPMDFullToShardShape", "SPMDShardToFullShape",
})

# targets that smuggle a HOST round-trip into the program: the python
# callback family (jax.debug.print / debug.callback / pure_callback /
# io_callback lower to these) — one of them inside a step program turns a
# device-rate hot loop into a host-rate one
HOST_CALLBACK_RE = re.compile(
    r"(callback|CallbackTo|host_callback)", re.IGNORECASE)

_TYPE_SIG_RE = re.compile(r':\s*\(([^()]*)\)\s*->\s*(.+?)\s*$')
_TENSOR_RE = re.compile(r'tensor<([^>]*)>')
_CUSTOM_TARGET_RE = re.compile(r'stablehlo\.custom_call\s+@(\w+)')
_REDUCER_RE = re.compile(
    r'stablehlo\.(add|maximum|minimum|multiply|and|or|xor)\b')


def parse_tensor_type(t: str) -> tuple[tuple[int, ...], str]:
    """``'8x10x8xbf16'`` → ``((8, 10, 8), 'bf16')``; ``'f32'`` → ``((), 'f32')``."""
    parts = t.strip().split("x")
    dims, i = [], 0
    while i < len(parts) and parts[i].isdigit():
        dims.append(int(parts[i]))
        i += 1
    return tuple(dims), "x".join(parts[i:])


@dataclass
class HloOp:
    """One inventoried StableHLO op."""

    kind: str                      # 'all_to_all', 'all_reduce', ...
    line: int                      # 0-based line of the op head
    operand_types: list = field(default_factory=list)   # [(shape, dtype)]
    result_types: list = field(default_factory=list)
    reducer: str | None = None     # all_reduce region body ('add', 'maximum')
    text: str = ""                 # joined op text (head → type signature)

    @property
    def wire(self) -> tuple:
        """(shape, dtype) of the first operand — the wire buffer of a
        collective dispatch."""
        return self.operand_types[0] if self.operand_types else ((), "?")


def _scan_op_signature(lines, i: int, max_span: int):
    """Scan from an op head at line ``i`` to the ``: (…) -> …`` type
    signature that closes it (ops with regions — ``all_reduce``,
    ``scatter`` — span many lines; region-body ops print bare
    ``: tensor<…>`` forms that never match the parenthesized signature).
    THE one extraction shared by every op inventory here; returns
    ``(sig_match_or_None, joined_text)``."""
    joined = []
    sig = None
    for j in range(i, min(i + max_span, len(lines))):
        joined.append(lines[j])
        sig = _TYPE_SIG_RE.search(lines[j])
        if sig:
            break
    return sig, "\n".join(joined)


def collective_ops(text: str, max_span: int = 400) -> list[HloOp]:
    """Inventory every collective / host-transfer StableHLO op in a lowered
    module.  Ops with regions (``all_reduce``) span lines; the op's operand
    and result types are read from the ``: (…) -> …`` signature that closes
    it, and the reduction body (``stablehlo.add`` / ``maximum`` …) is
    captured for reduce classification."""
    lines = text.splitlines()
    ops: list[HloOp] = []
    for i, ln in enumerate(lines):
        m = _OP_HEAD_RE.search(ln)
        if not m:
            continue
        kind = m.group(1)
        sig, joined = _scan_op_signature(lines, i, max_span)
        op = HloOp(kind=kind, line=i, text=joined)
        if sig:
            op.operand_types = [parse_tensor_type(t)
                                for t in _TENSOR_RE.findall(sig.group(1))]
            op.result_types = [parse_tensor_type(t)
                               for t in _TENSOR_RE.findall(sig.group(2))]
        if kind == "all_reduce":
            r = _REDUCER_RE.search(op.text)
            op.reducer = r.group(1) if r else None
        ops.append(op)
    return ops


def custom_call_targets(text: str) -> list[str]:
    """Every ``stablehlo.custom_call @Target`` in the module, in order."""
    return _CUSTOM_TARGET_RE.findall(text)


_SCATTER_HEAD_RE = re.compile(r'"?stablehlo\.scatter"?\b')


def scatter_result_types(text: str, max_span: int = 400) -> list[tuple]:
    """Result ``(shape, dtype)`` of every ``stablehlo.scatter`` op in the
    module — the halo-materialization rule of the ragged-Pallas audit
    (``expect.Expectation.forbidden_scatters``): a program that assembles
    the ``(R, f)`` halo table before the kernel betrays itself as a
    scatter with exactly that result signature.  Scatter ops carry an
    update-computation region, so extraction rides the shared
    ``_scan_op_signature`` scan ``collective_ops`` uses."""
    lines = text.splitlines()
    out: list[tuple] = []
    for i, ln in enumerate(lines):
        if not _SCATTER_HEAD_RE.search(ln):
            continue
        sig, _joined = _scan_op_signature(lines, i, max_span)
        if sig:
            out += [parse_tensor_type(t)
                    for t in _TENSOR_RE.findall(sig.group(2))]
    return out


def host_callback_targets(text: str) -> list[str]:
    """The custom-call targets that smuggle a host round-trip into the
    program (python-callback family), plus any ``@Target`` outside the
    benign SPMD-plumbing set that LOOKS like a callback."""
    return [t for t in custom_call_targets(text)
            if t not in BENIGN_CUSTOM_CALLS and HOST_CALLBACK_RE.search(t)]


def unknown_custom_calls(text: str) -> list[str]:
    """Custom-call targets that are neither SPMD plumbing nor recognized
    callbacks — surfaced so a NEW target class is a loud audit finding
    (e.g. a Pallas ``tpu_custom_call`` showing up in a mode that pins the
    ELL aggregator), never a silent pass."""
    return [t for t in custom_call_targets(text)
            if t not in BENIGN_CUSTOM_CALLS
            and not HOST_CALLBACK_RE.search(t)]


# --------------------------------------------------------------- main() args
@dataclass
class FuncArg:
    index: int
    type: tuple                    # (shape, dtype)
    donated: bool
    attrs: str


_MAIN_RE = re.compile(r'func\.func\s+public\s+@main\((.*?)\)\s*->', re.S)
_ARG_SPLIT_RE = re.compile(r'%arg(\d+):\s*tensor<([^>]*)>')


def main_args(text: str) -> list[FuncArg]:
    """The main function's arguments with their ``jax.buffer_donor``
    donation markers — the lowering-time form of ``donate_argnums``.  Each
    argument's attribute span runs to the next ``%arg`` head (attribute
    dicts may nest braces inside quoted sharding strings, so spans — not
    brace matching — delimit them)."""
    m = _MAIN_RE.search(text)
    if not m:
        raise ValueError("no public @main function in module text")
    body = m.group(1)
    heads = list(_ARG_SPLIT_RE.finditer(body))
    out = []
    for i, h in enumerate(heads):
        end = heads[i + 1].start() if i + 1 < len(heads) else len(body)
        attrs = body[h.end(): end]
        out.append(FuncArg(index=int(h.group(1)),
                           type=parse_tensor_type(h.group(2)),
                           donated="jax.buffer_donor" in attrs,
                           attrs=attrs.strip()))
    return out


# ----------------------------------------------------- scheduled-HLO (async)
def count_async_starts(text: str, kind: str = "all-to-all") -> int:
    """Number of ``%<kind>-start`` values in a scheduled HLO module — zero
    when the program was not compiled with the async-collective flags."""
    return len(re.findall(rf"^\s*%{kind}-start[\w.\-]* = ", text,
                          flags=re.M))


def async_windows(text: str, kind: str = "all-to-all",
                  body_pattern: str = r"fusion\(") -> list[int]:
    """Pair each async ``%<kind>-start`` with ITS ``-done`` via the SSA
    value name in a scheduled HLO module and count ``body_pattern`` matches
    strictly inside each start→done window — the compiled-schedule form of
    "real compute runs while the collective is in flight".

    Raises ``ValueError`` on a ``-done`` consuming an unknown start or any
    start left unmatched (a malformed schedule must fail the caller, not
    read as zero overlap)."""
    lines = text.splitlines()
    starts: dict[str, int] = {}
    for i, ln in enumerate(lines):
        m = re.match(rf"\s*(%{kind}-start[\w.\-]*) = ", ln)
        if m:
            starts[m.group(1)] = i
    windows: list[int] = []
    body_re = re.compile(body_pattern)
    for i, ln in enumerate(lines):
        m = re.search(rf"{kind}-done[\w.\-]*\(([^)]*)\)", ln)
        if not m:
            continue
        src = m.group(1).split(",")[0].strip()
        if src not in starts:
            raise ValueError(f"{kind}-done consumes unknown start {src!r}")
        s = starts.pop(src)
        windows.append(sum(bool(body_re.search(x)) for x in lines[s + 1: i]))
    if starts:
        raise ValueError(f"unmatched {kind}-start(s): {sorted(starts)}")
    return windows
