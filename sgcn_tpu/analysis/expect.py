"""Plan-derived expectations for the compiled-program audit.

Everything the HLO auditor asserts is computed HERE, from the same plan
fields and shared helpers the real programs are built from — never from a
golden dump of a previous lowering:

  * exchange collectives ride ``CommPlan.wire_buffer_shapes`` (the
    ``(peers, S)`` dense pad / per-live-round ``(S_d,)`` ring buffers,
    empty rounds elided per ``ops.pspmm.ragged_live_rounds``) crossed with
    the model's lane widths (``models.gcn.exchange_widths`` /
    ``models.gat.gat_table_form``);
  * the gradient allreduce census is the trainer's own parameter pytree —
    one full-mesh ``psum`` per leaf;
  * donation expectations are the trainer's argument pytrees classified
    donate/keep exactly as ``donate_argnums`` classifies them.

One constant is pinned empirically rather than derived:
``XENT_SCALAR_PSUMS`` — the scalar f32 allreduces the masked-xent loss
machinery lowers to (two ``lax.psum`` calls in
``models.gcn.masked_softmax_xent_local`` plus one re-emitted on the
linearized path by JAX's partial evaluation).  It is a property of the
loss code + JAX version, not of the plan; the full-matrix audit at HEAD
validates it for every mode, and a loss-code change that shifts it fails
the audit loudly (the point of a lint).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

# scalar f32 add-allreduces of one masked-xent train step (see module
# docstring); every audited train program uses the xent loss
XENT_SCALAR_PSUMS = 3

_DTYPE_SHORT = {
    "float32": "f32", "bfloat16": "bf16", "float64": "f64", "float16":
    "f16", "int8": "i8", "int16": "i16", "int32": "i32", "int64": "i64",
    "uint8": "ui8", "uint32": "ui32", "bool": "i1",
}


def dtype_short(dt) -> str:
    return _DTYPE_SHORT.get(np.dtype(dt).name if not isinstance(dt, str)
                            else dt, str(dt))


@dataclass
class Expectation:
    """What one lowered program must contain."""

    # exchange collectives: multiset of (kind, wire shape, wire dtype)
    exchanges: list = field(default_factory=list)
    # grad-sync allreduces: multiset of operand shapes (add-reduce, f32)
    grad_shapes: list = field(default_factory=list)
    # scalar f32 add-allreduces (loss machinery)
    scalar_psums: int = 0
    # max-allreduces (the GAT per-layer softmax stabilizer pmax): count
    max_psums: int = 0
    # serve logit gather: list of (shape,) add-allreduce operands
    gather_shapes: list = field(default_factory=list)
    # argument classification for the donation check, in flatten order:
    # list of (shape, dtype, klass) with klass in {'donate', 'keep'}
    args: list = field(default_factory=list)
    # result SHAPES no scatter op in the program may produce — the
    # halo-materialization rule of the ragged-Pallas modes: assembling the
    # (R, f_ℓ) halo table before the kernel (instead of feeding the ring's
    # receive concat to the VMEM tile accumulator directly) betrays itself
    # as a scatter with exactly that signature.  Shapes that collide with
    # the program's LEGITIMATE scatters (the emulate-mode segment-sums'
    # per-class (T_c·tb, f) blocks, the (B, f) folds) are dropped at
    # expectation-build time, never silently matched.
    forbidden_scatters: list = field(default_factory=list)


def _gcn_layer_plan(fin: int, widths) -> tuple[list, list]:
    """(per-layer exchanged lane widths, per-layer project-first flags) —
    the lane widths are ``models.gcn.exchange_widths`` verbatim; the flags
    re-state its condition so the backward-exchange census below can apply
    the layer-0 dead-code rule."""
    from ..models.gcn import PROJECT_FIRST_MIN_FIN, exchange_widths

    fs = exchange_widths(fin, list(widths))
    pf, f = [], fin
    for w in widths:
        pf.append(bool(w < f and f >= PROJECT_FIRST_MIN_FIN))
        f = w
    return fs, pf


def _exchange_ops(plan, schedule: str, lane: int | None, dtype: str,
                  replica: bool = False) -> list:
    """The collective dispatches of ONE halo exchange shipping ``lane``
    trailing lanes (``None`` = no lane axis, e.g. the GAT split scalar).
    ``replica=True``: the SHRUNKEN no-replica exchange of a
    ``--replica-budget`` step (``CommPlan.wire_buffer_shapes(replica=True)``
    — the ``nrep_s`` pad / live rounds of ``nrep_rr_sizes``)."""
    kind = "all_to_all" if schedule == "a2a" else "collective_permute"
    out = []
    for shape in plan.wire_buffer_shapes(schedule, replica=replica):
        full = shape if lane is None else shape + (lane,)
        out.append((kind, full, dtype))
    return out


def _wire_dtypes_gcn(mode, fresh: bool) -> tuple[str, str]:
    """(feature wire, gradient wire) dtypes of one GCN step — the
    ``halo_dtype`` / ``--halo-delta`` / f32-rebase rules of
    ``ops.pspmm._stale_exchange`` and ``halo_exchange``."""
    base = "bf16" if mode.halo_dtype == "bfloat16" else "f32"
    if not mode.staleness:
        return base, base
    if mode.delta:
        # stale steps ship the bf16 increment; a fresh step RE-BASES on the
        # full f32 row (both ends reset exactly — docs/stale_halo.md)
        return ("f32" if fresh else "bf16"), base
    return base, base


def pallas_ragged_forbidden_scatters(trainer, mode) -> list:
    """The ragged-Pallas halo-materialization rule's forbidden scatter
    result shapes: ``(R, f_ℓ)`` at every lane width the mode's exchanges
    ship (GCN: ``exchange_widths``; GAT: the fused ``fout+1`` and split
    ``fout`` table heights).  Shapes colliding with the program's
    legitimate scatter outputs — the per-class ``(T_c·tb, f)`` blocks of
    the emulate-mode segment-sums and the ``(B, f)`` folds — are dropped
    (a collision would turn the lint vacuous OR false-positive; dropping
    is the conservative side and the audit fixture does not collide)."""
    if not getattr(mode, "pallas", False) or mode.schedule != "ragged":
        return []
    plan = trainer.plan
    legit = {int(plan.b)}
    for cls, tb in ((plan.pallas_lclasses, plan.pallas_tb),
                    (plan.pallas_hclasses, plan.pallas_tb),
                    (plan.pallas_cclasses, plan.pallas_ctb)):
        if cls and tb:
            legit |= {int(t) * int(tb) for t, _e in cls}
    if int(plan.r) in legit:
        return []
    if mode.model == "gcn":
        fs, _ = _gcn_layer_plan(trainer.fin, trainer.widths)
        lanes = set(int(f) for f in fs)
    else:
        lanes = set()
        for fout in trainer.widths:
            lanes |= {int(fout), int(fout) + 1}
    return [(int(plan.r), lane) for lane in sorted(lanes)]


def train_expectation(trainer, mode, fresh: bool = False) -> Expectation:
    """Expected contents of one lowered train step for ``mode``.

    ``fresh`` selects the stale mode's full-sync program (both programs of
    a stale mode are audited — the f32 delta re-base is a sync-step-only
    contract)."""
    import jax

    plan = trainer.plan
    exp = Expectation()
    L = trainer.nlayers

    if mode.model == "gcn":
        fs, pf = _gcn_layer_plan(trainer.fin, trainer.widths)
        fdt, gdt = _wire_dtypes_gcn(mode, fresh)
        # replica REPLICA step (fresh=False): both directions ship the
        # SHRUNKEN nrep layout; the refresh (fresh=True) step ships the
        # full exact exchange
        rep_wire = bool(mode.replica) and not fresh
        for i in range(L):                       # forward: every layer
            exp.exchanges += _exchange_ops(plan, mode.schedule, fs[i], fdt,
                                           replica=rep_wire)
        if mode.staleness or (mode.replica and fresh):
            # backward: the fresh gradient exchange is EMITTED for every
            # layer — it is next step's carry (stale mode) / the refreshed
            # gradient-replica table (replica refresh step), so layer 0's
            # survives even though dL/dh0 is dead
            bwd_layers = range(L)
        else:
            # exact mode (and the replica step, whose grep cotangent is a
            # pass-through): layer 0's backward exchange exists only under
            # project-first (dL/d(h·W) feeds dW); aggregate-first layer 0
            # only needs dL/dagg-out, and its dL/dh0 path is dead code
            bwd_layers = [i for i in range(L) if i > 0 or pf[0]]
        for i in bwd_layers:
            exp.exchanges += _exchange_ops(plan, mode.schedule, fs[i], gdt,
                                           replica=rep_wire)
    else:
        from ..models.gat import gat_table_form
        for i in range(L):
            fout = trainer.widths[i]
            form = gat_table_form(fout, mode.compute_dtype)
            for _direction in ("fwd", "bwd"):    # both ride the same form
                if form == "packed":
                    exp.exchanges += _exchange_ops(
                        plan, mode.schedule, fout // 2 + 1, "f32")
                elif form == "fused":
                    exp.exchanges += _exchange_ops(
                        plan, mode.schedule, fout + 1, "f32")
                elif mode.schedule == "a2a":
                    # split pair: feature table + its own scalar buffer —
                    # TWO dense dispatches per exchange
                    exp.exchanges += _exchange_ops(plan, "a2a", fout, "f32")
                    exp.exchanges += _exchange_ops(plan, "a2a", None, "f32")
                else:
                    # on the ring the pair collapses into ONE two-lane
                    # dispatch per live round (halo_exchange_ragged_multi)
                    exp.exchanges += _exchange_ops(
                        plan, "ragged", fout + 1, "f32")
        exp.max_psums = L                        # per-layer softmax pmax

    exp.grad_shapes = [tuple(np.shape(x))
                       for x in jax.tree.leaves(trainer.params)]
    exp.scalar_psums = XENT_SCALAR_PSUMS
    exp.forbidden_scatters = pallas_ragged_forbidden_scatters(trainer, mode)

    # argument classification (donation): the jit args in flatten order
    groups = [("donate", trainer.params), ("donate", trainer.opt_state)]
    if mode.staleness:
        # the composed replica × stale mode carries NO replica state of
        # its own — the stale halo carry subsumes it, so the carry pytree
        # is exactly the stale mode's
        groups.append(("donate", trainer.halo_carry))
    elif mode.replica:
        groups.append(("donate", trainer.replica_carry))
    groups += [("keep", trainer.pa)]
    exp.args = _classify_args(groups)
    k, b = plan.k, plan.b
    exp.args += [((k, b, trainer.fin), "f32", "keep"),   # h0
                 ((k, b), "i32", "keep"),                # labels
                 ((k, b), "f32", "keep")]                # valid
    return exp


def serve_expectation(engine, mode, bucket: int) -> Expectation:
    """Expected contents of one lowered serve bucket program: L forward
    exchanges, ONE full-mesh logit-gather psum, and NO donated inputs
    (engine params/plan arrays are reused across micro-batches)."""
    import jax

    plan = engine.plan
    exp = Expectation()
    L = engine.nlayers
    if mode.model == "gcn":
        fs, _ = _gcn_layer_plan(engine.fin, engine.widths)
        dt = "bf16" if mode.halo_dtype == "bfloat16" else "f32"
        for i in range(L):
            exp.exchanges += _exchange_ops(plan, mode.schedule, fs[i], dt)
    else:
        from ..models.gat import gat_table_form
        for i in range(L):
            fout = engine.widths[i]
            form = gat_table_form(fout, None)
            if form == "fused":
                exp.exchanges += _exchange_ops(
                    plan, mode.schedule, fout + 1, "f32")
            elif mode.schedule == "a2a":
                exp.exchanges += _exchange_ops(plan, "a2a", fout, "f32")
                exp.exchanges += _exchange_ops(plan, "a2a", None, "f32")
            else:
                exp.exchanges += _exchange_ops(
                    plan, "ragged", fout + 1, "f32")
        exp.max_psums = L
    exp.gather_shapes = [(bucket, engine.widths[-1])]
    groups = [("keep", engine.params), ("keep", engine.pa)]
    exp.args = _classify_args(groups)
    k, b = plan.k, plan.b
    exp.args += [((k, b, engine.fin), "f32", "keep"),    # h0
                 ((bucket,), "i32", "keep"),             # q_owner
                 ((bucket,), "i32", "keep")]             # q_local
    return exp


def serve_subgraph_expectation(engine, mode, key: tuple) -> Expectation:
    """Expected contents of one lowered SUB-GRAPH serve program
    (``ServeEngine.lower_subgraph``) — the tentpole contract: NO exchange
    collectives at all (every source row is computed locally from
    host-gathered receptive-set features), no pmax (the GAT stabilizers
    arrive as an input), no scalar psums (no loss machinery), exactly ONE
    full-mesh logit-gather psum, and nothing donated (params and batch
    arrays are reused / engine-owned)."""
    from ..serve.subgraph import batch_struct

    exp = Expectation()
    qb = key[1]
    exp.gather_shapes = [(qb, engine.widths[-1])]
    groups = [("keep", engine.params),
              ("keep", np.zeros((engine.nlayers,), np.float32)),  # cgs
              ("keep", batch_struct(engine.sgindex, key, engine.fin))]
    exp.args = _classify_args(groups)
    exp.args += [((qb,), "i32", "keep"),                 # q_owner
                 ((qb,), "i32", "keep")]                 # q_pos
    return exp


def _classify_args(groups) -> list:
    import jax

    out = []
    for klass, tree in groups:
        for leaf in jax.tree.leaves(tree):
            out.append((tuple(np.shape(leaf)),
                        dtype_short(np.asarray(leaf).dtype
                                    if not hasattr(leaf, "dtype")
                                    else leaf.dtype), klass))
    return out
