"""Baseline CLIs — the reference's two comparison executables.

  * ``python -m sgcn_tpu.baselines oracle -a A.mtx -f H.mtx -y Y.mtx -c config``
    — the DGL single-process GCN role (``DGL/gcn.py``; reference flags
    ``-a -h -y -c``, ``README.md:150-166`` — its ``-h`` is spelled ``-f``
    here so argparse help stays usable): dense single-device training on the
    preprocessor outputs, sigmoid between layers, SGD+momentum, per-epoch
    loss + process time.
  * ``python -m sgcn_tpu.baselines cagnet -a A.mtx -c config -s k``
    — the CAGNET 1D-broadcast inference baseline role (``Cagnet/main.c``,
    ``README.md:168-183``): uniform block row distribution, k-step
    all-gather layer, inference only, phase-time breakdown
    (``Cagnet/main.c:35-38,395-413``).

Backend selection: ``-b cpu`` (default) forces host CPU devices via
``sgcn_tpu.utils.backend.use_cpu_devices`` — the platform choice is applied
with ``jax.config.update`` because running under ``-m`` executes the package
``__init__`` (which imports jax) before this file's body; backend init is
lazy, so the update still lands first.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _add_common(p):
    p.add_argument("-a", "--adjacency", required=True,
                   help="path to <name>.A.mtx (normalized adjacency)")
    p.add_argument("-c", "--config", default=None,
                   help="config sidecar 'nlayers nvtx f1 ... nout'; widths "
                        "default to it when present")
    p.add_argument("-f", "--features-mtx", default=None,
                   help="path to <name>.H.mtx (the reference DGL CLI's -h). "
                        "Without it, synthetic all-ones features are used "
                        "at a GUESSED input width: the config sidecar "
                        "'nlayers nvtx f1 ... nout' does not record fin, so "
                        "-c alone defaults the input width to f1 (the first "
                        "HIDDEN width) — pass -f whenever comparing against "
                        "a pipeline whose H.mtx has a different input width")
    p.add_argument("--epochs", type=int, default=5)
    p.add_argument("--seed", type=int, default=0)


def main() -> None:
    p = argparse.ArgumentParser(description="sgcn_tpu comparison baselines")
    sub = p.add_subparsers(dest="cmd", required=True)

    po = sub.add_parser("oracle", help="DGL/gcn.py role: dense single-device "
                                       "GCN on preprocessor outputs")
    _add_common(po)
    po.add_argument("-y", "--labels-mtx", default=None,
                    help="path to <name>.Y.mtx (one-hot labels)")
    po.add_argument("--lr", type=float, default=0.01)
    po.add_argument("-b", "--backend", default="cpu", choices=["jax", "cpu"],
                    help="cpu (default) = host CPU, the single-process "
                         "DGL-baseline deployment; jax = platform devices")

    pc = sub.add_parser("cagnet", help="Cagnet/main.c role: 1D-broadcast "
                                       "inference with phase breakdown "
                                       "(inference-only: no lr)")
    _add_common(pc)
    pc.add_argument("-s", "--nparts", type=int, required=True)
    pc.add_argument("-b", "--backend", default="cpu", choices=["jax", "cpu"])

    args = p.parse_args()
    if args.epochs < 1:
        raise SystemExit("--epochs must be >= 1")

    if args.backend == "cpu":
        from ..utils.backend import use_cpu_devices
        use_cpu_devices(getattr(args, "nparts", 1))

    import numpy as np

    from ..io.config import read_config
    from ..io.mtx import read_dense_features, read_mtx, read_onehot_labels

    a = read_mtx(args.adjacency)
    n = a.shape[0]
    cfg = read_config(args.config) if args.config else None

    if args.features_mtx:
        feats = read_dense_features(args.features_mtx)
    else:
        feats = np.ones((n, cfg.widths[0] if cfg else 16), np.float32)
    fin = feats.shape[1]
    widths = list(cfg.widths) if cfg else [fin, 2]

    if args.cmd == "oracle":
        from .oracle import DenseOracle
        import optax
        if args.labels_mtx:
            labels = read_onehot_labels(args.labels_mtx)
        else:
            labels = (np.arange(n) % widths[-1]).astype(np.int32)
        # DGL/gcn.py: sigmoid between layers, cross-entropy, SGD momentum,
        # 5 epochs timed with time.process_time (DGL/gcn.py:74-97)
        oracle = DenseOracle(a, fin=fin, widths=widths, activation="sigmoid",
                             optimizer=optax.sgd(args.lr, momentum=0.9),
                             seed=args.seed)
        t0 = time.process_time()
        losses = oracle.fit(feats, labels, epochs=args.epochs)
        for e, l in enumerate(losses):
            print(f"epoch {e}: loss {l:.6f}", file=sys.stderr, flush=True)
        print(json.dumps({
            "baseline": "oracle",
            "epochs": args.epochs,
            "process_time_s": time.process_time() - t0,
            "final_loss": losses[-1],
        }), flush=True)
        return

    from .cagnet1d import BroadcastGCN1D
    k = args.nparts
    # CAGNET's uniform block row distribution (Cagnet/main.c: contiguous
    # equal blocks; no partitioner)
    partvec = np.repeat(np.arange(k), -(-n // k))[:n]
    bc = BroadcastGCN1D(a, partvec, k, fin=fin, widths=widths,
                        seed=args.seed)
    report, _ = bc.run_epochs(feats, epochs=args.epochs)
    report["baseline"] = "cagnet1d"
    report["backend"] = args.backend
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
