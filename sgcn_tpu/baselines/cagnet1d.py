"""CAGNET-style uniform 1D broadcast baseline (comparison, inference-only).

Reference: ``Cagnet/main.c`` — the baseline the paper's partitioned algorithm
is measured against.  Per layer, every rank round-robin ``MPI_Bcast``s its
whole H block and all ranks accumulate ``A_local · H_bcast``
(``Cagnet/main.c:158-208``); forward-only, 5 epochs, sigmoid activations
(``:204-207``), with a phase-time breakdown (data-comm / local-SpMM /
update, ``:35-38,148-151,171-175,395-413``).

TPU-native form: the k-round broadcast ring collapses into ONE
``lax.all_gather`` of the local block per layer — every chip then holds the
full (k·B, f) feature table and runs its local SpMM against it.  Unlike the
partitioned path there is no boundary selection: the whole feature matrix
crosses the interconnect every layer regardless of the partition quality,
which is exactly the inefficiency the paper's halo exchange removes (and what
makes this a meaningful comparison baseline).

For the phase breakdown the comm (all_gather) and compute (SpMM + dense) are
compiled as separate programs with a host sync between — slightly slower than
the fused single program, but it reports the comm/compute split the reference
baseline instruments; ``fused=True`` gives the single-program variant for
best-case timing.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.activations import get_activation
from ..models.gcn import init_gcn_params
from ..parallel.mesh import AXIS, make_mesh_1d, replicate, shard_stacked
from ..parallel.plan import CommPlan, relabel_plan
from ..utils.timers import PhaseTimer


def broadcast_edge_lists(a, plan: CommPlan):
    """Per-chip edge lists whose src indexes the all-gathered (k·B, f) table.

    Same local rows as the plan, but src = owner·B + local_idx (global-table
    slot) instead of the [local; halo] compaction.
    """
    import scipy.sparse as sp

    a = sp.coo_matrix(a)
    k, b = plan.k, plan.b
    eo = plan.owner[a.row]
    e = plan.e
    edge_dst = np.full((k, e), b - 1, dtype=np.int32)
    edge_src = np.zeros((k, e), dtype=np.int32)
    edge_w = np.zeros((k, e), dtype=np.float32)
    for p in range(k):
        em = eo == p
        rows = plan.local_idx[a.row[em]].astype(np.int32)
        cols = a.col[em]
        gsrc = (plan.owner[cols] * b + plan.local_idx[cols]).astype(np.int32)
        vals = a.data[em].astype(np.float32)
        srt = np.argsort(rows, kind="stable")
        cnt = int(em.sum())
        edge_dst[p, :cnt] = rows[srt]
        edge_src[p, :cnt] = gsrc[srt]
        edge_w[p, :cnt] = vals[srt]
    return edge_dst, edge_src, edge_w


class BroadcastGCN1D:
    """Inference-only 1D-broadcast GCN over the mesh (Cagnet/main.c role)."""

    def __init__(self, a, partvec: np.ndarray, k: int, fin: int,
                 widths: list[int], mesh=None, activation: str = "sigmoid",
                 seed: int = 0, fused: bool = False):
        # relabel-only plan: the broadcast baseline has no halo exchange, so
        # the partitioned path's send/halo construction would be dead work
        self.plan = relabel_plan(a, partvec, k)
        self.mesh = mesh if mesh is not None else make_mesh_1d(k)
        self.activation = activation
        self.fused = fused
        dims = list(zip([fin] + widths[:-1], widths))
        self.params = replicate(
            self.mesh, init_gcn_params(jax.random.PRNGKey(seed), dims))
        ed, es, ew = broadcast_edge_lists(a, self.plan)
        self.pa = shard_stacked(
            self.mesh, {"edge_dst": ed, "edge_src": es, "edge_w": ew})
        self.timer = PhaseTimer()
        self._gather = self._build_gather()
        self._compute = self._build_compute()
        self._fused = self._build_fused()

    # ---------------------------------------------------------------- builders
    def _smap(self, fn, in_specs, out_specs):
        return jax.jit(jax.shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs))

    def _build_gather(self):
        def per_chip(h):
            h = h[0]
            full = lax.all_gather(h, AXIS)            # (k, B, f)
            return full.reshape(-1, h.shape[-1])[None]
        return self._smap(per_chip, (P(AXIS),), P(AXIS))

    def _build_compute(self):
        act = get_activation(self.activation)

        def per_chip(w, pa, table):
            pa, table = jax.tree.map(lambda x: x[0], (pa, table))
            gathered = jnp.take(table, pa["edge_src"], axis=0) * pa["edge_w"][:, None]
            ah = jax.ops.segment_sum(
                gathered, pa["edge_dst"], num_segments=self.plan.b,
                indices_are_sorted=True)
            return act(ah @ w)[None]
        return self._smap(per_chip, (P(), P(AXIS), P(AXIS)), P(AXIS))

    def _build_fused(self):
        act = get_activation(self.activation)

        def per_chip(params, pa, h):
            pa, h = jax.tree.map(lambda x: x[0], (pa, h))
            for w in params:
                full = lax.all_gather(h, AXIS).reshape(-1, h.shape[-1])
                gathered = jnp.take(full, pa["edge_src"], axis=0) * pa["edge_w"][:, None]
                ah = jax.ops.segment_sum(
                    gathered, pa["edge_dst"], num_segments=self.plan.b,
                    indices_are_sorted=True)
                h = act(ah @ w)
            return h[None]
        return self._smap(per_chip, (P(), P(AXIS), P(AXIS)), P(AXIS))

    # --------------------------------------------------------------------- api
    def forward(self, features: np.ndarray) -> np.ndarray:
        """One inference pass; returns global (n, nout) activations."""
        h = shard_stacked(self.mesh, self.plan.scatter_rows(
            features.astype(np.float32)))
        if self.fused:
            with self.timer.phase("total", sync=lambda: h):
                h = self._fused(self.params, self.pa, h)
        else:
            for w in self.params:
                with self.timer.phase("data_comm", sync=lambda: table):
                    table = self._gather(h)
                with self.timer.phase("local_spmm", sync=lambda: h):
                    h = self._compute(w, self.pa, table)
        return self.plan.gather_rows(np.asarray(h))

    def run_epochs(self, features: np.ndarray,
                   epochs: int = 5) -> tuple[dict, np.ndarray]:
        """Reference protocol: repeated forward passes, phase times reported
        (``Cagnet/main.c:125-220,395-413``)."""
        if epochs < 1:
            raise ValueError("epochs must be >= 1")
        t0 = time.perf_counter()
        for _ in range(epochs):
            out = self.forward(features)
        elapsed = time.perf_counter() - t0
        report = {
            "epochs": epochs,
            "elapsed_s": elapsed,
            "epoch_s": elapsed / max(epochs, 1),
            "phases": self.timer.report(),
            # the broadcast baseline ships every row to every peer each layer
            "send_volume_per_exchange": int(
                (self.plan.k - 1) * self.plan.part_sizes.sum()),
        }
        return report, out
