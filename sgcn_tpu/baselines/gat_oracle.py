"""Single-device dense GAT oracle — ground truth for distributed GAT parity.

Same role as ``DenseOracle`` (DGL-baseline analogue, SURVEY.md §4): identical
math to the distributed GAT — masked neighbor softmax ``e_ij = z1_i + z2_j``
over the Â nonzero pattern, ``H' = α·Z`` (``GPU/PGAT.py:137-150`` semantics
with proper -inf masking) — on one device with a dense mask.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import scipy.sparse as sp

from ..models.activations import get_activation
from ..models.gat import init_gat_params

_NEG = -1e30


class DenseGATOracle:
    def __init__(self, a: sp.spmatrix, fin: int, widths: list[int],
                 lr: float = 0.01, activation: str = "none",
                 final_activation: str = "none",
                 optimizer: optax.GradientTransformation | None = None,
                 seed: int = 0):
        self.mask = jnp.asarray(
            (sp.coo_matrix(a).todense() > 0), dtype=bool)
        dims = list(zip([fin] + widths[:-1], widths))
        self.params = init_gat_params(jax.random.PRNGKey(seed), dims)
        self.opt = optimizer if optimizer is not None else optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self.activation = activation
        self.final_activation = final_activation
        self._step = jax.jit(self._make_step())

    def forward(self, params, h):
        act = get_activation(self.activation)
        fact = get_activation(self.final_activation)
        nl = len(params)
        for i, p in enumerate(params):
            z = h @ p["w"]
            scores = (z @ p["a1"])[:, None] + (z @ p["a2"])[None, :]
            scores = jnp.where(self.mask, scores, _NEG)
            alpha = jax.nn.softmax(scores, axis=-1)
            alpha = jnp.where(self.mask, alpha, 0.0)
            h = alpha @ z
            h = fact(h) if i == nl - 1 else act(h)
        return h

    def loss(self, params, h, labels, mask):
        logits = self.forward(params, h)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        return -(picked * mask).sum() / mask.sum()

    def _make_step(self):
        def step(params, opt_state, h, labels, mask):
            loss, grads = jax.value_and_grad(self.loss)(params, h, labels, mask)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return step

    def step(self, h, labels, mask=None) -> float:
        h = jnp.asarray(h, jnp.float32)
        labels = jnp.asarray(labels, jnp.int32)
        mask = jnp.ones(h.shape[0]) if mask is None else jnp.asarray(mask, jnp.float32)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, h, labels, mask)
        return float(loss)

    def predict(self, h) -> np.ndarray:
        return np.asarray(self.forward(self.params, jnp.asarray(h, jnp.float32)))

    def fit(self, h, labels, mask=None, epochs: int = 5) -> list[float]:
        return [self.step(h, labels, mask) for _ in range(epochs)]
