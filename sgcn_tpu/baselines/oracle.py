"""Single-device dense oracle — ground truth for distributed parity tests.

The reference's correctness story is empirical: the single-process DGL GCN
(``DGL/gcn.py``) trained on the same preprocessed inputs is the ground truth
the distributed runs are eyeballed against, and ``GPU/PGCN-Accuracy.py`` checks
partitioned training does not change predictive performance (``README.md:110``).
We make that an automated golden test: this oracle runs the *same* math as the
distributed trainer (same init seed, same optimizer, same loss) on one device
with a dense Â, and tests assert loss/logit/gradient parity to tolerance.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import scipy.sparse as sp

from ..models.gcn import init_gcn_params

_ACTS = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "none": lambda x: x}


class DenseOracle:
    """Single-device full-batch GCN with dense adjacency (DGL/gcn.py role)."""

    def __init__(self, a: sp.spmatrix, fin: int, widths: list[int],
                 lr: float = 0.01, activation: str = "relu",
                 final_activation: str = "none",
                 optimizer: optax.GradientTransformation | None = None,
                 seed: int = 0):
        self.a = jnp.asarray(sp.coo_matrix(a).todense(), dtype=jnp.float32)
        dims = list(zip([fin] + widths[:-1], widths))
        self.params = init_gcn_params(jax.random.PRNGKey(seed), dims)
        self.opt = optimizer if optimizer is not None else optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self.activation = activation
        self.final_activation = final_activation
        self._step = jax.jit(self._make_step())

    def forward(self, params, h):
        act, fact = _ACTS[self.activation], _ACTS[self.final_activation]
        nl = len(params)
        for i, w in enumerate(params):
            z = (self.a @ h) @ w
            h = fact(z) if i == nl - 1 else act(z)
        return h

    def loss(self, params, h, labels, mask):
        logits = self.forward(params, h)
        logp = jax.nn.log_softmax(logits, axis=-1)
        picked = jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        return -(picked * mask).sum() / mask.sum()

    def _make_step(self):
        def step(params, opt_state, h, labels, mask):
            loss, grads = jax.value_and_grad(self.loss)(params, h, labels, mask)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
        return step

    def step(self, h, labels, mask=None) -> float:
        h = jnp.asarray(h, jnp.float32)
        labels = jnp.asarray(labels, jnp.int32)
        mask = jnp.ones(h.shape[0]) if mask is None else jnp.asarray(mask, jnp.float32)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, h, labels, mask)
        return float(loss)

    def predict(self, h) -> np.ndarray:
        return np.asarray(self.forward(self.params, jnp.asarray(h, jnp.float32)))

    def fit(self, h, labels, mask=None, epochs: int = 5) -> list[float]:
        return [self.step(h, labels, mask) for _ in range(epochs)]
