from .oracle import DenseOracle

__all__ = ["DenseOracle"]
