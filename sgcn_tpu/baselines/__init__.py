from .oracle import DenseOracle
from .gat_oracle import DenseGATOracle

__all__ = ["DenseOracle", "DenseGATOracle"]
