from .model import (
    communication_volume,
    generate_stochastic_hypergraph,
    run_shp,
    sample_sparse_submatrix,
    simulate,
)

__all__ = [
    "communication_volume", "generate_stochastic_hypergraph", "run_shp",
    "sample_sparse_submatrix", "simulate",
]
