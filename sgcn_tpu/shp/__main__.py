"""SHP CLI — role of ``python SHP/main.py -p A.mtx -k K -s S -b B -h H -o OUT``
(``GPU/SHP/main.py:96-129``; the sampled-batch count flag is ``-m`` here since
``-h`` is taken by help).  Pickles both part vectors as ``partvec.hp.<k>`` and
``partvec.stchp.<k>`` (``:131-140``), the format ``PGCN-Mini-batch`` consumes.
"""

from __future__ import annotations

import argparse
import os

from ..io.mtx import read_mtx
from ..partition.emit import write_partvec_pickle
from .model import run_shp


def main() -> None:
    p = argparse.ArgumentParser(description="stochastic hypergraph partitioner")
    p.add_argument("-p", "--path", required=True, help="adjacency .mtx")
    p.add_argument("-k", "--nparts", type=int, required=True)
    p.add_argument("-s", "--sim-iters", type=int, default=20)
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("-m", "--sampled-batches", type=int, default=10,
                   help="batches hstacked into the stochastic hypergraph")
    p.add_argument("-o", "--outdir", default=".")
    p.add_argument("-e", "--imbalance", type=float, default=0.03)
    p.add_argument("--seed", type=int, default=1)
    args = p.parse_args()

    a = read_mtx(args.path)
    res = run_shp(a, args.nparts, args.sampled_batches, args.batch_size,
                  args.sim_iters, args.imbalance, args.seed)
    os.makedirs(args.outdir, exist_ok=True)
    for name in ("hp", "stchp"):
        out = os.path.join(args.outdir, f"partvec.{name}.{args.nparts}")
        write_partvec_pickle(out, res[f"partvec_{name}"])
        print(f"{name}: {out}  km1={res[f'km1_{name}']}  "
              f"sim_comm_volume={res[f'sim_comm_volume_{name}']}", flush=True)


if __name__ == "__main__":
    main()
