"""Stochastic hypergraph partitioning (SHP) — mini-batch-aware partitions.

Reference: ``GPU/SHP/main.py``.  The idea: a partition minimizing *full-graph*
connectivity is not optimal for *mini-batch* training, where each step only
touches a random vertex subset.  SHP builds a "stochastic hypergraph" by
horizontally stacking the column-nets of ``h`` sampled batch submatrices
(``generate_stochastic_hypergraph`` ``:64-72``), partitions THAT with the
column-net km1 objective (KaHyPar there, our native partitioner here,
``partitionColNet`` ``:17-32``), and validates by simulating ``s`` random
batches and comparing expected communication volume against the baseline
full-graph hypergraph partition (``simulate`` ``:85-93``).

All sampling is vectorized numpy; partitioning is the native C++ multilevel
colnet partitioner (``sgcn_tpu.partition.native``).
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from ..partition.native import partition_hypergraph_colnet


def sample_sparse_submatrix(a: sp.spmatrix, batch_size: int,
                            rng: np.random.Generator) -> sp.csc_matrix:
    """Batch-restricted submatrix, global row space, empty columns dropped
    (``GPU/SHP/main.py:44-62``): keep nonzeros whose row AND col are in a
    random ``batch_size``-vertex subset."""
    a = sp.coo_matrix(a)
    n = a.shape[0]
    sub = rng.choice(n, size=min(batch_size, n), replace=False)
    member = np.zeros(n, dtype=bool)
    member[sub] = True
    keep = member[a.row] & member[a.col]
    s = sp.csc_matrix(
        (a.data[keep], (a.row[keep], a.col[keep])), shape=a.shape)
    nonempty = np.diff(s.indptr) != 0
    return s[:, nonempty]


def generate_stochastic_hypergraph(a: sp.spmatrix, nbatches: int,
                                   batch_size: int,
                                   rng: np.random.Generator) -> sp.csc_matrix:
    """hstack of sampled batch submatrices: rows = cells (vertices), columns =
    nets drawn from the batch distribution (``GPU/SHP/main.py:64-72``)."""
    subs = [sample_sparse_submatrix(a, batch_size, rng)
            for _ in range(nbatches)]
    return sp.csc_matrix(sp.hstack(subs))


def communication_volume(s: sp.spmatrix, partvec: np.ndarray) -> int:
    """Σ over columns of (distinct parts touching the column − 1)
    (``GPU/SHP/main.py:74-83``), vectorized via unique (col, part) pairs."""
    s = sp.coo_matrix(s)
    if s.nnz == 0:
        return 0
    pv = np.asarray(partvec)
    pairs = s.col.astype(np.int64) * (pv.max() + 1) + pv[s.row]
    n_pairs = len(np.unique(pairs))
    n_cols = len(np.unique(s.col))
    return int(n_pairs - n_cols)


def simulate(a: sp.spmatrix, partvecs: dict[str, np.ndarray], niter: int,
             batch_size: int, rng: np.random.Generator) -> dict[str, int]:
    """Expected batch comm volume per partvec over ``niter`` sampled batches
    (``GPU/SHP/main.py:85-93``)."""
    totals = {name: 0 for name in partvecs}
    for _ in range(niter):
        s = sample_sparse_submatrix(a, batch_size, rng)
        for name, pv in partvecs.items():
            totals[name] += communication_volume(s, pv)
    return totals


def run_shp(
    a: sp.spmatrix,
    k: int,
    nsampled_batches: int = 10,
    batch_size: int = 256,
    sim_iters: int = 20,
    imbalance: float = 0.03,
    seed: int = 1,
) -> dict:
    """Full SHP pipeline: baseline HP partition, stochastic HP partition,
    batch-comm simulation of both (``GPU/SHP/main.py:96-140``)."""
    a = sp.csr_matrix(a)
    rng = np.random.default_rng(seed)
    pv_hp, km1_hp = partition_hypergraph_colnet(a, k, imbalance, seed)
    stc = generate_stochastic_hypergraph(a, nsampled_batches, batch_size, rng)
    pv_stchp, km1_stc = partition_hypergraph_colnet(
        sp.csr_matrix(stc), k, imbalance, seed)
    sim = simulate(a, {"hp": pv_hp, "stchp": pv_stchp}, sim_iters,
                   batch_size, rng)
    return {
        "partvec_hp": pv_hp,
        "partvec_stchp": pv_stchp,
        "km1_hp": km1_hp,
        "km1_stchp": km1_stc,
        "sim_comm_volume_hp": sim["hp"],
        "sim_comm_volume_stchp": sim["stchp"],
    }
