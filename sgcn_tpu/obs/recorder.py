"""RunRecorder — the run manifest + append-only JSONL event stream.

One ``RunRecorder`` per run directory (``--metrics-out DIR``):

  * ``manifest.json``   — what ran: config, argv, git rev, backend/mesh,
    plan digest and partitioner metadata.  Rewritten in place as late
    facts arrive (``set_plan``/``set_backend``) — it is a small dict, and
    a crash mid-run must still leave a parseable manifest.
  * ``events.jsonl``    — one line per event (``step``/``eval``/``summary``
    and recorder-side ``heartbeat``), appended and flushed per event so a
    killed run keeps every completed step.
  * ``heartbeat.jsonl`` — liveness pings from OTHER layers/processes
    (``heartbeat()`` below): the launch rendezvous and the multichip
    dryrun write here through the ``SGCN_METRICS_OUT`` env var, so an
    operator can distinguish "slow" (heartbeats advancing) from "stalled"
    (last heartbeat stale) without attaching a debugger.

Every record is validated against ``schema`` BEFORE it is written, and
``load_run`` re-validates on read — a run directory either loads clean or
fails loudly.  Nothing here imports jax at module scope (the CLIs set up
the backend env before heavy imports).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from dataclasses import dataclass

from . import schema


def _git_rev() -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=10, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        return out.stdout.strip() if out.returncode == 0 else None
    except Exception:                   # noqa: BLE001 — best-effort metadata
        return None


def plan_digest(plan) -> str:
    """Stable 16-hex digest of a CommPlan's comm structure — enough to tell
    "same partition/layout" apart across runs without storing the arrays."""
    h = hashlib.sha256()
    h.update(repr((plan.n, plan.k, plan.b, plan.s, plan.r, plan.e,
                   bool(plan.symmetric), tuple(plan.ell_buckets))).encode())
    for arr in (plan.send_counts, plan.halo_counts, plan.nnz,
                plan.part_sizes):
        h.update(arr.tobytes())
    return h.hexdigest()[:16]


def plan_manifest_block(plan) -> dict:
    return {
        "n": int(plan.n), "k": int(plan.k), "b": int(plan.b),
        "s": int(plan.s), "r": int(plan.r), "e": int(plan.e),
        "symmetric": bool(plan.symmetric),
        "send_rows_per_exchange": int(plan.predicted_send_volume.sum()),
        "messages_per_exchange": int(plan.predicted_message_count.sum()),
        "digest": plan_digest(plan),
    }


class RunRecorder:
    """Owns one run directory; see module docstring."""

    def __init__(self, outdir: str, config: dict | None = None,
                 run_kind: str = "train", argv: list | None = None):
        self.dir = outdir
        os.makedirs(outdir, exist_ok=True)
        # sweep manifest temp litter from previous killed runs (one shared
        # sweep policy, resilience.atomic): a RunRecorder is only ever
        # constructed by the run directory's single writer (the
        # coordinator), so anything matching here is from a dead process
        from ..resilience.atomic import sweep_temp_litter

        sweep_temp_litter(outdir, schema.MANIFEST_NAME)
        self.manifest: dict = {
            "v": schema.SCHEMA_VERSION,
            "ts": time.time(),
            "run_kind": run_kind,
            "config": _jsonable(config or {}),
            "argv": list(sys.argv if argv is None else argv),
            "git_rev": _git_rev(),
        }
        self._events = open(os.path.join(outdir, schema.EVENTS_NAME), "a")
        self._write_manifest()

    # ------------------------------------------------------------- manifest
    def _write_manifest(self) -> None:
        schema.validate_manifest(self.manifest)
        # the ONE atomic-write helper (resilience.atomic): temp + fsync +
        # rename — a kill during set_profile/set_plan leaves the previous
        # manifest parseable, and the fsync makes the rewrite durable (the
        # bare os.replace this used to do ordered metadata only)
        from ..resilience.atomic import atomic_write_json

        atomic_write_json(os.path.join(self.dir, schema.MANIFEST_NAME),
                          self.manifest, indent=1)

    def set_plan(self, plan, partitioner: dict | None = None) -> None:
        """Record the comm plan's identity (and the partitioner provenance
        that produced its partvec) in the manifest."""
        self.manifest["plan"] = plan_manifest_block(plan)
        if partitioner is not None:
            self.manifest["partitioner"] = _jsonable(partitioner)
        self._write_manifest()

    def set_partitioner(self, partitioner: dict) -> None:
        """Record partitioner provenance alone (the mini-batch trainer has
        one plan per batch, so there is no single plan block to digest)."""
        self.manifest["partitioner"] = _jsonable(partitioner)
        self._write_manifest()

    def set_comm_schedule(self, decision: dict) -> None:
        """Record the transport-selection decision log
        (``parallel/plan.py::resolve_comm_schedule``): what was asked, what
        resolved, which rule fired, and the wire-row inputs — so an
        ``auto`` pick is reconstructible from the run directory alone."""
        self.manifest["comm_schedule"] = _jsonable(decision)
        self._write_manifest()

    def set_profile(self, profile_dir: str) -> None:
        """Record where the jax.profiler trace of this run landed (the
        ``--profile`` + ``--metrics-out`` composition): the directory plus
        every trace-event JSON found under it with its gzip'd size, so
        ``scripts/obs_report.py`` can find and parse the trace from the run
        directory alone (``tracing.trace_path_for_run``)."""
        from .tracing import find_trace_files

        self.manifest["profile"] = {
            "dir": os.path.abspath(profile_dir),
            "trace_files": find_trace_files(profile_dir),
        }
        self._write_manifest()

    def set_memory(self, block: dict) -> None:
        """Record the per-chip HBM footprint block (schema v6,
        ``obs/memory.py::MemoryModel.block()``): per-family ``{model_bytes,
        measured_bytes, ratio}`` plus the total/arguments/donated aggregate
        joins.  Rewritten as measured joins arrive (the serve engine
        re-publishes after each bucket compile), like every other late
        manifest fact."""
        self.manifest["memory"] = _jsonable(block)
        self._write_manifest()

    def set_backend(self, mesh=None) -> None:
        """Record the live jax backend + mesh (call after backend init)."""
        import jax

        self.manifest["backend"] = {
            "platform": jax.default_backend(),
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
        }
        if mesh is not None:
            self.manifest["mesh"] = {
                "axes": {str(k): int(v)
                         for k, v in mesh.shape.items()},
            }
        self._write_manifest()

    # --------------------------------------------------------------- events
    def _emit(self, ev: dict) -> None:
        ev.setdefault("v", schema.SCHEMA_VERSION)
        ev.setdefault("ts", time.time())
        ev = _jsonable(ev)
        schema.validate_event(ev)
        self._events.write(json.dumps(ev) + "\n")
        self._events.flush()

    def record_step(self, step: int, loss: float, wall_s: float,
                    err: float | None = None, grad_norm: float | None = None,
                    comm: dict | None = None, phases: dict | None = None,
                    roofline: dict | None = None, drift: dict | None = None,
                    **extra) -> None:
        ev = {"kind": "step", "step": int(step), "loss": float(loss),
              "wall_s": float(wall_s)}
        if err is not None:
            ev["err"] = float(err)
        if grad_norm is not None:
            ev["grad_norm"] = float(grad_norm)
        for k, val in (("comm", comm), ("phases", phases),
                       ("roofline", roofline), ("drift", drift)):
            if val is not None:
                ev[k] = val
        ev.update({k: v for k, v in extra.items() if v is not None})
        self._emit(ev)

    def record_eval(self, step: int, loss: float, acc: float | None = None,
                    wall_s: float | None = None) -> None:
        ev = {"kind": "eval", "step": int(step), "loss": float(loss)}
        if acc is not None:
            ev["acc"] = float(acc)
        if wall_s is not None:
            ev["wall_s"] = float(wall_s)
        self._emit(ev)

    def record_span(self, name: str, dur_s: float, parent: str | None = None,
                    depth: int = 0, **fields) -> None:
        """One measured wall-clock span (``obs.tracing.SpanTimer``) — the
        schema-v2 event that puts measured phase times in the same stream
        as the analytic gauges."""
        ev = {"kind": "span", "name": str(name), "dur_s": float(dur_s),
              "depth": int(depth)}
        if parent is not None:
            ev["parent"] = str(parent)
        ev.update(fields)
        self._emit(ev)

    def record_serve(self, queries: int, achieved_qps: float,
                     latency_p50_ms: float, latency_p95_ms: float,
                     latency_p99_ms: float, **fields) -> None:
        """One serving latency/throughput window (schema v3,
        ``sgcn_tpu/serve/engine.py``): measured per-query latency quantiles
        + achieved QPS, with the batching/compile counters and the analytic
        per-query wire-row gauge riding along as optional fields."""
        ev = {"kind": "serve", "queries": int(queries),
              "achieved_qps": float(achieved_qps),
              "latency_p50_ms": float(latency_p50_ms),
              "latency_p95_ms": float(latency_p95_ms),
              "latency_p99_ms": float(latency_p99_ms)}
        ev.update({k: v for k, v in fields.items() if v is not None})
        self._emit(ev)

    def record_checkpoint(self, step: int, path: str,
                          wall_s: float | None = None,
                          bytes: int | None = None) -> None:
        """One COMMITTED durable checkpoint (schema v4,
        ``resilience.runner``): emitted after the atomic rename, so this
        event in the stream certifies the named file was fully on disk."""
        ev = {"kind": "checkpoint", "step": int(step), "path": str(path)}
        for k, val in (("wall_s", wall_s), ("bytes", bytes)):
            if val is not None:
                ev[k] = val
        self._emit(ev)

    def record_resume(self, step: int, path: str, fallback: bool = False,
                      partial_state: bool = False,
                      skipped: list | None = None) -> None:
        """One restore (schema v4, the trainer CLI's ``--resume``):
        ``fallback`` marks a corrupted-latest → previous-intact fallback,
        ``partial_state`` a params-only restore of a pre-full-state file."""
        ev = {"kind": "resume", "step": int(step), "path": str(path),
              "fallback": bool(fallback), "partial_state": bool(partial_state)}
        if skipped:
            ev["skipped"] = [str(s) for s in skipped]
        self._emit(ev)

    def record_swap(self, path: str, weights_rev: int,
                    checkpoint_step: int | None = None,
                    wall_s: float | None = None) -> None:
        """One zero-recompile weight hot-swap (schema v5,
        ``ServeEngine.swap_weights``): emitted after provenance
        verification and the leaf swap, so every serve event after it
        describes ``weights_rev``."""
        ev = {"kind": "swap", "path": str(path),
              "weights_rev": int(weights_rev)}
        for k, val in (("checkpoint_step", checkpoint_step),
                       ("wall_s", wall_s)):
            if val is not None:
                ev[k] = val
        self._emit(ev)

    def record_memory(self, program: str, model, measured: dict | None = None,
                      budget_bytes: int | None = None) -> None:
        """One compiled program's analytic-vs-measured HBM join (schema v6,
        ``obs/memory.py``): ``model`` is a ``MemoryModel``, ``measured`` a
        ``measure_compiled`` dict (``None`` when the backend exposes no
        memory analysis — the join is then simply absent)."""
        ev = {"kind": "memory", "program": str(program),
              "model_bytes": int(model.total_bytes),
              "workload": model.workload,
              "families": {name: int(b)
                           for name, b in model.families.items()}}
        if measured is not None:
            # measure_compiled's "peak_bytes" lands as "measured_peak_bytes"
            # in the event vocabulary (the model side owns the bare names)
            ev.update({("measured_peak_bytes" if k == "peak_bytes" else k):
                       int(v) for k, v in measured.items()})
            if model.total_bytes > 0:
                ev["ratio"] = measured["peak_bytes"] / model.total_bytes
        if budget_bytes is not None:
            ev["budget_bytes"] = int(budget_bytes)
        self._emit(ev)

    def record_heartbeat(self, event: str, **fields) -> None:
        self._emit({"kind": "heartbeat", "event": str(event),
                    "pid": os.getpid(), **fields})

    def record_summary(self, report: dict) -> None:
        """End-of-run report (the trainer's ``fit()`` dict, the bench JSON)."""
        self._emit({"kind": "summary", "report": _jsonable(report)})

    def close(self) -> None:
        self._events.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ------------------------------------------------- out-of-recorder emission
def append_env_event(filename: str, ev: dict) -> None:
    """Validate + append one event to ``$SGCN_METRICS_OUT/<filename>`` — the
    ONE out-of-recorder emission path (``heartbeat`` pings and
    ``obs.tracing.emit_span`` bench spans both ride it).  No-op unless the
    env var names a directory; best-effort by design: a full disk must not
    kill the run it is observing."""
    outdir = os.environ.get("SGCN_METRICS_OUT")
    if not outdir:
        return
    try:
        schema.validate_event(ev)
        os.makedirs(outdir, exist_ok=True)
        with open(os.path.join(outdir, filename), "a") as fh:
            fh.write(json.dumps(_jsonable(ev)) + "\n")
    except (OSError, ValueError):
        pass


def heartbeat(event: str, **fields) -> None:
    """Append a liveness ping to ``$SGCN_METRICS_OUT/heartbeat.jsonl``.

    No-op unless the env var names a directory — callers sprinkle these at
    phase boundaries unconditionally (launch rendezvous, multichip dryrun)
    and pay nothing when telemetry is off.
    """
    if not os.environ.get("SGCN_METRICS_OUT"):
        return
    append_env_event(schema.HEARTBEAT_NAME, {
        "v": schema.SCHEMA_VERSION, "ts": time.time(), "kind": "heartbeat",
        "event": str(event), "pid": os.getpid(), **fields})


# -------------------------------------------------------------------- loader
@dataclass
class RunLog:
    path: str
    manifest: dict
    events: list          # validated events.jsonl records, in write order
    heartbeats: list      # validated heartbeat.jsonl records (may be empty)

    def steps(self) -> list:
        return [e for e in self.events if e["kind"] == "step"]

    def evals(self) -> list:
        return [e for e in self.events if e["kind"] == "eval"]

    def summaries(self) -> list:
        return [e for e in self.events if e["kind"] == "summary"]

    def serves(self) -> list:
        return [e for e in self.events if e["kind"] == "serve"]

    def checkpoints(self) -> list:
        return [e for e in self.events if e["kind"] == "checkpoint"]

    def resumes(self) -> list:
        return [e for e in self.events if e["kind"] == "resume"]


def load_run(path: str) -> RunLog:
    """Load + validate one run directory.  Raises on schema violations —
    a telemetry consumer must never silently chart garbage.

    A directory holding ONLY ``heartbeat.jsonl`` or ``events.jsonl`` is
    valid: the launch/dryrun layers write heartbeats — and ``bench.py`` and
    its A/B children write spans (``obs.tracing.emit_span``) — through
    ``$SGCN_METRICS_OUT`` without a ``RunRecorder`` (no manifest), and a
    killed run's completed measurements must be loadable from exactly
    that.  ``manifest`` is then ``{}``."""
    mpath = os.path.join(path, schema.MANIFEST_NAME)
    if os.path.exists(mpath):
        with open(mpath) as fh:
            manifest = json.load(fh)
        schema.validate_manifest(manifest)
    elif any(os.path.exists(os.path.join(path, n))
             for n in (schema.HEARTBEAT_NAME, schema.EVENTS_NAME)):
        manifest = {}
    else:
        raise FileNotFoundError(
            f"{path}: no {schema.MANIFEST_NAME}, {schema.HEARTBEAT_NAME} "
            f"or {schema.EVENTS_NAME} — not a run directory")

    def read_jsonl(name):
        p = os.path.join(path, name)
        if not os.path.exists(p):
            return []
        out = []
        with open(p) as fh:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"{p}:{i + 1}: not valid JSON ({e})") from e
                schema.validate_event(ev)
                out.append(ev)
        return out

    return RunLog(path=path, manifest=manifest,
                  events=read_jsonl(schema.EVENTS_NAME),
                  heartbeats=read_jsonl(schema.HEARTBEAT_NAME))


def _jsonable(x):
    """Coerce numpy scalars/arrays and other non-JSON leaves to JSON types."""
    import numpy as np

    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, np.ndarray):
        return [_jsonable(v) for v in x.tolist()]
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    # jax arrays and anything else scalar-like: try float, else repr
    try:
        return float(x)
    except (TypeError, ValueError):
        return repr(x)
