"""Run-telemetry subsystem: manifest + per-step JSONL events + attribution.

Four layers (see ``docs/observability.md`` for the operator guide):

  * ``recorder``    — ``RunRecorder`` (manifest, append-only event stream,
    heartbeats) and the ``load_run`` loader;
  * ``attribution`` — the analytic step cost model (plan-derived SpMM/dense
    FLOPs, gather bytes, halo wire bytes) joined against measured step time
    into roofline fields;
  * ``tracing``     — the MEASURED-time profiling layer: the span API
    (nested wall-clock spans emitted as ``span`` events), the
    ``jax.profiler`` trace parser (per-device op timelines classified into
    the attribution vocabulary → measured overlap / exposed comm /
    straggler skew), and the per-step ``measured_vs_model`` reconciliation
    of the two;
  * ``schema``      — the versioned event vocabulary all of the above are
    validated against.

Wired through the trainers (``FullBatchTrainer.attach_recorder`` /
``MiniBatchTrainer.attach_recorder``), the trainer CLI (``--metrics-out``),
``bench.py`` and the launch/dryrun layers (heartbeats via
``$SGCN_METRICS_OUT``).  Rendered by ``scripts/obs_report.py``.
"""

from .attribution import (STREAM_CEILING_GBS, StepCostModel,
                          gather_bytes_per_epoch, roofline_fields, step_cost)
from .memory import (MEM_MODEL_TOL, MemoryBudgetError, MemoryModel,
                     check_memory_budget, measure_compiled, memory_model,
                     parse_bytes, reconcile)
from .recorder import RunLog, RunRecorder, heartbeat, load_run, plan_digest
from .schema import SCHEMA_VERSION, validate_event, validate_manifest
from .tracing import (SpanTimer, TraceSummary, classify_op, emit_span,
                      find_trace_files, measured_vs_model_block, scoped_span,
                      summarize_trace, trace_path_for_run)

__all__ = [
    "MEM_MODEL_TOL", "SCHEMA_VERSION", "STREAM_CEILING_GBS",
    "MemoryBudgetError", "MemoryModel", "RunLog", "RunRecorder",
    "SpanTimer", "StepCostModel", "TraceSummary",
    "check_memory_budget", "classify_op", "emit_span",
    "find_trace_files", "gather_bytes_per_epoch", "heartbeat", "load_run",
    "measure_compiled", "measured_vs_model_block", "memory_model",
    "parse_bytes", "plan_digest", "reconcile", "roofline_fields",
    "scoped_span", "step_cost", "summarize_trace", "trace_path_for_run",
    "validate_event", "validate_manifest",
]
