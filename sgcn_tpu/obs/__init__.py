"""Run-telemetry subsystem: manifest + per-step JSONL events + attribution.

Three layers (see ``docs/observability.md`` for the operator guide):

  * ``recorder``    — ``RunRecorder`` (manifest, append-only event stream,
    heartbeats) and the ``load_run`` loader;
  * ``attribution`` — the analytic step cost model (plan-derived SpMM/dense
    FLOPs, gather bytes, halo wire bytes) joined against measured step time
    into roofline fields;
  * ``schema``      — the versioned event vocabulary both of the above are
    validated against.

Wired through the trainers (``FullBatchTrainer.attach_recorder`` /
``MiniBatchTrainer.attach_recorder``), the trainer CLI (``--metrics-out``),
``bench.py`` and the launch/dryrun layers (heartbeats via
``$SGCN_METRICS_OUT``).  Rendered by ``scripts/obs_report.py``.
"""

from .attribution import (STREAM_CEILING_GBS, StepCostModel,
                          gather_bytes_per_epoch, roofline_fields, step_cost)
from .recorder import RunLog, RunRecorder, heartbeat, load_run, plan_digest
from .schema import SCHEMA_VERSION, validate_event, validate_manifest

__all__ = [
    "SCHEMA_VERSION", "STREAM_CEILING_GBS", "RunLog", "RunRecorder",
    "StepCostModel", "gather_bytes_per_epoch", "heartbeat", "load_run",
    "plan_digest", "roofline_fields", "step_cost", "validate_event",
    "validate_manifest",
]
