"""Run-telemetry schema — the versioned vocabulary of the JSONL event stream.

Everything the subsystem writes (``manifest.json``, ``events.jsonl``,
``heartbeat.jsonl``) is validated against THIS module before it hits disk
(``recorder.RunRecorder``) and again on load (``recorder.load_run``), so a
run directory is machine-checkable end to end.  The field glossary lives in
``docs/observability.md``; this module is the executable form.

Design rules:

  * every record carries ``v`` (schema version) and ``ts`` (unix seconds);
    events additionally carry ``kind``;
  * required fields are typed; optional fields are typed WHEN present —
    unknown extra fields are allowed (forward compatibility), unknown
    ``kind`` values are not;
  * numeric health: wall-clock and step-index fields must be finite — a
    NaN wall time is always a recorder bug, while ``loss`` may be non-finite
    (a diverged run is exactly what telemetry must be able to show).

Bump ``SCHEMA_VERSION`` on any breaking field change and teach
``load_run``/``scripts/obs_report.py`` both versions for one release.
"""

from __future__ import annotations

import math
import numbers

SCHEMA_VERSION = 1

# event stream file names inside a run directory
MANIFEST_NAME = "manifest.json"
EVENTS_NAME = "events.jsonl"
HEARTBEAT_NAME = "heartbeat.jsonl"

EVENT_KINDS = ("step", "eval", "heartbeat", "summary")

_NUM = numbers.Real
_STR = str

# kind -> {field: type} (required)
_REQUIRED = {
    "step": {"step": _NUM, "loss": _NUM, "wall_s": _NUM},
    "eval": {"step": _NUM, "loss": _NUM},
    "heartbeat": {"event": _STR},
    "summary": {"report": dict},
}

# kind -> {field: type} (optional, typed when present)
_OPTIONAL = {
    "step": {
        "err": _NUM,          # the MPI stack's `err` metric (loss='bce')
        "grad_norm": _NUM,    # global L2 norm of the psum'd weight grads
        "comm": dict,         # cumulative CommStats.report() snapshot
        "phases": dict,       # PhaseTimer.report() snapshot
        "roofline": dict,     # attribution.roofline_fields output
        "drift": dict,        # stale-halo drift gauges (see below)
        "epoch": _NUM,
        "batch": _NUM,        # mini-batch trainer: batch index within epoch
    },
    "eval": {"acc": _NUM, "wall_s": _NUM},
    "heartbeat": {"pid": _NUM, "phase": _STR, "detail": _STR},
    "summary": {},
}

# comm snapshot: the CommStats.report() keys every step event must reconcile
# (hidden + exposed == total — asserted by tests/test_metrics_cli.py)
COMM_SPLIT_KEYS = ("exchanges", "exposed_exchanges", "hidden_exchanges",
                   "exposed_send_volume", "hidden_send_volume",
                   "total_send_volume")

# roofline wire-byte fields (PR-4, backward-compatible v1 addition): when a
# step event's roofline block carries ANY of these, it must carry them all —
# the padded-vs-true split is meaningless in halves.  Old run directories
# (rooflines without the split) still validate: absence is legal, an
# incomplete split is not.  ``halo_bytes_true_per_step`` is the Σ(λ−1)
# volume the partitioner optimizes; ``halo_bytes_wire_per_step`` what the
# selected schedule ships (k²·S·f dense a2a, Σ_d k·S_d·f ragged);
# ``padding_efficiency`` their row-level ratio in [0, 1].
ROOFLINE_WIRE_KEYS = ("comm_schedule", "halo_bytes_true_per_step",
                      "halo_bytes_wire_per_step",
                      "halo_wire_rows_per_exchange", "padding_efficiency")
COMM_SCHEDULES = ("a2a", "ragged", "mixed")

# drift-gauge fields (stale mode only): the AUTHORITATIVE field list —
# ``validate_event`` requires every one of these in a step event's ``drift``
# block, so this tuple, the trainer's ``_drift_fields`` and the
# docs/observability.md glossary cannot drift apart
DRIFT_KEYS = ("staleness_age", "sync_step", "halo_drift_rms",
              "halo_drift_rel", "halo_quant_err_rms")

# One OPTIONAL drift field, validated when present (see validate_event):
# ``round_age`` is the composed (stale × ragged) mode's per-round
# staleness-age vector — one entry per ring round, the age of the buffer
# this step CONSUMED (0 = received this step, N = carried N steps,
# null = empty round, ships nothing).

_MANIFEST_REQUIRED = {"v": _NUM, "ts": _NUM, "run_kind": _STR, "config": dict}
_MANIFEST_OPTIONAL = {
    "argv": list, "git_rev": (str, type(None)), "backend": dict,
    "mesh": dict, "plan": dict, "partitioner": (dict, type(None)),
    # resolve_comm_schedule's decision log (asked/resolved/rule + the
    # wire-row inputs) — how an 'auto' transport pick is reconstructible
    # from the run directory alone
    "comm_schedule": dict,
}


def _check_fields(rec: dict, required: dict, optional: dict, what: str) -> None:
    for f, t in required.items():
        if f not in rec:
            raise ValueError(f"{what}: missing required field {f!r}: {rec}")
        if not isinstance(rec[f], t) or isinstance(rec[f], bool) and t is _NUM:
            raise ValueError(
                f"{what}: field {f!r} has type {type(rec[f]).__name__}, "
                f"expected {t}")
    for f, t in optional.items():
        if f in rec and rec[f] is not None and not isinstance(rec[f], t):
            raise ValueError(
                f"{what}: optional field {f!r} has type "
                f"{type(rec[f]).__name__}, expected {t}")


def validate_event(ev: dict) -> None:
    """Raise ``ValueError`` unless ``ev`` is a valid schema-v1 event."""
    if not isinstance(ev, dict):
        raise ValueError(f"event must be a dict, got {type(ev).__name__}")
    kind = ev.get("kind")
    if kind not in EVENT_KINDS:
        raise ValueError(f"unknown event kind {kind!r} (know {EVENT_KINDS})")
    if ev.get("v") != SCHEMA_VERSION:
        raise ValueError(
            f"event schema version {ev.get('v')!r} != {SCHEMA_VERSION}")
    if not isinstance(ev.get("ts"), _NUM):
        raise ValueError(f"event missing numeric ts: {ev}")
    _check_fields(ev, _REQUIRED[kind], _OPTIONAL[kind], f"{kind} event")
    # wall-clock / index health: a NaN here is a recorder bug, not a run fact
    for f in ("step", "wall_s", "epoch", "batch"):
        if f in ev and isinstance(ev[f], _NUM) and not math.isfinite(ev[f]):
            raise ValueError(f"{kind} event: non-finite {f}={ev[f]}")
    if kind == "step" and "comm" in ev and ev["comm"] is not None:
        comm = ev["comm"]
        missing = [k for k in COMM_SPLIT_KEYS if k not in comm]
        if missing:
            raise ValueError(
                f"step event comm snapshot missing {missing} "
                "(must be a full CommStats.report())")
        if (comm["exposed_exchanges"] + comm["hidden_exchanges"]
                != comm["exchanges"]):
            raise ValueError(
                "step event comm snapshot violates the hidden/exposed "
                f"split: {comm['exposed_exchanges']} + "
                f"{comm['hidden_exchanges']} != {comm['exchanges']}")
    if kind == "step" and isinstance(ev.get("roofline"), dict):
        roof = ev["roofline"]
        present = [k for k in ROOFLINE_WIRE_KEYS if k in roof]
        if present and len(present) != len(ROOFLINE_WIRE_KEYS):
            missing = [k for k in ROOFLINE_WIRE_KEYS if k not in roof]
            raise ValueError(
                f"step event roofline carries a partial wire split "
                f"(has {present}, missing {missing}) — ship all of "
                "ROOFLINE_WIRE_KEYS or none")
        if present:
            if roof["comm_schedule"] not in COMM_SCHEDULES:
                raise ValueError(
                    f"roofline comm_schedule {roof['comm_schedule']!r} not "
                    f"one of {COMM_SCHEDULES}")
            pe = roof["padding_efficiency"]
            if not (isinstance(pe, _NUM) and 0 <= pe <= 1):
                raise ValueError(
                    f"roofline padding_efficiency {pe!r} outside [0, 1]")
            if roof["halo_bytes_wire_per_step"] \
                    < roof["halo_bytes_true_per_step"]:
                raise ValueError(
                    "roofline wire bytes below true bytes — a schedule "
                    "cannot ship less than the unpadded volume "
                    f"({roof['halo_bytes_wire_per_step']} < "
                    f"{roof['halo_bytes_true_per_step']})")
    if kind == "step" and ev.get("drift") is not None:
        missing = [k for k in DRIFT_KEYS if k not in ev["drift"]]
        if missing:
            raise ValueError(
                f"step event drift block missing {missing} "
                f"(must carry every DRIFT_KEYS field)")
        ra = ev["drift"].get("round_age")
        if ra is not None:
            if not isinstance(ra, list) or any(
                    not (x is None or (isinstance(x, _NUM)
                                       and not isinstance(x, bool)
                                       and x >= 0)) for x in ra):
                raise ValueError(
                    f"drift round_age must be a list of null / non-negative "
                    f"ages (one per ring round), got {ra!r}")


def validate_manifest(m: dict) -> None:
    """Raise ``ValueError`` unless ``m`` is a valid schema-v1 manifest."""
    if not isinstance(m, dict):
        raise ValueError(f"manifest must be a dict, got {type(m).__name__}")
    if m.get("v") != SCHEMA_VERSION:
        raise ValueError(
            f"manifest schema version {m.get('v')!r} != {SCHEMA_VERSION}")
    _check_fields(m, _MANIFEST_REQUIRED, _MANIFEST_OPTIONAL, "manifest")
