"""Run-telemetry schema — the versioned vocabulary of the JSONL event stream.

Everything the subsystem writes (``manifest.json``, ``events.jsonl``,
``heartbeat.jsonl``) is validated against THIS module before it hits disk
(``recorder.RunRecorder``) and again on load (``recorder.load_run``), so a
run directory is machine-checkable end to end.  The field glossary lives in
``docs/observability.md``; this module is the executable form.

Design rules:

  * every record carries ``v`` (schema version) and ``ts`` (unix seconds);
    events additionally carry ``kind``;
  * required fields are typed; optional fields are typed WHEN present —
    unknown extra fields are allowed (forward compatibility), unknown
    ``kind`` values are not;
  * numeric health: wall-clock and step-index fields must be finite — a
    NaN wall time is always a recorder bug, while ``loss`` may be non-finite
    (a diverged run is exactly what telemetry must be able to show).

Bump ``SCHEMA_VERSION`` on any breaking field change and teach
``load_run``/``scripts/obs_report.py`` both versions for one release.

Version history:

  * **v1** — manifest + ``step``/``eval``/``heartbeat``/``summary`` events.
  * **v2** — the measured-time profiling layer (``obs/tracing.py``): adds
    the ``span`` event kind (named, optionally nested measured wall-clock
    spans), the optional ``measured_vs_model`` block on step events
    (measured-vs-analytic roofline reconciliation), and the optional
    ``profile`` manifest block (where the jax.profiler trace landed).
    Purely additive — every valid v1 record is a valid record here, and
    ``validate_event`` accepts both versions (``SUPPORTED_VERSIONS``); a
    v1 stream must never carry the v2-only ``span`` kind.
  * **v3** — the serving subsystem (``sgcn_tpu/serve/``): adds the
    ``serve`` event kind — one latency/throughput window of the inference
    engine (query count, achieved QPS, p50/p95/p99 latency, batching and
    compile counters, per-query wire-row gauge).  Purely additive again:
    v1/v2 streams load unchanged and must not carry the v3-only kind.
  * **v4** — the resilience layer (``sgcn_tpu/resilience/``,
    ``docs/resilience.md``): adds the ``checkpoint`` event kind (one
    committed durable checkpoint: step, path, bytes, save wall time) and
    the ``resume`` event kind (one restore: step, path, whether the
    newest checkpoint was corrupt and fell back, whether the restore was
    partial-state), plus the optional ``shed``/``shed_factor`` keys on
    ``serve`` events (deadline-shed query count of the window — the
    graceful-degradation counter of the micro-batcher).  Purely additive:
    v1–v3 streams load unchanged and must not carry the v4-only kinds.
  * **v5** — sub-graph serving + weight hot-swap (``docs/serving.md``
    phase 2): adds the ``swap`` event kind (one zero-recompile weight
    hot-swap: checkpoint path, the engine's post-swap ``weights_rev``) and
    the optional ``serve_mode``/``weights_rev``/``touched_rows_per_query``
    /``subgraph_flops_per_query`` keys on ``serve`` events — a window
    spanning a swap is attributable to its weight revisions, and the
    sub-graph engine's per-query analytic gauges ride the same stream.
    Purely additive: v1–v4 streams load unchanged and must not carry the
    v5-only kind.
  * **v6** — memory observability (``obs/memory.py``): adds the ``memory``
    event kind (one compiled program's analytic-vs-measured per-chip HBM
    join: the plan-derived model total against XLA's
    ``memory_analysis()`` argument/output/temp/alias/peak bytes) and the
    optional ``memory`` manifest block (the per-family ``{model_bytes,
    measured_bytes, ratio}`` breakdown — ``MemoryModel.block()``).  The
    join fields follow the ``measured_vs_model`` discipline: when both
    endpoints are present the ``ratio`` must be derivable from them.
    Purely additive: v1–v5 streams load unchanged and must not carry the
    v6-only kind.
"""

from __future__ import annotations

import math
import numbers

SCHEMA_VERSION = 6
SUPPORTED_VERSIONS = (1, 2, 3, 4, 5, 6)

# event stream file names inside a run directory
MANIFEST_NAME = "manifest.json"
EVENTS_NAME = "events.jsonl"
HEARTBEAT_NAME = "heartbeat.jsonl"

EVENT_KINDS = ("step", "eval", "heartbeat", "summary", "span", "serve",
               "checkpoint", "resume", "swap", "memory")
# the span kind is a v2 addition, the serve kind v3, checkpoint/resume v4,
# swap v5, memory v6; a stream claiming an older version must not carry a
# newer kind
_KINDS_BY_VERSION = {1: ("step", "eval", "heartbeat", "summary"),
                     2: ("step", "eval", "heartbeat", "summary", "span"),
                     3: ("step", "eval", "heartbeat", "summary", "span",
                         "serve"),
                     4: ("step", "eval", "heartbeat", "summary", "span",
                         "serve", "checkpoint", "resume"),
                     5: ("step", "eval", "heartbeat", "summary", "span",
                         "serve", "checkpoint", "resume", "swap"),
                     6: EVENT_KINDS}

_NUM = numbers.Real
_STR = str

# kind -> {field: type} (required)
_REQUIRED = {
    "step": {"step": _NUM, "loss": _NUM, "wall_s": _NUM},
    "eval": {"step": _NUM, "loss": _NUM},
    "heartbeat": {"event": _STR},
    "summary": {"report": dict},
    # v2: one measured wall-clock span (obs/tracing.py::SpanTimer) — the
    # trainers' step/eval phases and bench.py's A/B phases all emit these,
    # so measured phase times live in the SAME stream as the analytic gauges
    "span": {"name": _STR, "dur_s": _NUM},
    # v3: one serving latency/throughput window (sgcn_tpu/serve/engine.py):
    # measured per-query latency quantiles + achieved QPS over `queries`
    # completed queries.  The quantiles are MEASURED figures (host clock
    # around submit→result), so the validator holds them to the same
    # health rules as wall_s — finite, non-negative, and ordered.
    "serve": {"queries": _NUM, "achieved_qps": _NUM,
              "latency_p50_ms": _NUM, "latency_p95_ms": _NUM,
              "latency_p99_ms": _NUM},
    # v4: one committed durable checkpoint (resilience.runner) — emitted
    # AFTER the atomic rename, so an event in the stream means the file
    # named was fully on disk at that moment
    "checkpoint": {"step": _NUM, "path": _STR},
    # v4: one restore (trainer CLI --resume): ``fallback`` true when the
    # newest checkpoint was corrupt and an older intact one was used;
    # ``partial_state`` true when a pre-full-state file loaded params-only
    "resume": {"step": _NUM, "path": _STR},
    # v5: one zero-recompile weight hot-swap (ServeEngine.swap_weights):
    # emitted AFTER provenance verification and the in-place leaf swap, so
    # every serve event after it describes the new ``weights_rev``
    "swap": {"path": _STR, "weights_rev": _NUM},
    # v6: one compiled program's analytic-vs-measured per-chip HBM join
    # (obs/memory.py): ``model_bytes`` is the plan-derived analytic total —
    # always computable, like measured_vs_model's model_s; the measured
    # side (XLA memory_analysis) rides as optional fields
    "memory": {"program": _STR, "model_bytes": _NUM},
}

# kind -> {field: type} (optional, typed when present)
_OPTIONAL = {
    "step": {
        "err": _NUM,          # the MPI stack's `err` metric (loss='bce')
        "grad_norm": _NUM,    # global L2 norm of the psum'd weight grads
        "comm": dict,         # cumulative CommStats.report() snapshot
        "phases": dict,       # PhaseTimer.report() snapshot
        "roofline": dict,     # attribution.roofline_fields output
        "drift": dict,        # stale-halo drift gauges (see below)
        "replica": dict,      # hot-halo replication gauges (see below)
        "epoch": _NUM,
        "batch": _NUM,        # mini-batch trainer: batch index within epoch
        # v2: measured-vs-analytic reconciliation block (obs/tracing.py):
        # the span-measured phase-time total of this step joined against
        # attribution.step_cost per component (ratio + absolute error) —
        # a mispredicting cost model becomes a visible gauge
        "measured_vs_model": dict,
    },
    "eval": {"acc": _NUM, "wall_s": _NUM},
    "heartbeat": {"pid": _NUM, "phase": _STR, "detail": _STR},
    "summary": {},
    "span": {
        "parent": (str, type(None)),  # enclosing span's name (None = root)
        "depth": _NUM,        # nesting depth at entry (0 = root)
        "step": _NUM,         # optimizer step the span belongs to, if any
        "pid": _NUM,          # emitting process (bench A/B children differ)
        "phase": _STR,        # coarse phase label (bench arms, trainer fit)
        "detail": _STR,
    },
    "serve": {
        "window_s": _NUM,       # wall-clock span of this window
        "offered_qps": _NUM,    # open-loop target rate (absent closed-loop)
        "mode": _STR,           # 'open' or 'closed' loop generator
        "batches": _NUM,        # micro-batches executed
        "mean_batch": _NUM,     # mean queries per micro-batch
        "deadline_flushes": _NUM,   # flushed by the latency budget
        "full_flushes": _NUM,       # flushed by max-batch
        "latency_budget_ms": _NUM,
        "compiles": _NUM,       # AOT bucket compiles (0 in steady state —
        #                         the no-recompile contract's gauge)
        "buckets": list,        # padded batch-size buckets pre-compiled
        "comm_schedule": _STR,  # resolved transport of the forward
        "wire_rows_per_query": _NUM,   # analytic: L·wire_rows/exchange ÷
        #                                max_batch (plan-derived, zero-band)
        # v4 additive: deadline shedding (docs/resilience.md): queries
        # whose age already exceeded budget × shed_factor before dispatch
        # were returned as shed markers instead of silently blowing p99
        "shed": _NUM,
        "shed_factor": _NUM,
        # v5 additive: sub-graph serving + hot-swap attribution
        # (docs/serving.md phase 2): which engine mode served the window,
        # under which weight revision, and — sub-graph mode only — the
        # accumulated per-query receptive-set gauges (analytic, zero-band)
        "serve_mode": _STR,
        "weights_rev": _NUM,
        "touched_rows_per_query": _NUM,
        "subgraph_flops_per_query": _NUM,
    },
    "checkpoint": {
        "bytes": _NUM,        # committed file size
        "wall_s": _NUM,       # save duration (host clock around the write)
    },
    "resume": {
        "fallback": bool,     # newest checkpoint corrupt, older one used
        "partial_state": bool,  # pre-full-state file: params-only restore
        "skipped": list,      # corrupt checkpoint paths passed over
    },
    "swap": {
        "checkpoint_step": _NUM,  # the swapped checkpoint's training step
        "wall_s": _NUM,           # load+verify+swap duration (host clock)
    },
    "memory": {
        "workload": _STR,             # 'train' | 'serve' | 'serve_subgraph'
        "measured_peak_bytes": _NUM,  # arg + out + temp − alias (per device)
        "argument_bytes": _NUM,       # XLA memory_analysis components
        "output_bytes": _NUM,
        "temp_bytes": _NUM,
        "alias_bytes": _NUM,          # donated set (0 for serve programs)
        "generated_code_bytes": _NUM,
        "ratio": _NUM,                # measured_peak / model — must be
        #                               derivable from its own record
        "families": dict,             # per-family model_bytes detail
        "budget_bytes": _NUM,         # the --memory-budget in force, if any
    },
}

# comm snapshot: the CommStats.report() keys every step event must reconcile
# (hidden + exposed == total — asserted by tests/test_metrics_cli.py)
COMM_SPLIT_KEYS = ("exchanges", "exposed_exchanges", "hidden_exchanges",
                   "exposed_send_volume", "hidden_send_volume",
                   "total_send_volume")

# roofline wire-byte fields (PR-4, backward-compatible v1 addition): when a
# step event's roofline block carries ANY of these, it must carry them all —
# the padded-vs-true split is meaningless in halves.  Old run directories
# (rooflines without the split) still validate: absence is legal, an
# incomplete split is not.  ``halo_bytes_true_per_step`` is the Σ(λ−1)
# volume the partitioner optimizes; ``halo_bytes_wire_per_step`` what the
# selected schedule ships (k²·S·f dense a2a, Σ_d k·S_d·f ragged);
# ``padding_efficiency`` their row-level ratio in [0, 1].
ROOFLINE_WIRE_KEYS = ("comm_schedule", "halo_bytes_true_per_step",
                      "halo_bytes_wire_per_step",
                      "halo_wire_rows_per_exchange", "padding_efficiency")
COMM_SCHEDULES = ("a2a", "ragged", "mixed")

# drift-gauge fields (stale mode only): the AUTHORITATIVE field list —
# ``validate_event`` requires every one of these in a step event's ``drift``
# block, so this tuple, the trainer's ``_drift_fields`` and the
# docs/observability.md glossary cannot drift apart
DRIFT_KEYS = ("staleness_age", "sync_step", "halo_drift_rms",
              "halo_drift_rel", "halo_quant_err_rms")

# One OPTIONAL drift field, validated when present (see validate_event):
# ``round_age`` is the composed (stale × ragged) mode's per-round
# staleness-age vector — one entry per ring round, the age of the buffer
# this step CONSUMED (0 = received this step, N = carried N steps,
# null = empty round, ships nothing).

# replica-gauge fields (--replica-budget mode only): the AUTHORITATIVE
# field list — ``validate_event`` requires every one of these in a step
# event's ``replica`` block (``FullBatchTrainer._replica_fields``):
# ``refresh_age`` = steps since the replica tables were last refreshed,
# ``replica_drift_rms``/``_rel`` = per-layer ‖replica − fresh‖ measured AT
# each refresh (the drift the refresh erased; identically zero between
# refreshes, where no fresh value exists to compare against),
# ``replica_rows`` = the plan's replicated row count.
REPLICA_KEYS = ("refresh_age", "sync_step", "replica_rows",
                "replica_drift_rms", "replica_drift_rel")

# OPTIONAL replica fields, validated when present: drift-banded PARTIAL
# refresh (--refresh-band, docs/replication.md) stamps refresh steps with
# ``refresh_kind`` ('full' | 'partial') and, on partial steps, the ACTUAL
# per-layer side-channel rows shipped (``refresh_rows`` — the per-step
# face of CommStats' partial_refresh_* cumulative booking) plus the
# static padded side-channel wire rows (``refresh_wire_rows``).
REPLICA_REFRESH_KINDS = ("full", "partial")

_MANIFEST_REQUIRED = {"v": _NUM, "ts": _NUM, "run_kind": _STR, "config": dict}
_MANIFEST_OPTIONAL = {
    "argv": list, "git_rev": (str, type(None)), "backend": dict,
    "mesh": dict, "plan": dict, "partitioner": (dict, type(None)),
    # resolve_comm_schedule's decision log (asked/resolved/rule + the
    # wire-row inputs) — how an 'auto' transport pick is reconstructible
    # from the run directory alone
    "comm_schedule": dict,
    # v2: where the jax.profiler trace of this run landed (--profile +
    # --metrics-out composed): directory, trace-event JSON path(s) and
    # their gzip'd sizes — obs_report.py parses the trace from the run
    # directory alone (obs/tracing.py::find_trace_files)
    "profile": dict,
    # v6: the per-chip HBM footprint block (obs/memory.py::MemoryModel
    # .block()): per-family {model_bytes, measured_bytes, ratio} plus the
    # total/arguments/donated aggregate joins — validated below so a
    # manifest's memory claims are self-consistent
    "memory": dict,
}

# memory-join entries ({model_bytes, measured_bytes, ratio} — the manifest
# memory block's per-family rows and the aggregate rows): model_bytes is
# required and non-negative; measured_bytes may be None (no compiled
# program measured yet); when both endpoints are present and model > 0 the
# ratio must be derivable from them (same rule as measured_vs_model).
_MEMORY_AGGREGATES = ("total", "arguments", "donated")

# measured_vs_model component entries: required/optional numeric fields.
# ``model_s`` is the analytic prediction, ``measured_s`` the span- or
# trace-derived figure (None = the measured side has no probe for this
# component in this run); when both are present the writer must also ship
# the join — ``ratio`` (measured/model) and ``abs_err_s`` (measured−model)
# — and they must be CONSISTENT with the endpoints (an inconsistent join
# is a writer bug, not a run fact).
_MVM_REL_TOL = 1e-6


def _check_fields(rec: dict, required: dict, optional: dict, what: str) -> None:
    for f, t in required.items():
        if f not in rec:
            raise ValueError(f"{what}: missing required field {f!r}: {rec}")
        if not isinstance(rec[f], t) or isinstance(rec[f], bool) and t is _NUM:
            raise ValueError(
                f"{what}: field {f!r} has type {type(rec[f]).__name__}, "
                f"expected {t}")
    for f, t in optional.items():
        if f in rec and rec[f] is not None and not isinstance(rec[f], t):
            raise ValueError(
                f"{what}: optional field {f!r} has type "
                f"{type(rec[f]).__name__}, expected {t}")


def _validate_measured_vs_model(mvm: dict) -> None:
    if not isinstance(mvm.get("phase_total_s"), _NUM) \
            or isinstance(mvm.get("phase_total_s"), bool) \
            or not math.isfinite(mvm["phase_total_s"]) \
            or mvm["phase_total_s"] < 0:
        raise ValueError(
            "measured_vs_model: missing/non-finite phase_total_s "
            f"(got {mvm.get('phase_total_s')!r}) — the span-measured "
            "phase-time total is the block's anchor")
    comps = mvm.get("components")
    if not isinstance(comps, dict) or not comps:
        raise ValueError(
            "measured_vs_model: missing/empty components dict")
    for name, c in comps.items():
        if not isinstance(c, dict):
            raise ValueError(
                f"measured_vs_model component {name!r} is not a dict")
        ms = c.get("model_s")
        if not (isinstance(ms, _NUM) and not isinstance(ms, bool)
                and math.isfinite(ms) and ms >= 0):
            raise ValueError(
                f"measured_vs_model component {name!r}: model_s={ms!r} "
                "(the analytic side must always be computable)")
        meas = c.get("measured_s")
        if meas is None:
            continue
        if not (isinstance(meas, _NUM) and not isinstance(meas, bool)
                and math.isfinite(meas) and meas >= 0):
            raise ValueError(
                f"measured_vs_model component {name!r}: "
                f"measured_s={meas!r}")
        if ms > 0:
            for f, want in (("ratio", meas / ms), ("abs_err_s", meas - ms)):
                got = c.get(f)
                if not (isinstance(got, _NUM) and not isinstance(got, bool)
                        and math.isfinite(got)
                        and abs(got - want)
                        <= _MVM_REL_TOL * max(abs(want), 1.0)):
                    raise ValueError(
                        f"measured_vs_model component {name!r}: {f}={got!r} "
                        f"inconsistent with measured/model endpoints "
                        f"(expected {want!r}) — the join must be derivable "
                        "from its own record")


def _validate_memory_join(entry, what: str) -> None:
    if not isinstance(entry, dict):
        raise ValueError(f"{what}: memory join entry must be a dict, got "
                         f"{type(entry).__name__}")
    mb = entry.get("model_bytes")
    if not (isinstance(mb, _NUM) and not isinstance(mb, bool)
            and math.isfinite(mb) and mb >= 0):
        raise ValueError(
            f"{what}: model_bytes={mb!r} (the analytic side must always "
            "be a non-negative byte count)")
    meas = entry.get("measured_bytes")
    if meas is None:
        return
    if not (isinstance(meas, _NUM) and not isinstance(meas, bool)
            and math.isfinite(meas) and meas >= 0):
        raise ValueError(f"{what}: measured_bytes={meas!r}")
    if mb > 0:
        want = meas / mb
        got = entry.get("ratio")
        if not (isinstance(got, _NUM) and not isinstance(got, bool)
                and math.isfinite(got)
                and abs(got - want) <= _MVM_REL_TOL * max(abs(want), 1.0)):
            raise ValueError(
                f"{what}: ratio={got!r} inconsistent with measured/model "
                f"endpoints (expected {want!r}) — the join must be "
                "derivable from its own record")


def _validate_memory_block(mem: dict) -> None:
    fams = mem.get("families")
    if not isinstance(fams, dict) or not fams:
        raise ValueError(
            "manifest memory block: missing/empty families dict — the "
            "itemized per-family breakdown IS the block")
    for name, entry in fams.items():
        _validate_memory_join(entry, f"memory family {name!r}")
    for agg in _MEMORY_AGGREGATES:
        if agg not in mem:
            raise ValueError(
                f"manifest memory block missing the {agg!r} aggregate "
                f"join (must carry all of {_MEMORY_AGGREGATES})")
        _validate_memory_join(mem[agg], f"memory aggregate {agg!r}")


def validate_event(ev: dict) -> None:
    """Raise ``ValueError`` unless ``ev`` is a valid event under its own
    declared schema version (``SUPPORTED_VERSIONS`` — v1 streams written
    before the measured-time layer still load)."""
    if not isinstance(ev, dict):
        raise ValueError(f"event must be a dict, got {type(ev).__name__}")
    v = ev.get("v")
    if v not in SUPPORTED_VERSIONS:
        raise ValueError(
            f"event schema version {v!r} not in {SUPPORTED_VERSIONS}")
    kind = ev.get("kind")
    kinds = _KINDS_BY_VERSION[v]
    if kind not in kinds:
        raise ValueError(
            f"unknown event kind {kind!r} for schema v{v} (know {kinds})")
    if not isinstance(ev.get("ts"), _NUM):
        raise ValueError(f"event missing numeric ts: {ev}")
    _check_fields(ev, _REQUIRED[kind], _OPTIONAL[kind], f"{kind} event")
    # wall-clock / index health: a NaN here is a recorder bug, not a run fact
    for f in ("step", "wall_s", "epoch", "batch", "dur_s", "depth"):
        if f in ev and isinstance(ev[f], _NUM) and not math.isfinite(ev[f]):
            raise ValueError(f"{kind} event: non-finite {f}={ev[f]}")
    if kind == "span":
        if ev["dur_s"] < 0:
            raise ValueError(f"span event: negative dur_s={ev['dur_s']}")
        if "depth" in ev and ev["depth"] < 0:
            raise ValueError(f"span event: negative depth={ev['depth']}")
    if kind == "checkpoint":
        for f in ("step", "bytes", "wall_s"):
            if f in ev and isinstance(ev[f], _NUM) and ev[f] < 0:
                raise ValueError(
                    f"checkpoint event: negative {f}={ev[f]}")
    if kind == "resume":
        if "step" in ev and isinstance(ev["step"], _NUM) and ev["step"] < 0:
            raise ValueError(f"resume event: negative step={ev['step']}")
    if kind == "swap":
        for f in ("weights_rev", "checkpoint_step", "wall_s"):
            if f in ev and isinstance(ev[f], _NUM) and (
                    not math.isfinite(ev[f]) or ev[f] < 0):
                raise ValueError(
                    f"swap event: non-finite/negative {f}={ev[f]}")
    if kind == "serve":
        for f in ("queries", "achieved_qps", "latency_p50_ms",
                  "latency_p95_ms", "latency_p99_ms", "window_s",
                  "offered_qps", "batches", "mean_batch",
                  "deadline_flushes", "full_flushes", "latency_budget_ms",
                  "compiles", "wire_rows_per_query", "shed", "shed_factor",
                  "weights_rev", "touched_rows_per_query",
                  "subgraph_flops_per_query"):
            if f in ev and isinstance(ev[f], _NUM) and (
                    not math.isfinite(ev[f]) or ev[f] < 0):
                raise ValueError(
                    f"serve event: non-finite/negative {f}={ev[f]}")
        p50, p95, p99 = (ev["latency_p50_ms"], ev["latency_p95_ms"],
                         ev["latency_p99_ms"])
        if not p50 <= p95 <= p99:
            raise ValueError(
                f"serve event: latency quantiles out of order "
                f"(p50={p50}, p95={p95}, p99={p99}) — a quantile "
                "inversion is a writer bug, not a run fact")
        if "mode" in ev and ev["mode"] not in ("open", "closed"):
            raise ValueError(
                f"serve event: mode={ev['mode']!r} not 'open'/'closed'")
        if "serve_mode" in ev and ev["serve_mode"] not in ("full",
                                                          "subgraph"):
            raise ValueError(
                f"serve event: serve_mode={ev['serve_mode']!r} not "
                "'full'/'subgraph'")
    if kind == "memory":
        for f in ("model_bytes", "measured_peak_bytes", "argument_bytes",
                  "output_bytes", "temp_bytes", "alias_bytes",
                  "generated_code_bytes", "ratio", "budget_bytes"):
            if f in ev and isinstance(ev[f], _NUM) and (
                    not math.isfinite(ev[f]) or ev[f] < 0):
                raise ValueError(
                    f"memory event: non-finite/negative {f}={ev[f]}")
        if "workload" in ev and ev["workload"] not in (
                "train", "serve", "serve_subgraph"):
            raise ValueError(
                f"memory event: workload={ev['workload']!r} not "
                "'train'/'serve'/'serve_subgraph'")
        if "ratio" in ev and isinstance(ev.get("measured_peak_bytes"), _NUM) \
                and ev["model_bytes"] > 0:
            want = ev["measured_peak_bytes"] / ev["model_bytes"]
            if abs(ev["ratio"] - want) > _MVM_REL_TOL * max(abs(want), 1.0):
                raise ValueError(
                    f"memory event: ratio={ev['ratio']!r} inconsistent "
                    f"with measured/model endpoints (expected {want!r})")
    if kind == "step" and isinstance(ev.get("measured_vs_model"), dict):
        _validate_measured_vs_model(ev["measured_vs_model"])
    if kind == "step" and "comm" in ev and ev["comm"] is not None:
        comm = ev["comm"]
        missing = [k for k in COMM_SPLIT_KEYS if k not in comm]
        if missing:
            raise ValueError(
                f"step event comm snapshot missing {missing} "
                "(must be a full CommStats.report())")
        if (comm["exposed_exchanges"] + comm["hidden_exchanges"]
                != comm["exchanges"]):
            raise ValueError(
                "step event comm snapshot violates the hidden/exposed "
                f"split: {comm['exposed_exchanges']} + "
                f"{comm['hidden_exchanges']} != {comm['exchanges']}")
    if kind == "step" and isinstance(ev.get("roofline"), dict):
        roof = ev["roofline"]
        present = [k for k in ROOFLINE_WIRE_KEYS if k in roof]
        if present and len(present) != len(ROOFLINE_WIRE_KEYS):
            missing = [k for k in ROOFLINE_WIRE_KEYS if k not in roof]
            raise ValueError(
                f"step event roofline carries a partial wire split "
                f"(has {present}, missing {missing}) — ship all of "
                "ROOFLINE_WIRE_KEYS or none")
        if present:
            if roof["comm_schedule"] not in COMM_SCHEDULES:
                raise ValueError(
                    f"roofline comm_schedule {roof['comm_schedule']!r} not "
                    f"one of {COMM_SCHEDULES}")
            pe = roof["padding_efficiency"]
            if not (isinstance(pe, _NUM) and 0 <= pe <= 1):
                raise ValueError(
                    f"roofline padding_efficiency {pe!r} outside [0, 1]")
            if roof["halo_bytes_wire_per_step"] \
                    < roof["halo_bytes_true_per_step"]:
                raise ValueError(
                    "roofline wire bytes below true bytes — a schedule "
                    "cannot ship less than the unpadded volume "
                    f"({roof['halo_bytes_wire_per_step']} < "
                    f"{roof['halo_bytes_true_per_step']})")
    if kind == "step" and ev.get("drift") is not None:
        missing = [k for k in DRIFT_KEYS if k not in ev["drift"]]
        if missing:
            raise ValueError(
                f"step event drift block missing {missing} "
                f"(must carry every DRIFT_KEYS field)")
        ra = ev["drift"].get("round_age")
        if ra is not None:
            if not isinstance(ra, list) or any(
                    not (x is None or (isinstance(x, _NUM)
                                       and not isinstance(x, bool)
                                       and x >= 0)) for x in ra):
                raise ValueError(
                    f"drift round_age must be a list of null / non-negative "
                    f"ages (one per ring round), got {ra!r}")
    if kind == "step" and ev.get("replica") is not None:
        rb = ev["replica"]
        missing = [k for k in REPLICA_KEYS if k not in rb]
        if missing:
            raise ValueError(
                f"step event replica block missing {missing} "
                f"(must carry every REPLICA_KEYS field)")
        for f in ("refresh_age", "replica_rows"):
            if not (isinstance(rb[f], _NUM) and not isinstance(rb[f], bool)
                    and math.isfinite(rb[f]) and rb[f] >= 0):
                raise ValueError(
                    f"replica block: non-finite/negative {f}={rb[f]!r}")
        for f in ("replica_drift_rms", "replica_drift_rel"):
            v = rb[f]
            if not isinstance(v, list) or any(
                    not (isinstance(x, _NUM) and not isinstance(x, bool)
                         and math.isfinite(x) and x >= 0) for x in v):
                raise ValueError(
                    f"replica block: {f} must be a list of finite "
                    f"non-negative per-layer norms, got {v!r}")
        if "refresh_kind" in rb and \
                rb["refresh_kind"] not in REPLICA_REFRESH_KINDS:
            raise ValueError(
                f"replica block: refresh_kind={rb['refresh_kind']!r} not "
                f"one of {REPLICA_REFRESH_KINDS}")
        if rb.get("refresh_kind") == "partial":
            rr = rb.get("refresh_rows")
            if not isinstance(rr, list) or any(
                    not (isinstance(x, _NUM) and not isinstance(x, bool)
                         and math.isfinite(x) and x >= 0) for x in rr):
                raise ValueError(
                    "replica block: a partial refresh must carry "
                    f"refresh_rows as per-layer non-negative counts, got "
                    f"{rr!r}")
            w = rb.get("refresh_wire_rows")
            if not (isinstance(w, _NUM) and not isinstance(w, bool)
                    and math.isfinite(w) and w >= 0):
                raise ValueError(
                    "replica block: a partial refresh must carry "
                    f"refresh_wire_rows >= 0, got {w!r}")


def validate_manifest(m: dict) -> None:
    """Raise ``ValueError`` unless ``m`` is a valid manifest under its own
    declared schema version (v1 manifests still load)."""
    if not isinstance(m, dict):
        raise ValueError(f"manifest must be a dict, got {type(m).__name__}")
    if m.get("v") not in SUPPORTED_VERSIONS:
        raise ValueError(
            f"manifest schema version {m.get('v')!r} not in "
            f"{SUPPORTED_VERSIONS}")
    _check_fields(m, _MANIFEST_REQUIRED, _MANIFEST_OPTIONAL, "manifest")
    if isinstance(m.get("memory"), dict):
        _validate_memory_block(m["memory"])
    prof = m.get("profile")
    if isinstance(prof, dict):
        if not isinstance(prof.get("dir"), str):
            raise ValueError(
                f"manifest profile block missing string 'dir': {prof}")
        tf = prof.get("trace_files")
        if tf is not None and not (
                isinstance(tf, list)
                and all(isinstance(e, dict) and isinstance(e.get("path"), str)
                        and isinstance(e.get("bytes"), _NUM)
                        for e in tf)):
            raise ValueError(
                "manifest profile.trace_files must be a list of "
                f"{{path, bytes}} dicts, got {tf!r}")
