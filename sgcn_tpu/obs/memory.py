"""Analytic per-chip HBM footprint — the memory side of model-vs-measured.

One home for every "how many bytes does this (plan, mode, model) put on a
chip" number, derived purely from the ``CommPlan``'s exact padded layout and
the model config — the same discipline ``attribution.step_cost`` applies to
time.  Per array FAMILY, in ``step_cost``'s vocabulary:

  * **params / opt_state** — replicated weights + Adam moments (donated
    every step, so they are resident ONCE despite the functional update);
  * **features** — the owned ``(b, fin)`` feature rows plus the train-only
    labels/valid masks;
  * **plan_arrays / pallas_tiles** — exactly what ``ForwardSetup
    .ship_arrays`` puts on the device (including the GAT int8 narrowing),
    split on the ``ptile_*`` prefix so the Pallas tile layout is its own
    line item;
  * **halo_tables** — the gathered ``(R, f_ℓ)`` receive tables of the dense
    a2a aggregators; ZERO under the ragged ring (receives fold as they
    arrive) and under the Pallas VMEM kernels (the fold runs in VMEM);
  * **wire_buffers** — one exchange's send+receive buffers at the selected
    schedule's padded shapes (``plan.wire_buffer_shapes``) and the wire
    dtype;
  * **halo_carries / replica_carries** — the cross-step stale/ring carries
    and replica tables (``plan.stale_carry_shapes`` /
    ``plan.replica_carry_shapes``, partial-refresh baselines included);
  * **workspace** — layer activations (and their backward mirrors for
    training) at the compute dtype.

The MEASURED side joins this against XLA's own figures:
``measure_compiled`` reads ``compiled.memory_analysis()`` (argument /
output / temp / alias bytes — all PER DEVICE on every backend this repo
runs) and ``reconcile`` produces the per-family ``{model_bytes,
measured_bytes, ratio}`` join that lands in the schema-v6 manifest
``memory`` block and the ``memory`` event kind.  The reconciliation
contract (``MEM_MODEL_TOL``, checked per audit mode by
``analysis/hlo_audit.py::run_memory_audit``):

  * measured peak ≤ model total × tol — the analytic model is the
    residency upper envelope (a program may touch a subset, e.g. the
    sub-graph forward; it may never exceed the envelope by more than the
    band);
  * measured argument bytes ≤ modeled resident-argument bytes — jit may
    prune dead inputs, never invent live ones (reconciles to the byte on
    the exact modes);
  * measured ``alias_size`` ≥ modeled params+opt bytes for training
    programs (params and opt state are always donated and never pruned —
    a stripped ``donate_argnums`` zeroes the alias and fails this
    deterministically), and == 0 for serve programs (no donation by
    design).

Nothing here imports jax at module scope — the CLIs configure the backend
before heavy imports, and the analytic side must be importable first.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

# Reconciliation band of measured-peak vs analytic-total (two-sided, see
# module docstring).  Calibrated on the audit fixture across the supported
# matrix (tests/test_memory_obs.py pins representatives of every family):
# the analytic model counts resident arrays exactly (argument bytes
# reconcile to the byte) but prices XLA's scratch conservatively, so the
# observed peak/total ratios sit in ~[0.25, 1.9] on CPU-compiled programs;
# 2.5 leaves headroom for backend scratch-allocator differences while still
# catching a doubled working set (the dropped-donation failure mode trips
# the alias floor first — deterministically).
MEM_MODEL_TOL = 2.5

# Families whose arrays enter the step program as ARGUMENTS (resident for
# the life of the trainer/engine) — their sum is what `memory_analysis()`'s
# argument_size_in_bytes must reconcile against.
ARGUMENT_FAMILIES = ("params", "opt_state", "features", "plan_arrays",
                     "pallas_tiles", "halo_carries", "replica_carries",
                     "subgraph_batch")
# Families the program materializes while running (XLA temp/output space).
SCRATCH_FAMILIES = ("halo_tables", "wire_buffers", "workspace")
# Donate-class families (jax.buffer_donor markers — the PR-9 donation
# contract): params + opt state always; carries in the stale/replica kinds.
DONATED_FAMILIES = ("params", "opt_state", "halo_carries",
                    "replica_carries")


class MemoryBudgetError(ValueError):
    """A (plan, mode) combination's analytic footprint exceeds the
    ``--memory-budget`` — raised at PLAN time (trainer/engine __init__),
    before any array ships, with the itemized per-family table."""


@dataclass
class MemoryModel:
    """Analytic per-chip HBM footprint of ONE (plan, mode, model) — plan
    arrays are padded identically across chips, so one chip's footprint is
    every chip's footprint."""

    workload: str                 # 'train' | 'serve' | 'serve_subgraph'
    families: dict                # family name -> modeled bytes per chip
    config: dict = field(default_factory=dict)   # scoping identity (n, nnz,
    #                               k, mode flags) — the trend-series key
    overlays: dict = field(default_factory=dict)  # informational figures
    #                               NOT summed into the total (pad_overhead
    #                               would double-count wire_buffers' pads)

    @property
    def total_bytes(self) -> int:
        return int(sum(self.families.values()))

    @property
    def argument_bytes(self) -> int:
        return int(sum(self.families.get(f, 0) for f in ARGUMENT_FAMILIES))

    @property
    def donated_bytes(self) -> int:
        return int(sum(self.families.get(f, 0) for f in DONATED_FAMILIES))

    @property
    def donated_floor_bytes(self) -> int:
        """The donation bytes NO mode may prune: params + opt state (the
        carries can legitimately be absent from the exact-mode program, so
        the audit's alias lower bound uses this floor, not donated_bytes)."""
        return int(self.families.get("params", 0)
                   + self.families.get("opt_state", 0))

    def table(self) -> str:
        """Human-readable itemized breakdown — the loud half of the
        ``--memory-budget`` failure."""
        lines = [f"  {name:<16} {int(b):>14,} B"
                 for name, b in sorted(self.families.items(),
                                       key=lambda kv: -kv[1]) if b]
        lines.append(f"  {'TOTAL':<16} {self.total_bytes:>14,} B")
        for name, b in sorted(self.overlays.items()):
            lines.append(f"  ({name:<14} {int(b):>14,} B — informational, "
                         "not summed)")
        return "\n".join(lines)

    def block(self, measured: dict | None = None) -> dict:
        """The schema-v6 manifest ``memory`` block: per-family
        ``{model_bytes, measured_bytes, ratio}``.  ``measured`` (a
        ``measure_compiled`` dict) fills the aggregate rows XLA itemizes —
        total↔peak, arguments↔argument_size, donated↔alias_size; the
        per-family detail stays model-only (XLA reports aggregates)."""
        fams = {name: {"model_bytes": int(b), "measured_bytes": None,
                       "ratio": None}
                for name, b in self.families.items()}

        def join(model_b, measured_b):
            e = {"model_bytes": int(model_b),
                 "measured_bytes": None if measured_b is None
                 else int(measured_b), "ratio": None}
            if measured_b is not None and model_b > 0:
                e["ratio"] = float(measured_b) / float(model_b)
            return e

        m = measured or {}
        out = {
            "workload": self.workload,
            "config": dict(self.config),
            "families": fams,
            "total": join(self.total_bytes, m.get("peak_bytes")),
            "arguments": join(self.argument_bytes, m.get("argument_bytes")),
            "donated": join(self.donated_bytes, m.get("alias_bytes")),
        }
        if self.overlays:
            out["overlays"] = {k: int(v) for k, v in self.overlays.items()}
        return out


def _prod(shape) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


def model_param_bytes(fin: int, widths, model: str = "gcn") -> int:
    """Replicated parameter bytes from the init formulas (f32 masters):
    GCN one ``(fin, fout)`` Glorot matrix per layer
    (``models/gcn.py::init_gcn_params`` — no bias); GAT adds the two
    ``(fout,)`` attention vectors (``models/gat.py::init_gat_params``)."""
    dims = list(zip([int(fin)] + [int(w) for w in widths][:-1],
                    [int(w) for w in widths]))
    per = [(fi * fo + (2 * fo if model == "gat" else 0)) for fi, fo in dims]
    return 4 * sum(per)


def memory_model(plan, fin: int, widths, *, workload: str = "train",
                 model: str = "gcn", comm_schedule: str = "a2a",
                 compute_dtype: str | None = None,
                 halo_dtype: str | None = None, halo_staleness: int = 0,
                 halo_delta: bool = False, replica_budget: int = 0,
                 refresh_band: float | None = None,
                 setup=None) -> MemoryModel:
    """Build the analytic footprint for one resolved mode.

    ``setup`` is the caller's ``ForwardSetup`` (the trainer and the serve
    engine already hold one — ``resolve_forward_setup`` is NOT re-run here,
    so the model prices exactly the fields/statics the live program ships,
    including the Pallas selection and the GAT int8 narrowing).  When
    ``None`` (standalone analytic use: bench blocks, trend baselines), the
    resolver runs with the given knobs — that path imports jax-adjacent
    modules, so call it only after backend setup."""
    widths = [int(w) for w in widths]
    fin = int(fin)
    if setup is None:
        from ..train.fullbatch import resolve_forward_setup
        setup = resolve_forward_setup(
            plan, fin, widths, model=model, comm_schedule=comm_schedule,
            compute_dtype=compute_dtype, halo_staleness=halo_staleness,
            replica_budget=replica_budget, refresh_band=refresh_band,
            serve_subgraph=(workload == "serve_subgraph"))
    comm_schedule = setup.comm_schedule
    replica_budget = int(setup.replica_budget or 0)
    pallas = "pallas_tb" in setup.fwd_static
    train = workload == "train"
    k, b = int(plan.k), int(plan.b)
    compute_isize = 2 if compute_dtype == "bfloat16" else 4

    families: dict[str, int] = {}
    families["params"] = model_param_bytes(fin, widths, model=model)
    # Adam: count scalar + one mu and one nu tree (optax.adam — the only
    # optimizer the CLIs construct); inference carries no optimizer state
    families["opt_state"] = (2 * families["params"] + 4) if train else 0
    families["features"] = b * fin * 4 + (2 * b * 4 if train else 0)

    plan_b = pallas_b = 0
    for name, arr in setup.ship_arrays(plan).items():
        per_chip = int(arr.nbytes) // k      # stacked (k, ...) per-chip pad
        if name.startswith("ptile_"):
            pallas_b += per_chip
        else:
            plan_b += per_chip
    families["plan_arrays"] = plan_b
    families["pallas_tiles"] = pallas_b

    # per-layer exchanged row widths (f32-lane equivalents) + wire itemsize
    # — the same split CommStats/step_cost price the wire with
    if model == "gat":
        from ..models.gat import gat_exchange_lane_widths
        lane_widths = list(gat_exchange_lane_widths(widths, compute_dtype))
        wire_isize = 4                        # lanes encode the dtype
    else:
        from ..models.gcn import exchange_widths
        lane_widths = list(exchange_widths(fin, widths))
        wire_isize = 2 if (halo_dtype == "bfloat16" or halo_delta
                           or compute_dtype == "bfloat16") else 4

    # halo tables: the dense a2a aggregators gather a (R, f_ℓ) receive
    # table per exchange direction; the ragged ring folds receives as they
    # arrive and the Pallas kernels fold in VMEM — neither materializes it
    ndir = 2 if train else 1                  # forward (+ gradient) halos
    if comm_schedule == "a2a" and not pallas:
        families["halo_tables"] = ndir * sum(
            int(plan.r) * f * compute_isize for f in lane_widths)
    else:
        families["halo_tables"] = 0

    # one exchange's send + receive wire buffers at the schedule's padded
    # shapes and the widest layer's lane width (XLA reuses across layers)
    wire_rows = sum(_prod(s) for s in plan.wire_buffer_shapes(comm_schedule))
    fmax = max(lane_widths) if lane_widths else 0
    families["wire_buffers"] = 2 * wire_rows * fmax * wire_isize

    families["halo_carries"] = 0
    families["replica_carries"] = 0
    if train and halo_staleness:
        shapes = plan.stale_carry_shapes(fin, widths, delta=halo_delta,
                                         comm_schedule=comm_schedule)
        families["halo_carries"] = sum(
            _prod(s) * 4 for shps in shapes.values() for s in shps)
    if train and replica_budget and not halo_staleness:
        shapes = plan.replica_carry_shapes(
            fin, widths, partial=refresh_band is not None)
        families["replica_carries"] = sum(
            _prod(s) * 4 for shps in shapes.values() for s in shps)

    # layer activations (+ backward mirrors when training) — XLA's scratch
    # working set, priced at the compute dtype over every layer width
    npass = 2 if train else 1
    workspace = npass * b * (fin + sum(widths)) * compute_isize
    if model == "gat":
        # the edge-softmax materializes per-slot attention scores over the
        # combined-edge layout (cell slots + spill tail), per direction
        slots = (sum(nb * wb for nb, wb in plan.cell_buckets)
                 + int(plan.ctl or 0)) if plan.cell_buckets is not None else 0
        workspace += npass * slots * max(lane_widths) * compute_isize
    if pallas:
        # the VMEM kernel family's per-tile-block working set (operand
        # windows + accumulator at the tile row count ``pallas_tb``) — in
        # HBM terms an upper envelope: on TPU it lives in VMEM, under the
        # CPU emulation XLA materializes it as temp
        tb = int(setup.fwd_static.get("pallas_tb", 0))
        workspace += npass * tb * (fin + sum(widths)) * compute_isize
    families["workspace"] = workspace

    # pad overhead (informational overlay — the wire_buffers family already
    # contains its pads; summing this too would double-count): the padded
    # wire rows the selected schedule ships beyond the true Σ(λ−1) volume
    true_rows = int(plan.send_counts.sum())
    padded_rows = int(plan.wire_rows_per_exchange(comm_schedule))
    overlays = {"pad_overhead_bytes":
                max(0, padded_rows - true_rows) * fmax * wire_isize}

    config = {
        "workload": workload, "model": model, "n": int(plan.n),
        "nnz": int(plan.nnz.sum()), "k": k, "fin": fin,
        "widths": list(widths), "comm_schedule": comm_schedule,
        "compute_dtype": compute_dtype or "float32",
        "halo_dtype": halo_dtype or "float32",
        "halo_staleness": int(halo_staleness), "halo_delta": bool(halo_delta),
        "replica_budget": replica_budget,
        "partial_refresh": refresh_band is not None, "pallas": pallas,
    }
    return MemoryModel(workload=workload, families=families, config=config,
                       overlays=overlays)


# ---------------------------------------------------------------- measured
def measure_compiled(compiled) -> dict | None:
    """Read ``compiled.memory_analysis()`` into a plain per-device byte
    dict; ``None`` when the backend does not expose the analysis (the
    join is then simply absent — never fabricated)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:                  # noqa: BLE001 — backend-optional API
        return None
    if ma is None:
        return None
    try:
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        gen = int(ma.generated_code_size_in_bytes)
    except AttributeError:
        return None
    # donated buffers appear in BOTH argument and output totals; peak
    # residency counts them once
    return {"argument_bytes": arg, "output_bytes": out, "temp_bytes": tmp,
            "alias_bytes": alias, "generated_code_bytes": gen,
            "peak_bytes": arg + out + tmp - alias}


def reconcile(model: MemoryModel, measured: dict | None,
              tol: float = MEM_MODEL_TOL) -> dict:
    """Join one compiled program's measured figures against the analytic
    model; returns ``{ok, violations, block}`` where ``block`` is the
    manifest-shaped per-family join and ``violations`` lists human-readable
    contract breaches (the ``memory-model`` audit rule's payload)."""
    violations: list[str] = []
    if measured is not None:
        peak, total = measured["peak_bytes"], model.total_bytes
        if total > 0 and peak > total * tol:
            violations.append(
                f"measured peak {peak:,} B exceeds the analytic total "
                f"{total:,} B x tol {tol} (ratio {peak / total:.2f}) — "
                "the model is the residency upper envelope; a program "
                "above it holds buffers the model does not know about "
                "(e.g. an un-donated double-buffered update)")
        # the program's arguments are a SUBSET of the modeled resident
        # arrays (jit prunes dead inputs; it never invents live ones) —
        # this side reconciles to the byte on the exact modes, so only a
        # small absolute slack for step-counter scalars is allowed
        arg_model = model.argument_bytes
        if measured["argument_bytes"] > arg_model + 256:
            violations.append(
                f"measured argument bytes {measured['argument_bytes']:,} B "
                f"exceed the modeled resident arguments {arg_model:,} B — "
                "the program takes inputs the footprint model does not "
                "price")
        floor = model.donated_floor_bytes
        if model.workload == "train":
            if measured["alias_bytes"] < floor:
                violations.append(
                    f"measured alias {measured['alias_bytes']:,} B below "
                    f"the donated params+opt floor {floor:,} B — "
                    "donate_argnums dropped; the step double-buffers "
                    "every update")
        elif measured["alias_bytes"] != 0:
            violations.append(
                f"serve program aliases {measured['alias_bytes']:,} B — "
                "engine buffers are reused across batches and must not "
                "be donated")
    return {"ok": not violations, "violations": violations,
            "block": model.block(measured)}


# ------------------------------------------------------------------ budget
def check_memory_budget(model: MemoryModel, budget_bytes: int | None,
                        what: str = "this run") -> None:
    """Raise ``MemoryBudgetError`` when the analytic footprint exceeds the
    budget — called at plan time (trainer/engine ``__init__``), before any
    array ships, so an over-budget (plan, mode) fails in milliseconds with
    the itemized table instead of OOMing mid-compile."""
    if budget_bytes is None:
        return
    budget_bytes = int(budget_bytes)
    if budget_bytes <= 0:
        raise ValueError(f"--memory-budget must be > 0 bytes, got "
                         f"{budget_bytes}")
    total = model.total_bytes
    if total > budget_bytes:
        raise MemoryBudgetError(
            f"{what}: analytic per-chip HBM footprint {total:,} B exceeds "
            f"--memory-budget {budget_bytes:,} B "
            f"(workload={model.workload}) — per-family breakdown:\n"
            f"{model.table()}")


_SUFFIX = {"K": 1024, "M": 1024 ** 2, "G": 1024 ** 3, "T": 1024 ** 4}


def parse_bytes(text: str) -> int:
    """Parse a ``--memory-budget`` value: plain bytes or a K/M/G/T binary
    suffix (``16G`` = 16 GiB)."""
    s = str(text).strip().upper().removesuffix("B")
    mult = 1
    if s and s[-1] in _SUFFIX:
        mult, s = _SUFFIX[s[-1]], s[:-1]
    try:
        val = float(s)
    except ValueError:
        raise ValueError(
            f"--memory-budget {text!r} is not BYTES or a K/M/G/T-suffixed "
            "size") from None
    if not math.isfinite(val) or val <= 0:
        raise ValueError(f"--memory-budget {text!r} must be positive")
    return int(val * mult)
