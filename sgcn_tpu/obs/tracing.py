"""Measured-time profiling layer — spans, trace parsing, model reconciliation.

Every perf gauge in ``attribution.py`` is ANALYTIC: derived from the
``CommPlan``, it says how fast a step *should* be.  This module is the
measured-time source of truth next to it, in two halves:

**Span API** (``SpanTimer`` / ``emit_span`` / ``scoped_span``) — named,
optionally nested wall-clock spans with ``block_until_ready`` sync points.
It generalizes ``utils.timers.PhaseTimer`` (every span IS a phase: the timer
keeps the CAGNET-vocabulary self-time breakdown, the span additionally
becomes a schema-v2 ``span`` event in the run's ``events.jsonl``), so
measured phase times land in the SAME stream as the analytic gauges.  Both
trainers thread their step/epoch paths through it, and ``bench.py``'s A/B
children emit arm-level spans through the env-gated ``emit_span`` (span the
arms, never the steps inside a timed region — instrumentation inside a
differential-timing loop would perturb the very number being measured).

**Trace parser** (``find_trace_files`` / ``summarize_trace``) — parses the
trace-event JSON ``jax.profiler.trace`` writes (``--profile DIR`` →
``DIR/plugins/profile/<run>/*.trace.json.gz``), classifies device ops into
the attribution vocabulary (spmm / dense / exchange / collective-wait /
other; table below and in ``docs/observability.md``) and computes MEASURED
overlap fraction, exposed-comm time and per-device skew (the straggler
gauge) — the quantities the analytic model only predicts.

**Reconciliation** (``measured_vs_model_block``) — joins a step's measured
span times against ``attribution.step_cost`` into the per-step
``measured_vs_model`` block (ratio + absolute error per component,
schema-validated), so a mispredicting cost model is a visible gauge instead
of a footnote.  ``scripts/obs_report.py`` renders both the per-step blocks
and the post-hoc trace join.

Nothing here imports jax at module scope (CLIs configure the backend before
heavy imports).
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
import time
from dataclasses import dataclass, field

# NOTE: no module-scope import of ..utils.timers — it imports jax, and the
# trace parser half of this module must stay importable in a jax-free
# context (SpanTimer imports PhaseTimer lazily)

# ---------------------------------------------------------------- span API


@dataclass
class Span:
    """Handle yielded by ``SpanTimer.span`` — filled at exit."""

    name: str
    parent: str | None = None
    depth: int = 0
    dur_s: float = 0.0


class SpanTimer:
    """Nested measured spans over a shared ``PhaseTimer``.

    One instance per trainer: ``timer`` keeps the phase breakdown (self
    time per name — the ``PhaseTimer`` nesting contract), and, when a
    ``RunRecorder`` is attached, every span exit appends one validated
    ``span`` event.  Without a recorder the only cost is the timer's two
    ``perf_counter`` reads — the default hot path stays un-instrumented.
    """

    def __init__(self, timer=None, recorder=None):
        from ..utils.timers import PhaseTimer

        self.timer = timer if timer is not None else PhaseTimer()
        self.recorder = recorder
        self._stack: list[str] = []

    @contextlib.contextmanager
    def span(self, name: str, sync=None, step: int | None = None,
             phase: str | None = None):
        """Time a named span (nesting under any open span).  ``sync`` is the
        ``PhaseTimer.phase`` sync callable — evaluated after the body, so
        the span duration includes the device-side completion it blocks on.
        Yields a ``Span`` whose ``dur_s`` is valid after exit."""
        sp = Span(name=name,
                  parent=self._stack[-1] if self._stack else None,
                  depth=len(self._stack))
        self._stack.append(name)
        t0 = time.perf_counter()
        try:
            with self.timer.phase(name, sync=sync):
                yield sp
        finally:
            sp.dur_s = time.perf_counter() - t0
            self._stack.pop()
            if self.recorder is not None:
                kw = {}
                if step is not None:
                    kw["step"] = int(step)
                if phase is not None:
                    kw["phase"] = str(phase)
                self.recorder.record_span(
                    name=sp.name, dur_s=sp.dur_s, parent=sp.parent,
                    depth=sp.depth, **kw)


def emit_span(name: str, dur_s: float, parent: str | None = None,
              depth: int = 0, phase: str | None = None,
              detail: str | None = None) -> None:
    """Append one validated ``span`` event to
    ``$SGCN_METRICS_OUT/events.jsonl`` — the out-of-recorder span emitter
    (``recorder.append_env_event``, the same path ``heartbeat`` rides):
    ``bench.py`` and its A/B child processes inherit the env var, so their
    arm-level measured times land in the parent run's event stream.  No-op
    without the env var; best-effort by design (a full disk must not kill
    the bench it is observing)."""
    if not os.environ.get("SGCN_METRICS_OUT"):
        return
    from . import schema
    from .recorder import append_env_event
    ev = {"v": schema.SCHEMA_VERSION, "ts": time.time(), "kind": "span",
          "name": str(name), "dur_s": float(dur_s), "depth": int(depth),
          "pid": os.getpid()}
    if parent is not None:
        ev["parent"] = str(parent)
    if phase is not None:
        ev["phase"] = str(phase)
    if detail is not None:
        ev["detail"] = str(detail)
    append_env_event(schema.EVENTS_NAME, ev)


@contextlib.contextmanager
def scoped_span(name: str, phase: str | None = None,
                detail: str | None = None):
    """Time a region and ``emit_span`` it at exit (env-gated no-op without
    ``$SGCN_METRICS_OUT``) — the bench-side span form."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        emit_span(name, time.perf_counter() - t0, phase=phase, detail=detail)


# ------------------------------------------------------------ trace parser

# The ONE collective-op name alternation both comm classes build on: the
# `collective_wait` pattern matches these names' `-done` halves and the
# `exchange` pattern the ops themselves, so a new collective (a ragged
# all-to-all lowering, say) added here lands in BOTH — two hand-kept copies
# would silently diverge and skew comm_s with no test failing.
_COLLECTIVES = (
    r"all-to-all|all_to_all|collective-permute|collective_permute|"
    r"ppermute|all-reduce|all_reduce|all-gather|all_gather|"
    r"reduce-scatter|reduce_scatter")

# Ordered op-classification table (first match wins, case-insensitive).
# The vocabulary is attribution.py's: spmm (the gather/scatter aggregation
# streams), dense (projections), exchange (the halo transport collectives),
# collective_wait (blocked-on-peer time), other (remaining device compute —
# copies, broadcasts, elementwise fusions).  docs/observability.md carries
# the human-readable form of this table; this tuple is the executable one.
TRACE_OP_CLASSES: tuple = (
    # only COLLECTIVE -done ops are comm wait: a bare `(^|-)done` would also
    # catch XLA's async `copy-done` (host/device copies) and inflate comm_s
    ("collective_wait", re.compile(
        r"rendezvous|^wait\b|^wait:|"
        r"(" + _COLLECTIVES + r"|send|recv)[-.]done", re.I)),
    # paired point-to-point transfers (multi-host / pipelined lowerings)
    # count as exchange too — booking `send.3` as compute would understate
    # comm_s and overstate the measured overlap gauge
    ("exchange", re.compile(
        _COLLECTIVES + r"|\bsend\b|\brecv\b", re.I)),
    # `convolution`, not `conv`: a bare `conv` would classify every bf16
    # `convert` cast as dense in a codebase with no convolutions at all
    ("dense", re.compile(
        r"\bdot\b|^dot|dot_general|gemm|matmul|convolution", re.I)),
    ("spmm", re.compile(
        r"gather|scatter|select_slice|dynamic.?slice|dynamic.?update|"
        r"segment", re.I)),
)

# events that are host/runtime scaffolding, not device op time
_TRACE_SKIP = re.compile(
    r"^\$|^end: |^ThreadpoolListener|^ThunkExecutor|^PjitFunction|"
    r"^XlaModule|^Pjit|^jit[_(]|^BufferAssignment|^TransferManager|"
    r"^Stream|^Execute$|^RunExecutable|^CopyToDevice|^CopyFromDevice",
    re.I)

TRACE_CLASSES = ("spmm", "dense", "exchange", "collective_wait", "other")


def classify_op(name: str) -> str | None:
    """Map one trace-event name into the attribution vocabulary; ``None``
    for host/runtime scaffolding that is not device op time."""
    if not name or _TRACE_SKIP.search(name):
        return None
    for cls, pat in TRACE_OP_CLASSES:
        if pat.search(name):
            return cls
    return "other"


def find_trace_files(profile_dir: str) -> list[dict]:
    """Locate the trace-event JSON files under a ``--profile`` directory
    (``plugins/profile/<run>/*.trace.json.gz``), newest run first.
    Returns ``[{path, bytes}]`` — the shape the manifest ``profile`` block
    records, so ``obs_report`` can find the trace from the run dir alone."""
    hits = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=lambda p: os.path.getmtime(p), reverse=True)
    return [{"path": os.path.abspath(p), "bytes": os.path.getsize(p)}
            for p in hits]


def _interval_union(iv: list) -> list:
    """Merge [start, end) intervals into a disjoint sorted union."""
    if not iv:
        return []
    iv = sorted(iv)
    out = [list(iv[0])]
    for s, e in iv[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _overlap_len(a: list, b: list) -> float:
    """Total intersection length of two DISJOINT SORTED interval unions."""
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if s < e:
            total += e - s
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


@dataclass
class TraceSummary:
    """Measured per-device attribution of one profiler trace."""

    path: str
    n_events: int
    devices: dict = field(default_factory=dict)   # name -> per-class seconds
    classes: dict = field(default_factory=dict)   # per-class totals (s)
    # comm WALL-CLOCK: per-pid interval union of the exchange +
    # collective_wait ops, summed over pids — ≤ the per-class op-second
    # sums whenever async collectives overlap each other on one device
    # (the same de-overlapping the exposed/hidden split needs)
    comm_s: float = 0.0
    exposed_comm_s: float = 0.0    # comm not covered by concurrent compute
    measured_overlap_frac: float | None = None    # 1 − exposed/comm
    skew: dict | None = None       # straggler gauge (multi-device only)

    def per_step(self, nsteps: int) -> dict:
        """Average the trace totals over ``nsteps`` optimizer steps — the
        per-step measured figures to join against ``step_cost``.

        ``nsteps`` must count EVERY optimizer step the trace covers — the
        recorded step events do (the trainer records warmup steps too), so
        ``len(log.steps())`` is the right denominator for a ``--profile``
        run.  Anything else executing inside the profiled region that is
        not a recorded step (``evaluate()`` forward passes, first-dispatch
        autotuning) still lands in the numerator, so these per-step figures
        are UPPER bounds there — ``obs_report`` prints the eval count next
        to the join when a run carries both."""
        n = max(int(nsteps), 1)
        out = {f"{c}_s": self.classes.get(c, 0.0) / n
               for c in TRACE_CLASSES}
        out["comm_s"] = self.comm_s / n
        out["exposed_comm_s"] = self.exposed_comm_s / n
        return out


def summarize_trace(path: str) -> TraceSummary:
    """Parse one ``*.trace.json.gz`` (or plain ``.json``) trace-event file
    into measured per-device op-class times, overlap/exposed-comm figures
    and the straggler gauge.

    Device attribution: trace processes (``pid``) map to devices on TPU
    (one pid per ``/device:TPU:n``); the CPU backend runs every virtual
    device in one ``/host:CPU`` pid, so per-device skew is only emitted
    when the trace distinguishes more than one device-like pid.  When any
    ``/device:…`` pid exists, host/runtime pids are dropped entirely —
    their wall time is not device op time and must not skew the gauges.  Overlap is
    computed per pid: comm intervals (exchange + collective-wait) minus
    their intersection with the union of concurrent compute intervals
    (spmm/dense/other, any thread of the pid) — comm time under compute is
    hidden, the remainder is EXPOSED comm sitting on the critical path."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", [])
    proc_names: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name",
                                                             str(e.get("pid")))
    per_dev: dict = {}
    intervals: dict = {}           # pid -> {"comm": [...], "compute": [...]}
    pid_counts: dict = {}          # per pid, so the filter below keeps
    for e in events:               # n_events consistent with the gauges
        if e.get("ph") != "X":
            continue
        cls = classify_op(e.get("name", ""))
        if cls is None:
            continue
        dur = float(e.get("dur", 0.0)) * 1e-6      # trace units: µs
        ts = float(e.get("ts", 0.0)) * 1e-6
        pid = e.get("pid")
        dev = per_dev.setdefault(pid, {c: 0.0 for c in TRACE_CLASSES})
        dev[cls] += dur
        bucket = intervals.setdefault(pid, {"comm": [], "compute": []})
        bucket["comm" if cls in ("exchange", "collective_wait")
               else "compute"].append((ts, ts + dur))
        pid_counts[pid] = pid_counts.get(pid, 0) + 1

    # a real TPU profile carries host/runtime pids next to the device pids
    # (enqueue threads, transfer spans) — when the trace distinguishes any
    # `/device:…` pid, only those are devices: host wall time must not
    # inflate class totals, and a host pid must never be elected straggler.
    # A CPU-backend trace has no `/device:` pid at all, so every pid (the
    # single `/host:CPU`) stays in — its op classes ARE the measurement.
    dev_pids = [p for p in per_dev
                if "/device:" in proc_names.get(p, str(p)).lower()]
    if dev_pids:
        per_dev = {p: per_dev[p] for p in dev_pids}
    n_classified = sum(pid_counts[p] for p in per_dev)

    classes = {c: sum(d[c] for d in per_dev.values()) for c in TRACE_CLASSES}
    comm_s = exposed_s = 0.0
    devices = {}
    busies = {}
    for pid, dev in per_dev.items():
        name = proc_names.get(pid, str(pid))
        if name in devices:
            # distinct pids can share process_name metadata (merged
            # multi-host captures) — collapsing them would shrink the
            # straggler denominator and overwrite per-class seconds
            name = f"{name} [pid {pid}]"
        busy_union = _interval_union(intervals[pid]["comm"]
                                     + intervals[pid]["compute"])
        busy = sum(e - s for s, e in busy_union)
        compute_union = _interval_union(intervals[pid]["compute"])
        comm_union = _interval_union(intervals[pid]["comm"])
        cm = sum(e - s for s, e in comm_union)
        hidden = _overlap_len(comm_union, compute_union)
        comm_s += cm
        exposed_s += max(0.0, cm - hidden)
        devices[name] = dict(dev, busy_s=busy)
        busies[name] = busy
    skew = None
    if len(busies) > 1:
        mean = sum(busies.values()) / len(busies)
        straggler = max(busies, key=busies.get)
        skew = {"busy_max_over_mean": (busies[straggler] / mean
                                       if mean > 0 else 1.0),
                "straggler": straggler}
    overlap = None
    if comm_s > 0:
        overlap = 1.0 - exposed_s / comm_s
    return TraceSummary(path=path, n_events=n_classified, devices=devices,
                        classes=classes, comm_s=comm_s,
                        exposed_comm_s=exposed_s,
                        measured_overlap_frac=overlap, skew=skew)


def trace_path_for_run(manifest: dict, rundir: str | None = None) -> str | None:
    """Resolve the run's trace-event file from its manifest ``profile``
    block (falling back to re-globbing the recorded profile dir, then the
    run directory itself) — how ``obs_report`` finds the trace from the run
    directory alone.  The manifest records ABSOLUTE paths from the machine
    the run executed on; for a relocated run dir (the normal way a TPU run
    is inspected) those are stale, so the last resort globs ``rundir`` —
    copying the profile tree into the run dir makes the claim literally
    true anywhere."""
    prof = manifest.get("profile") if isinstance(manifest, dict) else None
    if isinstance(prof, dict):
        for entry in prof.get("trace_files") or []:
            p = entry.get("path")
            if p and os.path.exists(p):
                return p
        d = prof.get("dir")
        if d and os.path.isdir(d):
            hits = find_trace_files(d)
            if hits:
                return hits[0]["path"]
    if rundir and os.path.isdir(rundir):
        hits = find_trace_files(rundir)
        if hits:
            return hits[0]["path"]
    return None


# ----------------------------------------------------------- reconciliation

def _sig(x: float, n: int = 6) -> float:
    return float(f"{x:.{n}g}")


def _mvm_entry(model_s: float, measured_s: float | None) -> dict:
    """One measured_vs_model component: model/measured endpoints plus the
    derived join (ratio + absolute error) whenever both are present."""
    d = {"model_s": _sig(model_s)}
    if measured_s is None:
        d["measured_s"] = None
        return d
    d["measured_s"] = _sig(float(measured_s))
    if d["model_s"] > 0:
        d["ratio"] = d["measured_s"] / d["model_s"]
        d["abs_err_s"] = d["measured_s"] - d["model_s"]
    return d


def exchange_join(trace_per_step: dict, exposed_halo_bytes: float) -> dict:
    """The ``exchange`` component of ``measured_vs_model``: measured
    per-step EXPOSED comm seconds (``TraceSummary.per_step``'s
    ``exposed_comm_s`` — comm minus what ran under concurrent compute)
    joined against the analytic exposed wire bytes serialized at the
    nominal ICI rate (``exposed_halo_bytes / ICI_CEILING_GBS`` — the
    roofline's ``exposed_halo_bytes`` gauge restated in seconds, exactly
    how ``gather_stream`` restates ``stream_ceiling_frac``).  Both sides
    are exposed figures — joining the measured TOTAL collective seconds
    here would conflate overlap (hidden comm) with cost-model error — and
    both are exchange-shaped: ``exposed_comm_frac`` is a fraction of the
    step's EXCHANGES, not of its wall, so an earlier ``frac × wall_s``
    model side equated "all exchanges exposed" with "the whole step is
    comm" and reported a 1/comm-share ratio as model error on every exact
    run.  The ONE implementation of this join — ``measured_vs_model_block``
    embeds it per step, ``scripts/obs_report.py`` renders it post-hoc over
    the whole-run trace."""
    from .attribution import ICI_CEILING_GBS

    return _mvm_entry(
        max(float(exposed_halo_bytes), 0.0) / (ICI_CEILING_GBS * 1e9),
        trace_per_step.get("exposed_comm_s", 0.0))


def measured_vs_model_block(cost, wall_s: float,
                            phase_total_s: float | None = None,
                            trace_per_step: dict | None = None,
                            exposed_halo_bytes: float | None = None) -> dict:
    """Join measured step time against the analytic ``StepCostModel`` into
    the schema-validated per-step ``measured_vs_model`` block.

    Components (each ``{model_s, measured_s, ratio, abs_err_s}``; ratio =
    measured/model — >1 means the step ran SLOWER than the analytic model
    predicts, the drift gauge for a stale cost model):

      * ``gather_stream`` — model: ``gather_bytes / STREAM_CEILING_GBS``
        (the analytic gather-bound step time — the workload's roofline
        axis); measured: the step's span-measured wall time.  The ratio is
        exactly ``1 / stream_ceiling_frac`` — the same reconciliation the
        roofline block states as a fraction, restated as seconds so model
        error is readable as absolute time.
      * ``exchange`` (only when a parsed profiler trace is joined —
        ``trace_per_step`` from ``TraceSummary.per_step`` plus the
        analytic ``exposed_halo_bytes`` from the roofline block): measured
        per-step EXPOSED comm seconds (``exposed_comm_s``) against the
        analytic exposed wire bytes serialized at the nominal ICI rate
        (``exposed_halo_bytes / ICI_CEILING_GBS``) — exposed vs exposed
        and both exchange-shaped, so the ratio reads as cost-model error,
        not overlap.  The other trace classes are NOT joined here
        — the analytic model predicts no per-class seconds for them
        (bytes and FLOPs, not times); ``obs_report`` renders their
        measured figures next to this block instead.

    ``phase_total_s`` defaults to ``wall_s`` — the span-measured total this
    block anchors on must reconcile with ``PhaseTimer.report()`` (tier-1
    pins <1% on the cora fixture)."""
    from .attribution import STREAM_CEILING_GBS

    wall_s = float(wall_s)
    comps = {
        "gather_stream": _mvm_entry(
            cost.gather_bytes / (STREAM_CEILING_GBS * 1e9), wall_s),
    }
    if trace_per_step is not None and exposed_halo_bytes is not None:
        comps["exchange"] = exchange_join(trace_per_step, exposed_halo_bytes)
    return {
        "phase_total_s": _sig(wall_s if phase_total_s is None
                              else float(phase_total_s)),
        "components": comps,
    }
