"""Analytic per-step cost attribution — plan-derived FLOPs, bytes, roofline.

One home for every "how fast SHOULD this step be" number, derived from the
``CommPlan``'s exact padded layout at the per-layer exchanged widths
(``models.gcn.exchange_widths`` — the trainer's project-first rule), so the
recorder, ``bench.py`` and ``scripts/obs_report.py`` all attribute measured
step time against the SAME model.  Previously ``bench.py`` hand-rolled its
roofline fields; it now imports them from here.

Three quantities per training step:

  * **gather bytes** — what the row gathers move (the workload is
    gather-bound on v5e; ``BASELINE.md`` microbenchmarks put the achievable
    stream rate at ``STREAM_CEILING_GBS``).  ``achieved_gather_GBs /
    STREAM_CEILING_GBS`` is the MFU-analogue for this workload.
  * **FLOPs** — per-layer SpMM (2·nnz·f) and dense projection (2·B·fin·fout)
    at the layer's true aggregation width, forward + backward (backward ≈
    2× the dense forward — dX and dW — plus one more SpMM pass under the
    symmetric custom VJP).
  * **halo bytes** — TWO figures per exchange (the padded-vs-true split of
    docs/comm_schedule.md): ``halo_bytes_true`` from the plan's predicted
    send volume (== Σ(λ−1), the connectivity metric the partitioner
    optimizes) and ``halo_bytes_wire`` from what the SELECTED schedule
    actually ships — ``k²·S·f·itemsize`` for the dense a2a,
    ``Σ_d k·S_d·f·itemsize`` for the ragged ppermute ring — at the wire
    dtype, per step from the exchange count (2·L: forward + backward),
    with a PER-DIRECTION itemsize split when the two directions ride
    different dtypes (the ``--halo-delta`` feature wire vs the
    ``--halo-dtype`` gradient wire — see ``step_cost``).
    The exposed-comm attribution charges wire bytes (what crosses ICI),
    never the under-count the true volume would give on a padded schedule.

Nothing here imports jax at module scope — the CLIs configure the backend
before heavy imports, and this module must be importable first.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# Measured achievable HBM stream rate through XLA on this chip (BASELINE.md
# microbenchmarks: 655 GB/s = 80% of nominal); the denominator of the
# gather-utilization figure — the MFU-analogue for this gather-bound workload.
STREAM_CEILING_GBS = 655.0

# Nominal v5e per-link ICI rate (400 Gbps/link, each direction) — the
# serialization rate one wire byte pays in the analytic exchange model.
# Unlike STREAM_CEILING_GBS this is a DATASHEET figure, not a measured one:
# the virtual CPU mesh has no ICI to microbenchmark, and the
# measured_vs_model `exchange` ratio gauge exists precisely to show how far
# a real mesh lands from it.
ICI_CEILING_GBS = 50.0


def _exchange_gather_rows(plan, comm_schedule: str = "a2a") -> int:
    """Per-chip rows the SELECTED transport's exchange machinery gathers
    per exchange direction.  The dense a2a gathers the whole padded
    ``(k, S)`` send buffer and then the ``R``-row halo table out of the
    receive buffer; the ragged ring gathers only its per-round send
    buffers (``Σ_d S_d`` rows) and SCATTERS receives (``.set`` — no
    halo-table gather), so charging the dense figure to a ragged run would
    overstate the stream by exactly the padded rows the ring deletes."""
    if comm_schedule == "ragged":
        sizes = (plan.rr_sizes if plan.rr_sizes is not None
                 else plan.ragged_round_sizes())
        return int(sum(sizes))
    return int(plan.k * plan.s + plan.r)


def gather_bytes_per_epoch(plan, fin: int, widths,
                           itemsize: int = 4,
                           comm_schedule: str = "a2a") -> int:
    """Bytes the epoch's row gathers move (fwd + symmetric bwd), from the
    plan's padded layout — the numerator of the roofline figure.

    Counts the gather streams only (ELL slots, hub tails, halo-src edges,
    and the selected transport's exchange gathers —
    ``_exchange_gather_rows``), at the aggregation width of each layer
    (``models/gcn.py::exchange_widths`` — the trainer's project-first
    rule).  Accumulate-side traffic (~30% more, BASELINE.md utilization
    accounting) is deliberately excluded: the metric is 'how fast are the
    gathers running', matching the measured 655 GB/s stream ceiling
    denominator.
    """
    from ..models.gcn import exchange_widths
    ell_slots = sum(nb * wb for nb, wb in plan.ell_buckets)
    rows = ell_slots + plan.tl          # local ELL + tail
    rows += plan.eh                     # halo-src edge gathers
    rows += _exchange_gather_rows(plan, comm_schedule)
    return int(2 * rows * itemsize * sum(exchange_widths(fin, widths)))


@dataclass
class StepCostModel:
    """Analytic cost of ONE full-batch training step on one chip.

    Per-chip figures (plan arrays are padded identically across chips, so
    one chip's program is every chip's program; multiply by ``k`` for
    global totals — except ``halo_send_rows``, which is already the global
    per-exchange row count Σ(λ−1))."""

    nlayers: int
    widths: list            # exchanged/aggregated width per layer (lanes)
    spmm_flops: int         # fwd SpMM FLOPs per chip (all layers)
    dense_flops: int        # fwd dense-projection FLOPs per chip
    step_flops: int         # fwd+bwd total per chip (2·spmm + 3·dense)
    gather_bytes: int       # fwd+bwd gather-stream bytes per chip
    halo_send_rows: int     # global TRUE boundary rows per exchange (Σ(λ−1))
    halo_bytes_per_exchange: int   # global TRUE bytes per exchange (legacy
    #                                name; == the Σ(λ−1) volume)
    halo_bytes_per_step: int       # 2·L exchanges per training step (true)
    per_layer: list = field(default_factory=list)  # [{width, spmm_flops,
    #   dense_flops, halo_bytes, halo_bytes_true, halo_bytes_wire}] — the
    #   attribution table obs_report renders
    # padded-vs-true split of the selected exchange schedule
    comm_schedule: str = "a2a"
    halo_wire_rows: int = 0        # padded rows per exchange on the wire
    padding_efficiency: float = 1.0  # halo_send_rows / halo_wire_rows
    halo_bytes_true_per_step: int = 0   # == halo_bytes_per_step (explicit)
    halo_bytes_wire_per_step: int = 0   # what the schedule ships per step


def step_cost(plan, fin: int, widths, compute_dtype: str | None = None,
              wire_itemsize=None,
              comm_schedule: str = "a2a",
              model: str = "gcn",
              replica: bool = False) -> StepCostModel:
    """Build the cost model for one (plan, layer-stack) pair.

    ``compute_dtype='bfloat16'`` halves the gather/wire itemsize (the
    packed bf16 path); ``wire_itemsize`` overrides the wire bytes alone
    (the ``--halo-dtype bfloat16`` wire-only lever).  It takes either one
    int for BOTH exchange directions, or a ``(fwd, bwd)`` pair (entries
    ``None`` = the compute itemsize) — the PER-STEP itemsize split: under
    ``--halo-delta`` the feature wire is bf16 on stale steps and full f32
    on re-base sync steps while the gradient wire follows ``--halo-dtype``,
    so the trainer builds one cost model per step kind and a single
    blended number would misstate both directions.  ``comm_schedule``
    selects the wire-byte model: the plan's TRUE volume (Σ(λ−1)) is
    schedule-independent, but the shipped bytes are the schedule's padded
    buffer — ``plan.wire_rows_per_exchange(schedule)``.

    ``model='gat'`` switches every per-layer width to the GAT exchange's
    REAL table lanes (``models.gat.gat_exchange_lane_widths``: fused
    ``fout+1``, packed-bf16 ``fout/2+1``, split pair ``fout+1`` across its
    buffers — all in f32-lane equivalents, so the itemsize stays 4 and
    narrow dtypes are encoded in the lane count), the SpMM term to the
    combined-edge num/den slot passes (one fused gather-accumulate per
    combined slot and tail edge, at the table width), and the gather-stream
    model to the combined layout (slot + tail table gathers plus the
    exchange's send/halo gathers).  Wire accounting is therefore the same
    figure CommStats' lane-weighted gauges report — the parity the
    reconciliation smokes pin (``wire_itemsize`` is ignored for GAT; its
    wire levers are the table forms themselves).

    ``replica=True`` prices the hot-halo-replication REPLICA step
    (``--replica-budget``, docs/replication.md): the exchange ships the
    shrunken ``nrep_*`` layout, so BOTH the true volume (replicated rows
    genuinely leave the exchange — ``plan.replica_send_volume``) and the
    wire rows (``plan.wire_rows_per_exchange(..., replica=True)``)
    shrink; refresh steps use the default full model.  GCN only (the
    trainer gates replication to it)."""
    if model == "gat":
        from ..models.gat import gat_exchange_lane_widths
        plan.ensure_cell()
        fs = gat_exchange_lane_widths(list(widths), compute_dtype)
        itemsize = 4                    # lanes are f32 equivalents
        wire_f = wire_bwd = 4
        # combined-edge work per layer: bucketed slots + hub tail
        nnz = sum(nb * wb for nb, wb in plan.cell_buckets) + int(plan.ctl)
    else:
        from ..models.gcn import exchange_widths
        itemsize = 2 if compute_dtype == "bfloat16" else 4
        if wire_itemsize is None:
            wire_f = wire_bwd = itemsize
        elif isinstance(wire_itemsize, (tuple, list)):
            wire_f, wire_bwd = (itemsize if x is None else int(x)
                                for x in wire_itemsize)
        else:
            wire_f = wire_bwd = int(wire_itemsize)
        fs = exchange_widths(fin, list(widths))
        nnz = int(plan.nnz.max()) if plan.nnz.size else 0
    dims = list(zip([fin] + list(widths)[:-1], widths))
    b = plan.b
    if replica:
        if model == "gat":
            raise ValueError("replica pricing is a GCN-trainer lever")
        send_rows = int(plan.replica_send_volume.sum())
        wire_rows = int(plan.wire_rows_per_exchange(comm_schedule,
                                                    replica=True))
    else:
        send_rows = int(plan.predicted_send_volume.sum())
        wire_rows = int(plan.wire_rows_per_exchange(comm_schedule))

    # per-layer bytes are PER EXCHANGE at the mean of the two directions'
    # itemsizes, so 2L × per-layer == the per-step totals exactly (the
    # split values are 2/4, whose sum is always even)
    per_layer, spmm_f, dense_f = [], 0, 0
    true_step = wire_step = 0
    for (fi, fo), w in zip(dims, fs):
        lf_spmm = 2 * nnz * w           # one multiply-add per (edge, lane)
        lf_dense = 2 * b * fi * fo
        hb2 = send_rows * w * (wire_f + wire_bwd)    # fwd + bwd of layer w
        hbw2 = wire_rows * w * (wire_f + wire_bwd)
        per_layer.append({"width": int(w), "spmm_flops": int(lf_spmm),
                          "dense_flops": int(lf_dense),
                          "halo_bytes": int(hb2 // 2),
                          "halo_bytes_true": int(hb2 // 2),
                          "halo_bytes_wire": int(hbw2 // 2)})
        spmm_f += lf_spmm
        dense_f += lf_dense
        true_step += hb2
        wire_step += hbw2
    halo_per_ex = sum(pl["halo_bytes"] for pl in per_layer) // max(
        len(per_layer), 1)
    true_step = int(true_step)
    wire_step = int(wire_step)
    if model == "gat":
        # fwd + bwd table-gather streams: per layer, one gathered row per
        # combined slot/tail edge plus the SELECTED transport's exchange
        # gathers (dense: send buffer + halo table; ragged: per-round send
        # buffers only — receives scatter), at that layer's table width
        rows = nnz + _exchange_gather_rows(plan, comm_schedule)
        gather_b = int(2 * rows * 4 * sum(fs))
    else:
        gather_b = int(gather_bytes_per_epoch(plan, fin, widths,
                                              itemsize=itemsize,
                                              comm_schedule=comm_schedule))
    return StepCostModel(
        nlayers=len(widths),
        widths=[int(w) for w in fs],
        spmm_flops=int(spmm_f),
        dense_flops=int(dense_f),
        # symmetric bwd = one more SpMM pass; dense bwd = dX + dW ≈ 2× fwd
        step_flops=int(2 * spmm_f + 3 * dense_f),
        gather_bytes=gather_b,
        halo_send_rows=send_rows,
        halo_bytes_per_exchange=int(halo_per_ex),
        halo_bytes_per_step=true_step,
        per_layer=per_layer,
        comm_schedule=comm_schedule,
        halo_wire_rows=wire_rows,
        padding_efficiency=(send_rows / wire_rows if wire_rows else 1.0),
        halo_bytes_true_per_step=true_step,
        halo_bytes_wire_per_step=wire_step,
    )


def forward_flops(plan, fin: int, widths, model: str = "gcn") -> int:
    """Analytic FLOPs of ONE full partitioned forward over all ``k`` chips
    (inference: no backward, no optimizer) — the denominator of the
    sub-graph serving A/B (``docs/serving.md`` phase 2).  Reuses
    ``step_cost``'s per-chip SpMM/dense models at the padded layout, ×k."""
    cost = step_cost(plan, fin, widths, model=model)
    return int(plan.k * (cost.spmm_flops + cost.dense_flops))


def subgraph_batch_flops(touched_rows: int, recipe_edges: int, fin: int,
                         widths, model: str = "gcn") -> int:
    """Analytic FLOPs of ONE sub-graph serving batch (``serve/subgraph.py``)
    at its TRUE receptive-set size: per layer, one multiply-add per
    (recipe edge, lane) at the layer's aggregation width plus the dense
    projection over the touched rows — the same per-(edge, lane) /
    per-(row, fin, fout) vocabulary as ``step_cost``, so the A/B ratio
    against ``forward_flops`` compares like with like.  Deterministic in
    (graph, queries): a zero-band bench-trend counter."""
    touched_rows = int(touched_rows)
    recipe_edges = int(recipe_edges)
    dims = list(zip([fin] + list(widths)[:-1], widths))
    total = 0
    if model == "gat":
        for fi, fo in dims:
            # z = h·w, the score projection, and the (fout+1)-lane num/den
            # gather-macs per combined edge
            total += 2 * touched_rows * (fi * fo + fo)
            total += 2 * recipe_edges * (fo + 1)
    else:
        from ..models.gcn import exchange_widths
        for (fi, fo), w in zip(dims, exchange_widths(fin, list(widths))):
            total += 2 * touched_rows * fi * fo
            total += 2 * recipe_edges * w
    return int(total)


def add_partial_refresh(cost: StepCostModel, refresh_rows,
                        wire_rows: int, itemsize_fwd: int,
                        itemsize_bwd: int) -> StepCostModel:
    """Price one ``--refresh-band`` PARTIAL refresh step: the shrunken
    replica-step cost (``step_cost(..., replica=True)`` — pass that model
    in) plus the replica-only side channel at the step's ACTUAL per-layer
    shipped rows.  The byte arithmetic is the SAME formula
    ``CommStats.count_partial_refresh_step`` accumulates (value lanes per
    direction; the gradient side channel's 0/1 indicator adds one
    f32-equivalent lane to its wire bytes), so the per-step roofline event
    and the cumulative gauges reconcile exactly.  Returns a new model;
    the input is not mutated."""
    from dataclasses import replace

    refresh_rows = [int(x) for x in refresh_rows]
    if len(refresh_rows) != len(cost.widths):
        raise ValueError(
            f"add_partial_refresh: {len(refresh_rows)} per-layer counts "
            f"for {len(cost.widths)} layers")
    true_extra = wire_extra = 0
    per_layer = []
    for pl, rows, w in zip(cost.per_layer, refresh_rows, cost.widths):
        t = rows * w * (itemsize_fwd + itemsize_bwd)
        wi = int(wire_rows) * (w * itemsize_fwd + (w + 1) * itemsize_bwd)
        true_extra += t
        wire_extra += wi
        per_layer.append(dict(pl,
                              halo_bytes=pl["halo_bytes"] + t // 2,
                              halo_bytes_true=pl["halo_bytes_true"] + t // 2,
                              halo_bytes_wire=pl["halo_bytes_wire"]
                              + wi // 2))
    return replace(
        cost,
        per_layer=per_layer,
        halo_bytes_per_step=cost.halo_bytes_per_step + true_extra,
        halo_bytes_true_per_step=cost.halo_bytes_true_per_step + true_extra,
        halo_bytes_wire_per_step=cost.halo_bytes_wire_per_step + wire_extra,
    )


def roofline_fields(cost: StepCostModel, wall_s: float,
                    exchanges: int = 0, exposed_exchanges: int = 0) -> dict:
    """Join the analytic cost against ONE measured step time.

    ``exchanges`` / ``exposed_exchanges`` are the step's exchange counts
    (from ``CommStats``); ``exposed_comm_frac`` is the fraction of this
    step's wire traffic that sat on the critical path — 1.0 in exact mode,
    0.0 for a fully pipelined stale step, in between for a mixed window.
    """
    def sig(x, n=4):
        # significant digits, not fixed decimals: a CPU-smoke step is
        # micro-scale and a fixed round would collapse it to 0.0
        return float(f"{x:.{n}g}")

    wall_s = max(float(wall_s), 1e-12)
    out = {
        "gather_GB": sig(cost.gather_bytes / 1e9, 6),
        "achieved_gather_GBs": sig(cost.gather_bytes / wall_s / 1e9),
        "stream_ceiling_frac": sig(
            cost.gather_bytes / wall_s / 1e9 / STREAM_CEILING_GBS),
        "model_step_GFLOP": sig(cost.step_flops / 1e9, 6),
        "achieved_GFLOPs": sig(cost.step_flops / wall_s / 1e9),
        "halo_bytes_per_step": cost.halo_bytes_per_step,
        # the padded-vs-true wire split (schema.ROOFLINE_WIRE_KEYS):
        # *_true is the Σ(λ−1) volume the partitioner optimizes, *_wire the
        # selected schedule's shipped bytes — these must reconcile EXACTLY
        # with CommStats' wire_rows/padding_efficiency gauges
        "comm_schedule": cost.comm_schedule,
        "halo_bytes_true_per_step": cost.halo_bytes_true_per_step,
        "halo_bytes_wire_per_step": cost.halo_bytes_wire_per_step,
        "halo_wire_rows_per_exchange": cost.halo_wire_rows,
        "padding_efficiency": cost.padding_efficiency,
    }
    if exchanges > 0:
        out["exposed_comm_frac"] = round(exposed_exchanges / exchanges, 6)
        # exposed bytes charge the WIRE volume: a padded schedule's dead
        # slots cross ICI and sit on the critical path like any other byte
        # (the pre-ragged model charged Σ(λ−1) and under-counted exactly
        # the padding a schedule should be judged on)
        out["exposed_halo_bytes"] = int(
            cost.halo_bytes_wire_per_step * exposed_exchanges / exchanges)
    return out
