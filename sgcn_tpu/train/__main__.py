"""Trainer CLI — flag-compatible with the reference's PGCN/PGAT family.

Reference: ``python PGCN.py -a A.mtx -p partvec -b nccl|gloo -s size -l layers
-f features`` (``README.md:92``, ``GPU/PGCN.py:262-278``); ``PGCN-Mini-batch``
adds ``-n batch_size``; ``PGAT.py`` is the attention flavor.  Here one CLI
covers all four trainers:

  * ``-b jax``  — run on the platform's real devices (TPU mesh), the
    NCCL-equivalent backend per ``BASELINE.json``;
  * ``-b cpu``  — force ``-s`` virtual host CPU devices, the Gloo-equivalent
    "cluster on one box" mode (``GPU/PGCN.py:166-169``);
  * ``--model gat`` — PGAT;  ``-n BATCH`` — PGCN-Mini-batch.

Without ``--features-mtx/--labels-mtx`` the synthetic benchmark harness inputs
are used, like the reference benchmark scripts: ``H[i] = [i]·f`` and
``labels = arange % f`` (``GPU/PGCN.py:186-192``).

The backend env setup must happen before JAX initializes, so heavy imports
are deferred into ``main`` after arg parsing.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys


def _budget(text: str):
    """``--replica-budget`` values: a non-negative int or ``auto`` (the
    λ·degree-knee rule, ``parallel/plan.py::choose_replica_budget``)."""
    if text == "auto":
        return "auto"
    return int(text)


def _mem_budget(text: str) -> int:
    """``--memory-budget`` values: bytes with optional binary suffix
    (``512M``, ``2G``; ``obs/memory.py::parse_bytes``)."""
    from ..obs.memory import parse_bytes

    try:
        return parse_bytes(text)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from e


def _resume_auto(mgr, target, recorder):
    """The ONE --resume auto sequence for both trainers: restore the
    newest intact checkpoint into ``target``, surface the partial-state
    flag the loader set, and emit the schema-v4 resume event.  Returns
    ``(start_step, resumed_block)``."""
    start_step, rpath, skipped = mgr.load_latest(target)
    partial = getattr(target, "last_restore_partial", False)
    resumed = {"step": start_step, "path": rpath,
               "fallback": bool(skipped)}
    if recorder is not None:
        recorder.record_resume(step=start_step, path=rpath,
                               fallback=bool(skipped),
                               partial_state=partial,
                               skipped=skipped or None)
    return start_step, resumed


def _fit_minibatch_durable(tr, feats, labels, args, mgr, recorder, ctx,
                           start_ep: int = 0) -> dict:
    """Mini-batch flavor of the durable path: fit in chunks of
    ``--checkpoint-every`` EPOCHS (the mini-batch trainer's natural
    checkpoint grain — its per-batch plans have no stable step identity),
    saving the inner trainer's state after each chunk.  ``--warmup`` runs
    only on a fresh start (warm-up steps are real optimizer steps; a
    resumed run must not repeat them).  No bit-identity claim here — that
    contract is the full-batch trainer's (docs/resilience.md)."""
    from ..resilience.runner import save_and_record

    every = args.checkpoint_every
    total = args.epochs
    history: list = []
    warm = args.warmup if start_ep == 0 else 0
    done, report = start_ep, None
    while done < total:
        run = total - done
        if every:
            run = min(run, every - done % every)
        report = tr.fit(feats, labels, epochs=run, warmup=warm)
        warm = 0
        history += report.get("loss_history", [])
        done += run
        if every and done % every == 0 and ctx.is_coordinator:
            save_and_record(mgr, tr.inner, done, recorder=recorder)
    if report is None:
        # resumed at (or past) the full schedule: nothing left to train
        report = {"note": "resume found the epoch schedule complete"}
    report.update(epochs=done, loss_history=history, start_epoch=start_ep)
    return report


def main() -> None:
    p = argparse.ArgumentParser(description="sgcn_tpu distributed trainer")
    p.add_argument("-a", "--adjacency", default=None,
                   help=".mtx adjacency (or use --npz)")
    p.add_argument("-p", "--partvec", required=True,
                   help="part vector: text (.gp/.hp/.rp) or pickle")
    p.add_argument("-b", "--backend", default="jax", choices=["jax", "cpu"])
    p.add_argument("-s", "--nparts", type=int, required=True)
    p.add_argument("-l", "--nlayers", type=int, default=2)
    p.add_argument("-f", "--nfeatures", type=int, default=16)
    p.add_argument("-n", "--batch-size", type=int, default=None,
                   help="enable the mini-batch trainer")
    p.add_argument("--model", default="gcn", choices=["gcn", "gat"])
    p.add_argument("--activation", default=None,
                   choices=["relu", "sigmoid", "elu", "none"],
                   help="inter-layer activation; defaults to relu for gcn "
                        "(GPU/PGCN.py:147) and none for gat — the reference "
                        "stacks bare PGAT modules with no nonlinearity "
                        "between them (GPU/PGAT.py:202-213)")
    p.add_argument("--loss", default="xent", choices=["xent", "bce"],
                   help="xent = torch-stack log-softmax+NLL "
                        "(GPU/PGCN.py:204-205); bce = the MPI stack's "
                        "sigmoid+BCE with the reported `err` metric "
                        "(Parallel-GCN/main.c:70-90,318-335)")
    p.add_argument("--dtype", default=None, choices=["bfloat16"],
                   help="mixed-precision compute (f32 master params)")
    p.add_argument("--halo-dtype", default=None, choices=["bfloat16"],
                   help="wire-only exchange dtype: halves a2a ICI bytes, "
                        "all compute stays f32 (full-batch GCN only)")
    p.add_argument("--halo-staleness", type=int, default=0, choices=[0, 1],
                   help="0 (default) = exact per-layer halo exchange; 1 = "
                        "pipelined one-step-stale exchange: layer L of step "
                        "t aggregates with the halo exchanged during step "
                        "t-1, so the a2a leaves the critical path "
                        "(full-batch GCN, symmetric adjacency only; see "
                        "docs/stale_halo.md)")
    p.add_argument("--halo-delta", action="store_true",
                   help="halo-delta cache on top of --halo-staleness 1: "
                        "boundary rows ship as bf16 deltas accumulated "
                        "into the carried remote halo (half the wire bytes)")
    p.add_argument("--sync-every", type=int, default=0,
                   help="stale mode: run a full-sync (exact-math) step "
                        "every N steps to bound staleness/quantization "
                        "drift; replica mode: refresh the replica tables "
                        "every N steps; 0 = only the initializing first "
                        "step")
    p.add_argument("--replica-budget", type=_budget, default=0,
                   metavar="B|auto",
                   help="hot-halo replication (docs/replication.md): "
                        "promote the top-B boundary rows (by λ·degree from "
                        "the comm plan) to persistent replicas on their "
                        "consumer chips — they leave the per-layer wire "
                        "entirely, refreshed only on --sync-every refresh "
                        "steps (at --sync-every 1 the trajectory is f32-"
                        "bit-identical to the no-replica path); full-batch "
                        "GCN, symmetric adjacency, f32; composes with "
                        "--comm-schedule a2a/ragged, --halo-dtype AND "
                        "--halo-staleness 1 (the composed mode: stale "
                        "steps hide the already-shrunken exchange); "
                        "'auto' picks B at the knee of the plan's "
                        "λ·degree curve (the pick lands in the manifest "
                        "comm_schedule block); 0 = off")
    p.add_argument("--refresh-band", type=float, default=None, metavar="RHO",
                   help="drift-driven PARTIAL replica refresh "
                        "(docs/replication.md): scheduled refresh steps "
                        "ship only the replica rows whose relative drift "
                        "‖x−base‖/‖base‖ exceeds RHO, as deltas against "
                        "the refresh baseline (CaPGNN-style) — booked at "
                        "the actual shipped rows; requires "
                        "--replica-budget > 0, --comm-schedule a2a, no "
                        "staleness; step 0 always refreshes in full")
    p.add_argument("--comm-schedule", default=None,
                   choices=["a2a", "ragged", "auto"],
                   help="halo transport (docs/comm_schedule.md): a2a = "
                        "dense globally-padded all_to_all (default); "
                        "ragged = per-round-sized ppermute ring (same "
                        "math, bit-identical f32 losses, fewer wire bytes "
                        "on skewed partitions; symmetric adjacency — GCN "
                        "ships feature rows, GAT its attention tables; "
                        "composes with --halo-staleness 1: the carry "
                        "becomes round-structured and BOTH perf levers "
                        "apply); auto = ragged when the plan's padding "
                        "efficiency drops below 0.5 (under staleness: "
                        "whenever ragged ships fewer wire rows — the "
                        "hidden exchange makes latency moot).  Default: "
                        "$SGCN_COMM_SCHEDULE, else a2a")
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--warmup", type=int, default=1)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--hidden", type=int, default=None,
                   help="hidden width (default: nfeatures)")
    p.add_argument("--normalize", action="store_true",
                   help="apply Â normalization to the input adjacency")
    p.add_argument("--features-mtx", default=None)
    p.add_argument("--labels-mtx", default=None)
    p.add_argument("--npz", default=None,
                   help="planetoid/ogbn-style .npz snapshot (adj_* CSR + "
                        "attr_* + labels); replaces -a, and supplies "
                        "features/labels unless --features-mtx/--labels-mtx "
                        "explicitly override them")
    p.add_argument("--experiment", default=None, choices=["accuracy"],
                   help="accuracy = the PGCN-Accuracy parity experiment "
                        "(GPU/PGCN-Accuracy.py, README.md:110): train the "
                        "dense oracle + the partitioned trainer(s) on a "
                        "planetoid split and report test accuracy for each")
    p.add_argument("--train-per-class", type=int, default=20,
                   help="planetoid split: train nodes per class")
    p.add_argument("--resume", default=None, metavar="CKPT|auto",
                   help="restore FULL trainer state (params/opt_state plus "
                        "the stale/replica carries, sync counters, "
                        "controller retunes and cumulative comm gauges — "
                        "docs/resilience.md) from a checkpoint .npz before "
                        "training; 'auto' picks the newest INTACT "
                        "checkpoint in --checkpoint-dir, falling back past "
                        "corrupt files with a logged warning, and trains "
                        "only the REMAINING steps of the "
                        "--warmup + --epochs schedule — bit-identical to "
                        "the uninterrupted run for every supported mode")
    p.add_argument("--save-checkpoint", default=None, metavar="CKPT",
                   help="save the full trainer state after training")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="durable checkpoint directory "
                        "(docs/resilience.md): step-stamped atomic "
                        "checkpoints with keep-last-K rotation — the "
                        "directory --resume auto restores from")
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                   help="write a durable full-state checkpoint into "
                        "--checkpoint-dir every N optimizer steps "
                        "(full-batch; for the mini-batch trainer N counts "
                        "EPOCHS).  0 = off")
    p.add_argument("--keep-checkpoints", type=int, default=3, metavar="K",
                   help="rotation depth of --checkpoint-dir (keep the "
                        "newest K checkpoints; default 3)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the training run "
                        "into DIR (view with TensorBoard / xprof; the "
                        "reference's analogue is its manual phase timers, "
                        "Cagnet/main.c:35-38 — see utils/timers.py for "
                        "those)")
    p.add_argument("--metrics-out", default=None, metavar="DIR",
                   help="run-telemetry directory (sgcn_tpu.obs): writes a "
                        "run manifest (config, git rev, plan digest) plus a "
                        "per-step JSONL event stream — loss, grad-norm, "
                        "wall time, the hidden/exposed comm split, roofline "
                        "attribution and (stale mode) drift gauges; render "
                        "with scripts/obs_report.py, schema in "
                        "docs/observability.md")
    p.add_argument("--memory-budget", type=_mem_budget, default=None,
                   metavar="BYTES",
                   help="per-chip HBM budget (suffixes K/M/G/T, e.g. 2G): "
                        "the analytic footprint model "
                        "(sgcn_tpu.obs.memory) is checked at PLAN time — "
                        "before any array ships or compile starts — and an "
                        "over-budget (plan, mode) fails with the itemized "
                        "per-family breakdown")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    # pure flag conflicts fail BEFORE any dataset load (minutes at scale)
    if args.halo_dtype and (args.batch_size is not None
                            or args.model != "gcn"
                            or args.experiment == "accuracy"
                            or args.dtype):
        raise SystemExit(
            "--halo-dtype narrows the full-batch GCN exchange only (the "
            "mini-batch trainer and GAT narrow via --dtype bfloat16; the "
            "accuracy-parity harness is defined for the f32-wire config; "
            "under --dtype bfloat16 the wire is already bf16, so the flag "
            "would be a silent no-op)")
    if args.halo_staleness and (args.batch_size is not None
                                or args.model != "gcn"
                                or args.experiment == "accuracy"
                                or args.dtype):
        raise SystemExit(
            "--halo-staleness 1 pipelines the full-batch GCN trainer only "
            "(the mini-batch sweep re-plans per batch, GAT ships per-layer "
            "attention tables, the accuracy-parity harness is defined for "
            "the exact exchange, and the carries are f32 state — drop the "
            "conflicting flag)")
    if args.halo_delta and not args.halo_staleness:
        raise SystemExit(
            "--halo-delta configures the stale pipelined exchange; add "
            "--halo-staleness 1")
    if args.sync_every and not (args.halo_staleness or args.replica_budget):
        raise SystemExit(
            "--sync-every schedules the stale mode's full-sync steps or "
            "the replica mode's refresh steps; add --halo-staleness 1 or "
            "--replica-budget B")
    if args.replica_budget and (args.batch_size is not None
                                or args.model != "gcn"
                                or args.experiment == "accuracy"
                                or args.dtype
                                or args.halo_delta):
        raise SystemExit(
            "--replica-budget replicates rows of the full-batch GCN "
            "exchange only (the mini-batch trainer re-plans per batch, so "
            "replica carries have no stable identity across batch plans; "
            "GAT ships per-layer attention tables; the accuracy-parity "
            "harness is defined for the exact exchange; the carries are "
            "f32 state; composition with --halo-delta is deferred — the "
            "delta baseline and the replica carry would disagree on what "
            "a stale step ships — drop the conflicting flag)")
    if args.refresh_band is not None and (not args.replica_budget
                                          or args.halo_staleness
                                          or args.comm_schedule == "ragged"):
        raise SystemExit(
            "--refresh-band schedules the drift-driven PARTIAL replica "
            "refresh: it requires --replica-budget > 0, rides the dense "
            "a2a transport, and does not compose with --halo-staleness 1 "
            "(the composed mode's replica state lives inside the stale "
            "carry) — drop the conflicting flag")
    # --comm-schedule ragged composes with --halo-staleness 1 since the
    # round-structured stale carry (pspmm_stale_ragged); the remaining
    # genuinely unsupported combo is the accuracy-parity harness, which is
    # defined for the default transport only
    if args.comm_schedule == "ragged" and args.experiment == "accuracy":
        raise SystemExit(
            "--comm-schedule ragged: the accuracy-parity harness is "
            "defined for the default transport — drop the conflicting "
            "flag or use --comm-schedule auto")
    if args.checkpoint_every < 0:
        raise SystemExit(
            f"--checkpoint-every must be >= 0, got {args.checkpoint_every}")
    if (args.checkpoint_every or args.resume == "auto") \
            and not args.checkpoint_dir:
        raise SystemExit(
            "--checkpoint-every / --resume auto operate on the durable "
            "checkpoint directory; add --checkpoint-dir DIR "
            "(docs/resilience.md)")
    if args.checkpoint_dir and args.experiment == "accuracy":
        raise SystemExit(
            "--experiment accuracy trains fresh oracle+partitioned pairs; "
            "durable checkpointing (--checkpoint-dir) is not supported "
            "there")
    if (args.checkpoint_dir and args.batch_size is not None
            and args.resume and args.resume != "auto"):
        raise SystemExit(
            "mini-batch: explicit --resume CKPT does not compose with "
            "--checkpoint-dir (the durable stamps count EPOCHS of THIS "
            "schedule and would collide with the chained run's) — resume "
            "the durable directory with --resume auto, or drop "
            "--checkpoint-dir for a chained run")

    if args.metrics_out:
        # before any heavy import: heartbeat() in the launch/backend layers
        # reads this env var, so rendezvous pings land in the run directory
        import os
        os.environ["SGCN_METRICS_OUT"] = args.metrics_out

    from ..utils.backend import enable_tpu_async_collectives, use_cpu_devices
    if args.backend == "cpu":
        use_cpu_devices(args.nparts)
    enable_tpu_async_collectives()   # overlap needs async all-to-all on TPU

    import jax

    from ..parallel.launch import init_distributed
    ctx = init_distributed()   # no-op single-process; SLURM/TPU-pod rendezvous otherwise

    recorder = None
    if args.metrics_out and ctx.is_coordinator:
        # rank-0-only, like every other end-of-run artifact (the reference
        # prints rank-0 stats; multi-host ranks share the filesystem)
        from ..obs import RunRecorder
        recorder = RunRecorder(args.metrics_out, config=vars(args))
        recorder.set_backend()

    import numpy as np

    from ..io.mtx import read_dense_features, read_mtx, read_onehot_labels
    from ..parallel.plan import build_comm_plan
    from ..partition.emit import read_partvec, read_partvec_pickle
    from ..prep import normalize_adjacency
    from .fullbatch import FullBatchTrainer, make_train_data
    from .minibatch import MiniBatchTrainer

    feats = labels = None
    if args.npz:
        from ..io.datasets import load_npz_dataset
        a, feats, labels = load_npz_dataset(args.npz)
    elif args.adjacency:
        a = read_mtx(args.adjacency)
    else:
        raise SystemExit("need -a/--adjacency or --npz")
    if args.normalize:
        a = normalize_adjacency(a)
    n = a.shape[0]
    try:
        pv = read_partvec(args.partvec)
    except (UnicodeDecodeError, ValueError):
        pv = read_partvec_pickle(args.partvec)
    if len(pv) != n:
        raise SystemExit(f"partvec length {len(pv)} != n {n}")
    k = args.nparts
    if pv.max() >= k:
        raise SystemExit(f"partvec references part {pv.max()} >= k {k}")

    f = args.nfeatures
    if args.features_mtx:
        feats = read_dense_features(args.features_mtx)
    if feats is not None:
        f = feats.shape[1]
    else:
        # synthetic benchmark harness inputs (GPU/PGCN.py:186-192)
        feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, f))
    if args.labels_mtx:
        labels = read_onehot_labels(args.labels_mtx)
    if labels is not None:
        nclasses = int(labels.max()) + 1
    else:
        labels = np.arange(n) % f
        nclasses = f
    labels = labels.astype(np.int32)

    hidden = args.hidden or f
    widths = [hidden] * (args.nlayers - 1) + [nclasses]
    # PGAT stacks bare modules: no inter-layer nonlinearity unless asked
    activation = args.activation or ("none" if args.model == "gat" else "relu")

    prof = (jax.profiler.trace(args.profile) if args.profile
            else contextlib.nullcontext())

    if args.experiment == "accuracy":
        # the PGCN-Accuracy run (GPU/PGCN-Accuracy.py, README.md:110):
        # planetoid split, oracle vs partitioned trainers, test accuracy each.
        # The parity harness compares against the dense GCN oracle, so it is
        # defined for the gcn/xent/relu/f32 configuration only — reject other
        # flags instead of silently mislabeling a default-config run.
        if (args.model != "gcn" or args.loss != "xent" or args.dtype
                or (args.activation or "relu") != "relu"):
            raise SystemExit(
                "--experiment accuracy compares against the dense GCN oracle "
                "and supports only --model gcn --loss xent --activation relu "
                "(f32); drop the conflicting flags")
        if args.resume or args.save_checkpoint:
            raise SystemExit(
                "--experiment accuracy trains fresh oracle+partitioned pairs "
                "for the parity comparison; --resume/--save-checkpoint are "
                "not supported there")
        from ..io.datasets import planetoid_split
        from .accuracy import run_accuracy_parity
        train_mask, test_mask = planetoid_split(
            labels, per_class=args.train_per_class, seed=args.seed)
        with prof:
            report = run_accuracy_parity(
                a, feats, labels, pv, k, widths, train_mask, test_mask,
                epochs=args.epochs, batch_size=args.batch_size, lr=args.lr,
                seed=args.seed)
        report["experiment"] = "accuracy"
        report["backend"] = args.backend
        if recorder is not None:
            # the parity harness drives its own trainers; record the run's
            # identity + outcome (no per-step stream for this experiment)
            if args.profile:
                # --profile and --metrics-out compose: the manifest records
                # where the trace landed (and its gzip'd size), so
                # obs_report.py parses it from the run directory alone
                recorder.set_profile(args.profile)
            recorder.record_summary(report)
            recorder.close()
        if ctx.is_coordinator:
            print(json.dumps(report), flush=True)
        return

    # durable checkpointing (docs/resilience.md): one manager per run
    # directory; saves are coordinator-only (multi-host ranks share the
    # filesystem), restores run on every rank
    mgr = None
    if args.checkpoint_dir:
        from ..resilience.checkpoint import CheckpointManager
        mgr = CheckpointManager(args.checkpoint_dir,
                                keep_last=args.keep_checkpoints)
    resumed = None

    from ..obs.memory import MemoryBudgetError

    with prof:
        if args.batch_size is not None:
            try:
                tr = MiniBatchTrainer(a, pv, k, fin=f, widths=widths,
                                      batch_size=args.batch_size, lr=args.lr,
                                      model=args.model, loss=args.loss,
                                      activation=activation, seed=args.seed,
                                      compute_dtype=args.dtype,
                                      comm_schedule=args.comm_schedule,
                                      memory_budget=args.memory_budget)
            except MemoryBudgetError as e:
                raise SystemExit(str(e)) from e
            if recorder is not None:
                recorder.set_partitioner({"partvec": args.partvec, "k": k})
                tr.attach_recorder(recorder)
            state = tr.inner          # checkpointable params/opt_state holder
            start_step = 0
            if args.resume == "auto":
                # mini-batch checkpoints count EPOCHS completed
                start_step, resumed = _resume_auto(mgr, state, recorder)
            elif args.resume:
                from ..utils.checkpoint import load_checkpoint
                start_step = load_checkpoint(state, args.resume)
            if mgr is not None:
                report = _fit_minibatch_durable(
                    tr, feats, labels, args, mgr, recorder, ctx,
                    start_ep=start_step if args.resume == "auto" else 0)
            else:
                report = tr.fit(feats, labels, epochs=args.epochs,
                                warmup=args.warmup)
        else:
            plan = build_comm_plan(a, pv, k)
            try:
                tr = FullBatchTrainer(plan, fin=f, widths=widths, lr=args.lr,
                                      model=args.model, loss=args.loss,
                                      activation=activation, seed=args.seed,
                                      compute_dtype=args.dtype,
                                      halo_dtype=args.halo_dtype,
                                      halo_staleness=args.halo_staleness,
                                      halo_delta=args.halo_delta,
                                      sync_every=args.sync_every,
                                      comm_schedule=args.comm_schedule,
                                      replica_budget=args.replica_budget,
                                      refresh_band=args.refresh_band,
                                      memory_budget=args.memory_budget)
            except MemoryBudgetError as e:
                raise SystemExit(str(e)) from e
            if recorder is not None:
                recorder.set_plan(plan, partitioner={"partvec": args.partvec,
                                                     "k": k})
                recorder.set_backend(tr.mesh)
                tr.attach_recorder(recorder)
            state = tr
            start_step = 0
            if args.resume == "auto":
                start_step, resumed = _resume_auto(mgr, tr, recorder)
            elif args.resume:
                from ..utils.checkpoint import load_checkpoint
                start_step = load_checkpoint(state, args.resume)
            data = make_train_data(plan, feats, labels)
            if mgr is not None:
                # the resumable per-step loop: durable checkpoints every N
                # steps + the fault-injection kill point.  --resume auto:
                # --warmup/--epochs name the run's TOTAL step schedule and
                # the resumed process completes the remainder (bit-identity
                # contract, docs/resilience.md).  Explicit --resume CKPT
                # keeps its chained semantics (train warmup+epochs MORE
                # steps) but threads the loaded step through, so the
                # durable stamps continue the trainer's real step count
                # instead of restarting at 1
                from ..resilience.runner import run_resumable
                save_mgr = mgr if ctx.is_coordinator else None
                total = args.warmup + args.epochs
                if args.resume and args.resume != "auto":
                    total += start_step
                report = run_resumable(
                    tr, data, total,
                    manager=save_mgr,
                    checkpoint_every=(args.checkpoint_every
                                      if save_mgr is not None else 0),
                    start_step=(start_step if args.resume else 0))
            else:
                report = tr.fit(data, epochs=args.epochs,
                                warmup=args.warmup)
    if resumed is not None:
        report["resumed"] = resumed
    if recorder is not None and args.profile:
        # --profile and --metrics-out compose: the jax.profiler trace is
        # flushed when the `with prof:` context above exits, so NOW the
        # manifest can record its path and gzip'd size — obs_report.py
        # finds and parses the trace from the run directory alone
        recorder.set_profile(args.profile)
    if args.save_checkpoint and ctx.is_coordinator:
        # coordinator-only write (multi-host ranks share the filesystem);
        # step accumulates across chained resumes.  Warm-up epochs are real
        # optimizer steps (fit() runs them before the timed ones), so they
        # count toward the saved step — chained --resume runs would otherwise
        # silently accumulate unreported parameter updates.
        from ..utils.checkpoint import save_checkpoint
        if args.batch_size is not None and mgr is not None:
            # the mini-batch DURABLE path stamps at EPOCH grain everywhere
            # (the CheckpointManager files count epochs) — the final stamp
            # must agree with them whether or not this run resumed, or two
            # bit-identical end states would carry different step stamps
            final_step = args.epochs
        elif args.resume == "auto":
            # --resume auto completes a FIXED total schedule (the durable
            # path's bit-identity contract): the final step is absolute,
            # not additive
            final_step = args.epochs + args.warmup
        else:
            final_step = start_step + args.epochs + args.warmup
        report["checkpoint"] = save_checkpoint(
            state, args.save_checkpoint, step=final_step)

    # rank-0-style end-of-run line (GPU/PGCN.py:226-238)
    report["backend"] = args.backend
    report["model"] = args.model
    report["activation"] = activation
    report["loss"] = args.loss
    report.pop("loss_history", None)
    if recorder is not None:
        recorder.close()
    if ctx.is_coordinator:
        print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
