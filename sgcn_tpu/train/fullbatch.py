"""Full-batch distributed GCN trainer over a 1D vertex-parallel mesh.

Reference equivalents: the epoch loop of ``GPU/PGCN.py:162-238`` (NCCL/Gloo)
and ``Parallel-GCN/main.c:166-453`` (MPI+GraphBLAS).  Structure preserved:

  * one graph part per chip; weights replicated; per-step gradient allreduce
    (here ``lax.psum`` over the mesh) — ``GPU/PGCN.py:150-154``;
  * synchronized initialization (shared PRNG seed instead of the reference's
    init-allreduce, ``GPU/PGCN.py:156-160``);
  * a warm-up step excluded from timing, per-epoch wall-clock aggregated MAX
    over ranks (``GPU/PGCN.py:202-228``) — under jit all chips run the same
    program, so host wall-clock of the blocking step IS the max;
  * end-of-run comm statistics in the reference's vocabulary
    (``GPU/PGCN.py:230-238``, ``Parallel-GCN/main.c:506-524``).

The whole train step — L forward exchanges+SpMMs, loss, L backward
exchanges+SpMMs, grad psum, Adam update — is ONE jitted ``shard_map`` program:
XLA schedules the collectives asynchronously against local compute, which is
the compiler-native form of the reference's Irecv/compute/Waitany overlap
(``Parallel-GCN/main.c:238-299``).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.gat import GAT_PLAN_FIELDS, gat_forward_local, init_gat_params
from ..models.gcn import (
    gcn_forward_local,
    gcn_plan_fields,
    init_gcn_params,
    masked_accuracy_local,
    masked_err_local,
    masked_sigmoid_bce_local,
    masked_softmax_xent_local,
)
from ..parallel.mesh import AXIS, make_mesh_1d, replicate, shard_stacked
from ..parallel.plan import CommPlan
from ..utils.stats import CommStats
from ..utils.timers import PhaseTimer

# model registry: name → (param init, per-chip forward, plan→fields shipped
# to the device). GAT is the reference's PGAT capability (GPU/PGAT.py) on the
# same trainer scaffold — like the reference, only the nn.Module differs
# between PGCN.py and PGAT.py. GCN ships the ELL fast-path arrays for
# symmetric Â (split COO otherwise); GAT the combined edge list its
# edge-softmax needs.
MODELS = {
    # name -> (init, forward, plan->shipped array fields, plan->static kwargs)
    "gcn": (init_gcn_params, gcn_forward_local, gcn_plan_fields,
            lambda plan: ({"ell_buckets": plan.ell_buckets}
                          if plan.symmetric else {})),
    "gat": (init_gat_params, gat_forward_local, lambda plan: GAT_PLAN_FIELDS,
            # ensure_cell: the combined-edge layout is built lazily — only
            # GAT ships it, and it duplicates the edge storage
            lambda plan: {"cell_buckets": plan.ensure_cell().cell_buckets}),
}

# loss registry: 'xent' is the torch stack's log-softmax+NLL
# (GPU/PGCN.py:204-205), 'bce' the MPI stack's sigmoid+BCE
# (Parallel-GCN/main.c:70-90) whose reported metric is `err`.
LOSSES = {
    "xent": masked_softmax_xent_local,
    "bce": masked_sigmoid_bce_local,
}


@dataclass
class ForwardSetup:
    """Resolved forward configuration — the ONE model/schedule/aggregator
    selection shared by the trainer and the serve engine
    (``sgcn_tpu/serve/engine.py``).  Keeping a single resolver is what makes
    the serve engine's forward program the SAME program the trainer's
    ``evaluate()`` compiles (bit-identical f32 logits, tier-1-pinned by
    ``tests/test_serve.py``) — a second copy of the selection rules would
    drift on exactly the branch parity depends on (Pallas auto-select,
    ragged field tuples, GAT table forms)."""

    model: str
    comm_schedule: str            # resolved: 'a2a' or 'ragged', never 'auto'
    plan_fields: tuple            # CommPlan array fields the forward ships
    fwd_static: dict              # static kwargs of the forward fn
    forward_fn: object            # per-chip forward (MODELS registry)
    init_fn: object               # param init (MODELS registry)
    decision: dict                # resolve_comm_schedule's selection log
    replica_budget: int = 0       # resolved: 'auto' -> the λ·degree knee B

    def ship_arrays(self, plan) -> dict:
        """The plan arrays the forward consumes, ready to shard — including
        the GAT int8 edge-mask narrowing (attention ignores Â's values, and
        the f32 forms are ~0.6 GB of per-chip arguments at products scale)."""
        arrays = _plan_arrays(plan, self.plan_fields)
        if self.model == "gat":
            # mask on w != 0: plan padding carries weight exactly 0 by
            # construction, so every real edge survives even for a signed/
            # unnormalized weighted graph (ADVICE r4 — `> 0` dropped
            # negative-weight edges).  The Pallas field set's plan-time 0/1
            # mask tiles (ptile_cw) narrow the same way — gat_pallas_pass
            # upcasts in-program, exactly like the slot passes.
            for f in ("cell_w", "ctail_w", "ptile_cw"):
                if f in arrays:
                    arrays[f] = (arrays[f] != 0).astype(np.int8)
        return arrays


def resolve_forward_setup(plan: "CommPlan", fin: int, widths,
                          model: str = "gcn",
                          comm_schedule: str | None = None,
                          compute_dtype: str | None = None,
                          halo_staleness: int = 0,
                          replica_budget: int | str = 0,
                          refresh_band: float | None = None,
                          serve_subgraph: bool = False,
                          allow_pallas: bool = True
                          ) -> ForwardSetup:
    """Resolve (schedule, shipped plan fields, static forward kwargs) for one
    plan — the selection logic that used to live inline in
    ``FullBatchTrainer.__init__``, factored out so the forward-only serve
    engine rides the identical rules.  Builds the lazy plan layouts the
    selection needs (``ensure_ragged``, ``ensure_cell``,
    ``ensure_pallas_tiles``, ``ensure_replicas``) as side effects, exactly
    as the trainer did.  ``replica_budget`` is a TRAINING-only lever (the
    trainer gates it; serving always runs the exact forward and never
    passes it): it swaps the shipped fields for the replica union tuples —
    ``fwd_static`` stays the EXACT forward's statics, because evaluation
    and serving ride ``gcn_forward_local`` on the same (superset) plan
    arrays, with jit pruning the ``nrep_*`` half.  ``allow_pallas=False``
    keeps the selection on the slot-pass/ELL aggregators regardless of the
    VMEM-fit rule — the mini-batch trainer's ONE compiled step must serve
    every per-batch plan, and the Pallas tile layout (per-class Emax_c
    statics, tiles built per plan) has no shared-envelope form."""
    from ..parallel.plan import choose_replica_budget, resolve_comm_schedule

    decision: dict = {}
    init_fn, forward_fn, fields_fn, static_fn = MODELS[model]
    if replica_budget == "auto":
        # --replica-budget auto: the λ·degree-knee rule, resolved BEFORE
        # the schedule selection so the auto transport scores the wire at
        # the chosen shrink; the knee log lands in the manifest's
        # comm_schedule block (docs/replication.md)
        if model != "gcn":
            raise ValueError("replica_budget='auto' is a GCN lever "
                             "(replication is GCN-only)")
        knee: dict = {}
        replica_budget = choose_replica_budget(plan, decision=knee)
        decision["replica_auto"] = knee
    replica_budget = int(replica_budget or 0)
    comm_schedule = resolve_comm_schedule(
        comm_schedule, [plan], model, halo_staleness,
        fin=fin, widths=list(widths), compute_dtype=compute_dtype,
        replica_budget=replica_budget if model == "gcn" else 0,
        decision=decision)
    if comm_schedule == "ragged":
        if not plan.symmetric:
            raise ValueError(
                "comm_schedule='ragged' uses the symmetric custom "
                "backward (the gradient rides the same ppermute ring); "
                "this plan is asymmetric — run the a2a schedule")
        plan.ensure_ragged()
    plan_fields = fields_fn(plan)
    fwd_static = static_fn(plan)
    if model == "gcn" and comm_schedule == "ragged":
        # the ragged ELL aggregation path (fold-as-you-arrive scatter over
        # the per-owner edge split); the Pallas selection below may swap
        # it for the schedule-agnostic VMEM kernel family.  The composed
        # (stale × ragged) step ships the same ring arrays under its own
        # contract tuple.
        from ..models.gcn import GCN_PLAN_FIELDS_RAGGED
        from ..parallel.plan import STALE_PLAN_FIELDS_RAGGED
        plan_fields = (STALE_PLAN_FIELDS_RAGGED if halo_staleness
                       else GCN_PLAN_FIELDS_RAGGED)
        fwd_static = {"ell_buckets": plan.ell_buckets,
                      "comm_schedule": "ragged",
                      "rr_sizes": plan.rr_sizes,
                      "rr_edge_sizes": plan.rr_edge_sizes}
    if model == "gcn" and replica_budget:
        # hot-halo replication (docs/replication.md): the shipped fields
        # are the UNION of the full exchange layout (the sync/refresh
        # program = the exact program + replica gathers; evaluate() rides
        # it) and the shrunken no-replica layout; fwd_static stays the
        # exact forward's statics — the replica-only statics
        # (nrep_rr_sizes, halo table height) live on the trainer.  The
        # composed (replica × stale) step ships its own contract tuples:
        # the stale carry subsumes the replica tables, so no rep/grep
        # arrays ride along, and the ragged flavor adds the carry scatter
        # map ``nrep_ring_dst``.  ``refresh_band`` adds the partial-
        # refresh side channel (ronly buckets + baselines routing).
        from ..parallel.plan import (REPLICA_PARTIAL_PLAN_FIELDS,
                                     REPLICA_PLAN_FIELDS,
                                     REPLICA_PLAN_FIELDS_RAGGED,
                                     REPLICA_STALE_PLAN_FIELDS,
                                     REPLICA_STALE_PLAN_FIELDS_RAGGED)
        plan.ensure_replicas(replica_budget)
        if halo_staleness:
            plan_fields = (REPLICA_STALE_PLAN_FIELDS_RAGGED
                           if comm_schedule == "ragged"
                           else REPLICA_STALE_PLAN_FIELDS)
        elif refresh_band is not None:
            plan_fields = REPLICA_PARTIAL_PLAN_FIELDS
        else:
            plan_fields = (REPLICA_PLAN_FIELDS_RAGGED
                           if comm_schedule == "ragged"
                           else REPLICA_PLAN_FIELDS)
    if model == "gat" and comm_schedule == "ragged":
        # the attention tables ride the plan's model-independent
        # per-vertex ring layout (rsend_idx/rhalo_dst); the combined
        # bucketed slot passes are schedule-blind, so only the shipped
        # exchange arrays and the static ring spec change
        from ..models.gat import GAT_PLAN_FIELDS_RAGGED
        plan_fields = GAT_PLAN_FIELDS_RAGGED
        fwd_static = dict(fwd_static,
                          comm_schedule="ragged",
                          rr_sizes=plan.rr_sizes,
                          halo_r=plan.r)
    if not halo_staleness and not replica_budget and allow_pallas:
        # plan-driven kernel choice (VERDICT r3 #9, schedule- and
        # model-agnostic since ISSUE 15): per-chip tables in the VMEM
        # regime switch the aggregator to the Pallas kernel family, on
        # EITHER transport and for BOTH models, with the kernel picked
        # per degree-binned tile class (choose_pallas_dispatch — hub
        # classes may stay on the XLA gather form while the dense
        # low-degree mass rides VMEM; the per-bucket decision lands in
        # the manifest decision log).  The stale mode stays on the ELL
        # aggregator: pspmm_stale's carry contract is built around it,
        # and hiding the exchange removes the latency the VMEM kernel
        # would have overlapped; the replica mode likewise — its
        # halo-table assembly and carry contract are built around the
        # ELL + hedge fold; the mini-batch trainer passes
        # allow_pallas=False (one compiled step, many per-batch plans —
        # see the docstring).
        from ..ops.pallas_spmm import (PALLAS_PLAN_FIELDS,
                                       PALLAS_PLAN_FIELDS_RAGGED,
                                       choose_pallas_dispatch,
                                       use_pallas_spmm)
        if use_pallas_spmm(plan, fin, widths, model=model,
                           compute_dtype=compute_dtype,
                           schedule=comm_schedule):
            if serve_subgraph:
                # the sub-graph serve engine's compact mirror reproduces
                # the ELL fold's per-row chains (serve/subgraph.py); the
                # Pallas tile fold has a different per-row addition
                # sequence, so bit-parity would silently break — refuse
                # here, in the ONE selection-rule home, rather than in
                # the engine
                raise ValueError(
                    "sub-graph serving reproduces the ELL fold; this plan "
                    "resolved to the Pallas VMEM aggregator — serve with "
                    "mode='full' or set SGCN_PALLAS_SPMM=0")
            pallas_static = choose_pallas_dispatch(
                plan, model=model, schedule=comm_schedule,
                decision=decision)
            pallas_static["pallas_emulate"] = \
                jax.default_backend() != "tpu"
            if model == "gat":
                from ..models.gat import (GAT_PLAN_FIELDS_PALLAS,
                                          GAT_PLAN_FIELDS_PALLAS_RAGGED)
                plan_fields = (GAT_PLAN_FIELDS_PALLAS_RAGGED
                               if comm_schedule == "ragged"
                               else GAT_PLAN_FIELDS_PALLAS)
                fwd_static = dict(
                    cell_buckets=plan.cell_buckets, **pallas_static)
            else:
                plan_fields = (PALLAS_PLAN_FIELDS_RAGGED
                               if comm_schedule == "ragged"
                               else PALLAS_PLAN_FIELDS)
                fwd_static = dict(pallas_static)
            if comm_schedule == "ragged":
                # both models thread the same static ring spec (the ring
                # concat needs only rr_sizes — no redge fold, no halo_r)
                fwd_static.update(comm_schedule="ragged",
                                  rr_sizes=plan.rr_sizes)
    return ForwardSetup(model=model, comm_schedule=comm_schedule,
                        plan_fields=plan_fields, fwd_static=fwd_static,
                        forward_fn=forward_fn, init_fn=init_fn,
                        decision=decision, replica_budget=replica_budget)


@dataclass
class TrainData:
    """Stacked per-chip training data (leading axis k, sharded over the mesh)."""

    h0: Any        # (k, B, f) input features
    labels: Any    # (k, B) int32
    train_valid: Any  # (k, B) float32 — 1 on real rows in the train split
    eval_valid: Any   # (k, B) float32 — 1 on real rows in the eval split


def make_train_data(
    plan: CommPlan,
    features: np.ndarray,
    labels: np.ndarray,
    train_mask: np.ndarray | None = None,
    eval_mask: np.ndarray | None = None,
) -> TrainData:
    """Scatter global (n, f) features and (n,) int labels into per-chip blocks."""
    n = plan.n
    h0 = plan.scatter_rows(features.astype(np.float32))
    lab = plan.scatter_rows(labels.reshape(n, 1).astype(np.int32))[..., 0]
    if train_mask is None:
        train_mask = np.ones(n, dtype=np.float32)
    if eval_mask is None:
        eval_mask = train_mask
    tv = plan.scatter_rows(train_mask.reshape(n, 1).astype(np.float32))[..., 0]
    ev = plan.scatter_rows(eval_mask.reshape(n, 1).astype(np.float32))[..., 0]
    tv = tv * plan.row_valid
    ev = ev * plan.row_valid
    return TrainData(h0=h0, labels=lab, train_valid=tv, eval_valid=ev)


def make_train_data_multihost(
    plan: CommPlan,
    mesh,
    features: np.ndarray,
    labels: np.ndarray,
    train_mask: np.ndarray | None = None,
    eval_mask: np.ndarray | None = None,
) -> TrainData:
    """Multi-process data placement: each process materializes blocks ONLY
    for its own chips and assembles the global sharded arrays with
    ``jax.make_array_from_process_local_data`` — the supported multi-host
    path (a ``device_put`` of host-local data to a global sharding is not).

    ``features``/``labels``/masks are indexed globally, but only rows owned
    by this process's chips are READ — each host may leave remote rows as
    zeros / memory-mapped, exactly like each MPI rank reading only its own
    ``H.r`` shard (``Parallel-GCN/main.c:456-504``; SLURM deployment
    ``GPU/pytorch.3node.slurm:46-56`` + ``GPU/PGCN.py:241-260``).

    Returns a ``TrainData`` of global jax.Arrays, drop-in for ``step`` /
    ``run_epochs`` / ``evaluate``.
    """
    import jax

    from ..parallel.mesh import local_chip_slice
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = plan.n
    sl = local_chip_slice(mesh)
    chips = range(plan.k)[sl]
    if train_mask is None:
        train_mask = np.ones(n, dtype=np.float32)
    if eval_mask is None:
        eval_mask = train_mask

    sh = NamedSharding(mesh, P(AXIS))

    def put(local, gshape):
        if jax.process_count() == 1:
            return jax.device_put(local, sh)
        return jax.make_array_from_process_local_data(sh, local, gshape)

    scatter = lambda x, dt: plan.scatter_rows(  # noqa: E731 — local shorthand
        np.asarray(x, dtype=dt).reshape(n, -1), chips=chips)
    f = features.shape[1]
    rv = plan.row_valid[sl]
    h0 = put(scatter(features, np.float32), (plan.k, plan.b, f))
    lab = put(scatter(labels, np.int32)[..., 0], (plan.k, plan.b))
    tv = put(scatter(train_mask, np.float32)[..., 0] * rv, (plan.k, plan.b))
    ev = put(scatter(eval_mask, np.float32)[..., 0] * rv, (plan.k, plan.b))
    return TrainData(h0=h0, labels=lab, train_valid=tv, eval_valid=ev)


def _plan_arrays(plan: CommPlan, fields) -> dict:
    return {f: getattr(plan, f) for f in fields}


def _unblock(tree):
    """Strip the leading per-chip block axis shard_map hands us (size 1)."""
    return jax.tree.map(lambda x: x[0], tree)


def _reblock(tree):
    """Re-add the leading per-chip block axis for ``out_specs=P(AXIS)``
    outputs (the stacked-carry convention, like ``logits[None]`` in eval)."""
    return jax.tree.map(lambda x: x[None], tree)


def _global_grad_norm(grads):
    """L2 norm over every leaf of an (already psum'd, replicated) grad tree."""
    import jax.numpy as jnp

    sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree.leaves(grads))
    return jnp.sqrt(sq)


class FullBatchTrainer:
    """Distributed full-batch trainer (PGCN-equivalent, ``-b jax`` backend)."""

    def __init__(
        self,
        plan: CommPlan,
        fin: int,
        widths: list[int],
        mesh=None,
        lr: float = 0.01,
        activation: str = "relu",
        final_activation: str = "none",
        optimizer: optax.GradientTransformation | None = None,
        seed: int = 0,
        model: str = "gcn",
        loss: str = "xent",
        compute_dtype: str | None = None,
        remat: bool = False,
        halo_dtype: str | None = None,
        halo_staleness: int = 0,
        halo_delta: bool = False,
        sync_every: int = 0,
        comm_schedule: str | None = None,
        replica_budget: int | str = 0,
        refresh_band: float | None = None,
        auto_tune_sync: bool = False,
        allow_pallas: bool = True,
        memory_budget: int | None = None,
    ):
        """``compute_dtype='bfloat16'`` runs forward/backward (including the
        halo exchange — half the ICI bytes) in bf16 with f32 master params
        and f32 loss/grad reduction; the reference stacks are f32-only, this
        is the TPU-native mixed-precision option (MXU eats bf16).

        ``halo_dtype='bfloat16'`` narrows ONLY the wire: the a2a send buffer
        is cast after the send-side gather and upcast after the halo gather
        (both directions — the symmetric backward's gradient exchange too),
        so ICI bytes halve while every table, activation and accumulation
        stays f32.  The single-chip bf16 lesson (BASELINE.md: casts of the
        master arrays cost more than the halved HBM bytes buy) does not
        apply: only the (k, S, f) boundary buffer is cast.  GCN only — the
        GAT exchange ships its attention tables, which narrow via
        ``compute_dtype='bfloat16'`` (the packed one-gather path).

        ``remat=True`` wraps the forward in ``jax.checkpoint`` so layer
        activations are recomputed in the backward pass instead of stored —
        the HBM-for-FLOPs trade for deep stacks / huge vertex counts (no
        reference analogue; the MPI code stores every layer's H and Z,
        ``Parallel-GCN/main.c:553-607``).

        ``halo_staleness=1`` selects the PIPELINED exchange (the
        PipeGCN-style bounded-staleness mode, ``ops/pspmm.py::pspmm_stale``):
        each chip carries per-layer halo buffers across steps, layer ℓ of
        step t aggregates with the halo exchanged during step t−1, and step
        t's exchange (features forward, gradients backward) has no same-step
        consumer — XLA schedules the a2a entirely behind local compute, so
        the only collective on the critical path disappears from it.  Step 0
        and, with ``sync_every=N``, every N-th step run the FULL-SYNC
        program (fresh halos consumed — exact math) to initialize/bound the
        carries' drift.  ``halo_delta=True`` adds the halo-delta cache on
        the feature wire: boundary rows ship as ``h_t − h_{t−1}`` in bf16
        and both ends accumulate the identical quantized increment, halving
        wire bytes (the gradient wire stays at ``halo_dtype``).  ``0``
        (default) is EXACTLY the pre-existing trainer — same code path, same
        program.  GCN + symmetric Â only; evaluation always runs the exact
        forward.

        ``comm_schedule`` selects the halo transport
        (``docs/comm_schedule.md``): ``'a2a'`` (default) is the dense
        globally-padded ``all_to_all``; ``'ragged'`` the per-round-sized
        ppermute ring (``ops/pspmm.py::pspmm_ragged_sym``) — same math, f32
        bit-identical losses, strictly fewer wire bytes whenever
        ``send_counts`` is skewed; ``'auto'`` picks ragged when the plan's
        dense padding efficiency falls below ``RAGGED_AUTO_EFFICIENCY``
        (``parallel/plan.py`` — the wire-byte ratio, which reduces to the
        row ratio for every table form; under ``halo_staleness=1`` the
        hidden exchange switches ``auto`` to the wire-byte-only rule).
        ``None`` reads ``$SGCN_COMM_SCHEDULE`` (default ``'a2a'``).
        Model-agnostic: GCN rides the ring with feature rows, GAT with its
        per-layer attention tables (fused, packed-bf16 and split forms —
        the split pair's two dense dispatches collapse into one two-lane
        ring).  Symmetric edge patterns only.  ``'ragged'`` +
        ``halo_staleness=1`` is the COMPOSED mode
        (``ops/pspmm.py::pspmm_stale_ragged``): round-structured carries
        ride the ring across steps, so both the Σ(λ−1) wire win and the
        hidden-exchange critical-path win apply at once.

        ``replica_budget=B`` (B > 0) enables HOT-HALO REPLICATION
        (CaPGNN-style, ``docs/replication.md``): the plan's top-B boundary
        rows by λ·degree live as persistent per-layer replicas on their
        consumer chips (``CommPlan.ensure_replicas``), leaving the
        per-layer wire entirely — both directions ship the shrunken
        ``nrep_*`` buckets/ring and fill the replica halo slots from
        carried tables.  Step 0 and every ``sync_every``-th step run the
        REFRESH program: the full exact exchange (f32-bit-identical math —
        ``--sync-every 1`` reproduces the no-replica trajectory exactly)
        with the replica tables re-read fresh as a byproduct.  Unlike
        ``halo_staleness``, every exchange stays synchronous: replication
        shrinks wire bytes (``halo_bytes_true`` is the gauge), not
        exposure.  GCN + symmetric Â + f32 non-remat only; composition
        with ``halo_staleness=1`` is deferred with a clean error;
        evaluation always runs the exact forward."""
        if halo_dtype is not None and model != "gcn":
            raise ValueError(
                "halo_dtype is a GCN-trainer lever; for GAT use "
                "compute_dtype='bfloat16' (the packed exchange already "
                "ships half-width rows)")
        if halo_staleness not in (0, 1):
            raise ValueError(
                f"halo_staleness must be 0 (exact) or 1 (pipelined), got "
                f"{halo_staleness}")
        if halo_delta and not halo_staleness:
            raise ValueError(
                "halo_delta accumulates into the stale halo carry; it "
                "requires halo_staleness=1")
        if sync_every < 0:
            raise ValueError(f"sync_every must be >= 0, got {sync_every}")
        if sync_every and not (halo_staleness or replica_budget):
            raise ValueError(
                "sync_every schedules the stale mode's full-sync steps / "
                "the replica mode's refresh steps; it requires "
                "halo_staleness=1 or replica_budget>0 (exact mode is "
                "always in sync)")
        if replica_budget != "auto" and replica_budget < 0:
            raise ValueError(
                f"replica_budget must be >= 0 or 'auto', got "
                f"{replica_budget}")
        if replica_budget:
            if model != "gcn":
                raise ValueError(
                    "replica_budget replicates rows of the GCN feature "
                    "exchange; the GAT exchange ships per-layer attention "
                    "tables whose replication is not supported")
            if halo_delta:
                raise ValueError(
                    "replica_budget composed with halo_delta is deferred: "
                    "the delta baseline and the replica carry would "
                    "disagree on what a stale step ships — compose "
                    "replication with plain --halo-staleness 1 instead "
                    "(docs/replication.md)")
            if not plan.symmetric:
                raise ValueError(
                    "replica_budget uses the symmetric-Â custom backward "
                    "(gradient replicas mirror the feature replicas); this "
                    "plan is asymmetric — run without replication")
            if compute_dtype is not None or remat:
                raise ValueError(
                    "replica_budget is defined for the f32 non-remat "
                    "trainer (replica carries are f32 state threaded "
                    "through the step); drop compute_dtype/remat or run "
                    "without replication")
        if refresh_band is not None:
            if refresh_band < 0:
                raise ValueError(
                    f"refresh_band must be >= 0, got {refresh_band}")
            if not replica_budget:
                raise ValueError(
                    "refresh_band schedules the drift-driven PARTIAL "
                    "replica refresh; it requires replica_budget > 0 "
                    "(docs/replication.md)")
            if halo_staleness:
                raise ValueError(
                    "refresh_band with halo_staleness=1 is deferred: the "
                    "composed mode's replica state lives inside the stale "
                    "halo carry, which partial refresh cannot address per "
                    "row — run full refreshes there (docs/replication.md)")
        if halo_staleness:
            if model != "gcn":
                raise ValueError(
                    "halo_staleness=1 pipelines the GCN hot path; the GAT "
                    "exchange ships per-layer attention tables whose "
                    "staleness is not supported (models/gat.py)")
            if not plan.symmetric:
                raise ValueError(
                    "halo_staleness=1 uses the symmetric-Â custom backward "
                    "(stale gradient exchange == stale forward exchange "
                    "pattern); this plan is asymmetric — run exact mode")
            if compute_dtype is not None or remat:
                raise ValueError(
                    "halo_staleness=1 is defined for the f32 non-remat "
                    "trainer (carries are f32 state threaded through the "
                    "step); drop compute_dtype/remat or run exact mode")
        # ONE selection rule for both trainers AND the serve engine
        # (resolve_forward_setup → parallel/plan.py::resolve_comm_schedule):
        # 'auto' silently prefers ragged on skewed plans (the kernel family
        # is schedule-agnostic since ISSUE 15, so the transport choice no
        # longer forfeits the Pallas VMEM aggregator); an explicit 'ragged'
        # is a contract, validated loudly inside the resolver.  Composition
        # with halo_staleness=1 is SUPPORTED (the round-structured carry of
        # pspmm_stale_ragged); the staleness gates above (GCN, symmetric,
        # f32 non-remat) already cover the genuinely unsupported combos.
        setup = resolve_forward_setup(
            plan, fin, widths, model=model, comm_schedule=comm_schedule,
            compute_dtype=compute_dtype, halo_staleness=halo_staleness,
            replica_budget=replica_budget, refresh_band=refresh_band,
            allow_pallas=allow_pallas)
        self.comm_decision = setup.decision   # selection → run manifest
        comm_schedule = setup.comm_schedule
        replica_budget = setup.replica_budget   # 'auto' -> the knee B
        self.comm_schedule = comm_schedule
        self.halo_staleness = halo_staleness
        self.halo_delta = halo_delta
        self.sync_every = sync_every
        self.halo_dtype = halo_dtype
        self.replica_budget = replica_budget
        self.refresh_band = refresh_band
        if refresh_band is not None and comm_schedule != "a2a":
            raise ValueError(
                "refresh_band rides the dense-a2a replica path; the "
                "ragged partial-refresh side channel is deferred — run "
                "--comm-schedule a2a (docs/replication.md)")
        # mid-run --sync-every retune (docs/comm_schedule.md, controller):
        # enabled when the schedule was asked as 'auto' (the controller
        # contract) or explicitly via auto_tune_sync, on any mode with a
        # sync schedule to tune
        self.controller = None
        if ((auto_tune_sync
             or str(self.comm_decision.get("asked")) == "auto")
                and sync_every and (halo_staleness or replica_budget)):
            from .controller import CommController
            self.controller = CommController(sync_every=sync_every)
            # the controller block is manifest-visible even before any
            # retune — "the controller ran and held" is itself a decision
            self.comm_decision["controller"] = self.controller.log()
        self.plan = plan
        self.fin = fin
        self.widths = list(widths)
        # analytic per-chip HBM footprint (obs/memory.py) + the
        # --memory-budget plan-time gate: an over-budget (plan, mode) fails
        # HERE — before any params init or array shipping — with the
        # itemized per-family table (docs/observability.md, memory block)
        from ..obs.memory import check_memory_budget, memory_model
        self.memory = memory_model(
            plan, fin, self.widths, workload="train", model=model,
            compute_dtype=compute_dtype, halo_dtype=halo_dtype,
            halo_staleness=halo_staleness, halo_delta=halo_delta,
            refresh_band=refresh_band, setup=setup)
        check_memory_budget(self.memory, memory_budget,
                            what=f"{model} trainer")
        # run telemetry (sgcn_tpu.obs): attach_recorder() compiles the
        # telemetry step variants; until then the recorder is off and every
        # code path below is the pre-existing trainer
        self.recorder = None
        self.timer = PhaseTimer()   # CAGNET-vocabulary phase breakdown —
        # the ONE code path for phase boundaries (fit()'s wall-clock and the
        # JSONL phase records both read it; sync= callables sit at each
        # block_until_ready boundary)
        from ..obs.tracing import SpanTimer
        self.spans = SpanTimer(timer=self.timer)   # measured-span layer
        # over the same timer: without a recorder a span IS a phase (two
        # perf_counter reads); with one, every span exit appends a
        # schema-v2 span event (docs/observability.md, measured vs analytic)
        self._step_count = 0
        self._cost_cache = {}       # lazy obs.attribution.step_cost models,
        # keyed by step kind (sync vs stale) — under --halo-delta the
        # feature wire's itemsize differs between the two (obs glossary)
        self.mesh = mesh if mesh is not None else make_mesh_1d(plan.k)
        self.activation = activation
        self.final_activation = final_activation
        self.compute_dtype = compute_dtype
        self.remat = remat
        init_fn, self._forward_fn = setup.init_fn, setup.forward_fn
        self.plan_fields = setup.plan_fields
        self._fwd_static = setup.fwd_static  # e.g. the ELL bucket structure
        if model == "gat":
            # pre-flight the measured single-chip capacity edge: a clear
            # error beats a compile OOM or a dead TPU worker — BOTH were
            # observed at products scale (models/gat.py::check_gat_memory;
            # static_fn above already ran ensure_cell, so tail size is known)
            from ..models.gat import check_gat_memory
            check_gat_memory(
                plan.b, int(plan.halo_counts.max()), fin, widths,
                nnz=int(plan.nnz.max()),
                tail=int(plan.ctail_nnz.max()) if plan.ctail_nnz is not None
                else 0,
                dtype=compute_dtype)
        self.model = model
        self.loss_name = loss
        self._loss_fn = LOSSES[loss]
        dims = list(zip([fin] + widths[:-1], widths))
        self.params = init_fn(jax.random.PRNGKey(seed), dims)
        self.opt = optimizer if optimizer is not None else optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self.params = replicate(self.mesh, self.params)
        self.opt_state = replicate(self.mesh, self.opt_state)
        self.last_err = None
        self.pa = shard_stacked(self.mesh, setup.ship_arrays(plan))
        # per-exchange wire lane widths (f32-lane equivalents) — the real
        # table widths each model ships, so the CommStats byte gauges
        # (halo_bytes_true/halo_bytes_wire) reconcile EXACTLY with the obs
        # roofline's attribution (docs/observability.md): GCN ships feature
        # rows at the project-first widths, GAT its attention tables (fused
        # fout+1 / packed fout/2+1 / split pair)
        if model == "gat":
            from ..models.gat import gat_exchange_lane_widths
            lane_widths = tuple(gat_exchange_lane_widths(
                self.widths, compute_dtype))
            wire_itemsize = wire_itemsize_bwd = 4   # lanes encode the dtype
        else:
            from ..models.gcn import exchange_widths
            lane_widths = tuple(exchange_widths(fin, self.widths))
            # per-DIRECTION wire itemsize (docs/observability.md): the
            # halo-delta cache narrows only the FEATURE wire (and only on
            # stale steps — count_step takes a per-step override for the
            # f32 re-base syncs); the gradient wire follows --halo-dtype
            wire_itemsize = 2 if (halo_dtype == "bfloat16" or halo_delta
                                  or compute_dtype == "bfloat16") else 4
            wire_itemsize_bwd = 2 if (halo_dtype == "bfloat16"
                                      or compute_dtype == "bfloat16") else 4
        self.stats = CommStats.from_plan(plan, schedule=comm_schedule,
                                         lane_widths=lane_widths,
                                         wire_itemsize=wire_itemsize,
                                         wire_itemsize_bwd=wire_itemsize_bwd)
        if replica_budget:
            # the shrunken no-replica exchange's per-rank/wire figures —
            # count_step(replica=True) books replica steps at these, so
            # the cumulative gauges reconcile with the per-step roofline
            self.stats.set_replica(plan)
        self._step = self._build_step()
        self._eval = self._build_eval()
        self._multi = {}        # epochs -> compiled on-device epoch loop
        # composed replica × stale statics (docs/comm_schedule.md): the
        # stale forward dispatches to the pspmm_replica_stale ops, whose
        # stale steps ship the SHRUNKEN nrep_* exchange; kept off
        # _fwd_static so evaluate()'s exact forward never sees them
        self._rep_stale_static = {}
        if replica_budget and halo_staleness:
            self._rep_stale_static = {"replica": True}
            if comm_schedule == "ragged":
                self._rep_stale_static["nrep_rr_sizes"] = plan.nrep_rr_sizes
        if halo_staleness:
            # per-layer carry state, stacked per chip and sharded like the
            # plan arrays; zeros are never consumed — the first step (and
            # every sync step) runs the full-sync program, which reads the
            # FRESH exchange and refreshes every carry as a byproduct.
            # Under the composed mode the carries are ROUND-STRUCTURED ring
            # receive buffers (plan.stale_carry_shapes, schedule-aware);
            # under replica × stale the SAME carries subsume the replica
            # tables (replica slots/positions just stop being overwritten
            # between syncs), so no extra state appears.
            shapes = plan.stale_carry_shapes(fin, widths, delta=halo_delta,
                                             comm_schedule=comm_schedule)
            carry = {
                name: [np.zeros((plan.k,) + s, np.float32) for s in shps]
                for name, shps in shapes.items()
            }
            self.halo_carry = shard_stacked(self.mesh, carry)
            self._stale_step_idx = 0
            self._last_sync_idx = 0     # staleness-age gauge anchor
            self._step_stale = self._build_step_stale(fresh=False)
            self._step_sync = self._build_step_stale(fresh=True)
            self._multi_stale = {}   # epochs -> compiled stale epoch loop
        if replica_budget and not halo_staleness:
            # per-layer feature/gradient replica tables, stacked per chip
            # and sharded like the plan arrays; zeros are never consumed —
            # step 0 (and every sync_every-th step) runs the refresh
            # program, which reads the FULL exchange and refreshes every
            # carry as a byproduct (plan.replica_carry_shapes).  (The
            # composed replica × stale mode carries NO replica state of
            # its own — the stale halo carry above subsumes it.)
            self._rep_static = (
                {"comm_schedule": "ragged",
                 "rr_sizes": plan.rr_sizes,
                 "rr_edge_sizes": plan.rr_edge_sizes,
                 "nrep_rr_sizes": plan.nrep_rr_sizes,
                 "halo_r": plan.r}
                if comm_schedule == "ragged" else {"comm_schedule": "a2a"})
            partial = refresh_band is not None
            if partial:
                self._rep_static = dict(self._rep_static, track_base=True)
            shapes = plan.replica_carry_shapes(fin, widths, partial=partial)
            carry = {
                name: [np.zeros((plan.k,) + s, np.float32) for s in shps]
                for name, shps in shapes.items()
            }
            self.replica_carry = shard_stacked(self.mesh, carry)
            self._rep_step_idx = 0
            self._last_refresh_idx = 0    # refresh-age gauge anchor
            self._step_rep = self._build_step_replica(fresh=False)
            self._step_rep_sync = self._build_step_replica(fresh=True)
            if partial:
                # the drift-banded partial refresh program (the
                # --refresh-band refresh step; step 0 stays FULL — it
                # initializes the carries and baselines)
                self._step_rep_partial = self._build_step_replica(
                    fresh=False, partial=True)
            self._multi_rep = {}     # epochs -> compiled replica epoch loop

    # ------------------------------------------------------------------ build
    def _forward(self, params, pa, h0):
        if self.compute_dtype is not None:
            import jax.numpy as jnp
            dt = jnp.dtype(self.compute_dtype)
            params = jax.tree.map(lambda w: w.astype(dt), params)
            h0 = h0.astype(dt)
            pa = {k: v.astype(dt) if v.dtype == jnp.float32 else v
                  for k, v in pa.items()}
        extra = ({"halo_dtype": self.halo_dtype}
                 if self.halo_dtype is not None else {})
        out = self._forward_fn(
            params, h0, pa,
            activation=self.activation,
            final_activation=self.final_activation,
            symmetric=self.plan.symmetric,
            **self._fwd_static,
            **extra,
        )
        return out.astype("float32")

    def _one_step(self, params, opt_state, pa, h0, labels, valid,
                  telemetry: bool = False):
        """One per-chip training step (shared by _build_step/_build_multi).

        ``telemetry=True`` (the program compiled by ``attach_recorder``)
        additionally returns the global L2 norm of the psum'd weight grads
        — already replicated, so it costs one reduce of each grad leaf."""
        fwd = (jax.checkpoint(self._forward, static_argnums=())
               if self.remat else self._forward)

        def loss_fn(ps):
            logits = fwd(ps, pa, h0)
            loss = self._loss_fn(logits, labels, valid)
            err = (masked_err_local(logits, labels, valid)
                   if self.loss_name == "bce" else loss)
            return loss, err

        (loss, err), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # dense weight-grad allreduce — GPU/PGCN.py:150-154 /
        # Parallel-GCN/main.c:422-425 (psum of local partials = full grad)
        grads = jax.tree.map(lambda g: lax.psum(g, AXIS), grads)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if telemetry:
            gnorm = _global_grad_norm(grads)
            return params, opt_state, loss, err, gnorm
        return params, opt_state, loss, err

    # ------------------------------------------------------- stale pipelining
    def _forward_stale(self, params, pa, h0, halos, ghalos, bases,
                       fresh: bool, gauges: bool = False):
        from ..models.gcn import gcn_forward_local_stale

        # composed mode: the stale forward rides the ring — pass the static
        # ring spec through (absent under the dense a2a carry)
        ragged = {k: self._fwd_static[k]
                  for k in ("comm_schedule", "rr_sizes", "rr_edge_sizes")
                  if k in self._fwd_static}
        out = gcn_forward_local_stale(
            params, h0, pa, halos, ghalos, bases,
            activation=self.activation,
            final_activation=self.final_activation,
            ell_buckets=self._fwd_static["ell_buckets"],
            delta=self.halo_delta,
            # the delta cache IS the bf16 wire; otherwise the stale feature
            # wire keeps the exact mode's halo_dtype semantics
            wire_dtype="bfloat16" if self.halo_delta else self.halo_dtype,
            gwire_dtype=self.halo_dtype,
            fresh=fresh,
            gauges=gauges,
            **ragged,
            **self._rep_stale_static,
        )
        if gauges:
            logits, nh, nb, qe = out
            return logits.astype("float32"), nh, nb, qe
        logits, nh, nb = out
        return logits.astype("float32"), nh, nb

    def _one_step_stale(self, params, opt_state, carry, pa, h0, labels,
                        valid, fresh: bool, telemetry: bool = False):
        """One per-chip training step under the pipelined stale exchange.

        The gradient-halo carries ride jax's cotangent machinery: the loss
        is differentiated w.r.t. ``(params, ghalos)`` and ``pspmm_stale``'s
        custom VJP returns, as the "gradient" of each ``ghalos[ℓ]``, the
        FRESH gradient exchange that becomes next step's carry.

        ``telemetry=True`` additionally returns ``(gnorm, gauges)`` — the
        drift gauges of the stale mode (``docs/observability.md``), all
        psum'd to global scalars so they come back replicated:

          * ``drift_sq[ℓ]``  — ``Σ (halo_next − halo_in)²``: the fresh
            exchange against the stale carry the step actually consumed —
            the per-layer ‖stale − fresh‖² proxy, available EVERY step
            (on a full-sync step it measures the drift the sync erased);
          * ``ref_sq[ℓ]``    — ``Σ halo_next²``, the normalizer for a
            relative drift figure;
          * ``qerr_sq[ℓ]``   — this step's halo-delta wire quantization
            residual ``Σ (full − base_next)²`` (zero without ``--halo-delta``).
        """
        halos, ghalos, bases = carry["halos"], carry["ghalos"], carry["bases"]

        def loss_fn(ps, gh):
            if telemetry:
                logits, nh, nb, qe = self._forward_stale(
                    ps, pa, h0, halos, gh, bases, fresh, gauges=True)
            else:
                logits, nh, nb = self._forward_stale(
                    ps, pa, h0, halos, gh, bases, fresh)
                qe = None
            loss = self._loss_fn(logits, labels, valid)
            err = (masked_err_local(logits, labels, valid)
                   if self.loss_name == "bce" else loss)
            return loss, (err, nh, nb, qe)

        (loss, (err, nh, nb, qe)), (grads, ngh) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, ghalos)
        # weight grads are global partial sums (exact mode's psum); the halo
        # carries are PER-CHIP state — never reduced
        grads = jax.tree.map(lambda g: lax.psum(g, AXIS), grads)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        new_carry = {"halos": nh, "ghalos": list(ngh), "bases": nb}
        if not telemetry:
            return params, opt_state, new_carry, loss, err
        import jax.numpy as jnp
        gauges = {
            "drift_sq": jnp.stack([
                lax.psum(jnp.sum(jnp.square(n - o)), AXIS)
                for n, o in zip(nh, halos)]),
            "ref_sq": jnp.stack([
                lax.psum(jnp.sum(jnp.square(n)), AXIS) for n in nh]),
            "qerr_sq": jnp.stack([lax.psum(q, AXIS) for q in qe]),
        }
        return (params, opt_state, new_carry, loss, err,
                _global_grad_norm(grads), gauges)

    def _build_step_stale(self, fresh: bool, telemetry: bool = False):
        def per_chip(params, opt_state, carry, pa, h0, labels, valid):
            carry, pa, h0, labels, valid = _unblock(
                (carry, pa, h0, labels, valid))
            out = self._one_step_stale(
                params, opt_state, carry, pa, h0, labels, valid, fresh,
                telemetry=telemetry)
            params, opt_state, carry = out[:3]
            return (params, opt_state, _reblock(carry)) + out[3:]

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(AXIS), P(), P()) + ((P(), P())
                                                       if telemetry else ()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1, 2))

    def _build_multi_stale(self, epochs: int):
        """``epochs`` STALE steps as one on-device fori_loop (the carry
        threads through the loop body; sync steps are scheduled around the
        loop by ``run_epochs``).  ``z`` enters replicated for the same
        check_rep reason as ``_build_multi``."""
        def per_chip(params, opt_state, carry, pa, h0, labels, valid, z):
            carry, pa, h0, labels, valid = _unblock(
                (carry, pa, h0, labels, valid))

            def body(i, st):
                params, opt_state, carry, losses, errs = st
                params, opt_state, carry, loss, err = self._one_step_stale(
                    params, opt_state, carry, pa, h0, labels, valid, False)
                return (params, opt_state, carry, losses.at[i].set(loss),
                        errs.at[i].set(err))

            params, opt_state, carry, losses, errs = lax.fori_loop(
                0, epochs, body, (params, opt_state, carry, z, z))
            return params, opt_state, _reblock(carry), losses, errs

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                      P()),
            out_specs=(P(), P(), P(AXIS), P(), P()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1, 2))

    def _stale_sync_due(self) -> bool:
        """Carry init (step 0) + the periodic full-sync schedule."""
        if self._stale_step_idx == 0:
            return True
        return bool(self.sync_every) and \
            self._stale_step_idx % self.sync_every == 0

    def _stale_run_one(self, data: TrainData):
        """One stale-mode optimizer step (sync or pipelined per schedule).

        With a recorder attached the telemetry programs run instead and the
        drift gauges ride along: returns ``(loss, err, extra)`` where
        ``extra`` is ``(gnorm, gauges, staleness_age, sync_step)`` under
        telemetry, else ``None``."""
        sync_step = self._stale_sync_due()
        age = self._stale_step_idx - self._last_sync_idx
        first = sync_step and self._stale_step_idx == 0
        telemetry = self.recorder is not None or (
            self.controller is not None and sync_step)
        if telemetry:
            self._ensure_tel_programs()
            prog = self._step_sync_tel if sync_step else self._step_stale_tel
            (self.params, self.opt_state, self.halo_carry, loss, err, gnorm,
             gauges) = prog(
                self.params, self.opt_state, self.halo_carry, self.pa,
                data.h0, data.labels, data.train_valid,
            )
            extra = (gnorm, gauges, age, sync_step)
            if sync_step:
                self._controller_observe(gauges, kind="stale", first=first)
        else:
            prog = self._step_sync if sync_step else self._step_stale
            (self.params, self.opt_state, self.halo_carry, loss, err) = prog(
                self.params, self.opt_state, self.halo_carry, self.pa,
                data.h0, data.labels, data.train_valid,
            )
            extra = None
        if sync_step:
            self._last_sync_idx = self._stale_step_idx
        self._stale_step_idx += 1
        # per-step feature-wire itemsize: a delta-mode SYNC step re-bases
        # with the full f32 row (ops/pspmm.py::_stale_exchange), so its
        # wire bytes are booked at 4, not the stale steps' bf16 2.
        # Composed replica × stale: a stale step's hidden exchange ships
        # the SHRUNKEN wire (replica=True booking); sync steps the full one
        self.stats.count_step(
            nlayers=self.nlayers, hidden=not sync_step,
            wire_itemsize=4 if (self.halo_delta and sync_step) else None,
            replica=bool(self.replica_budget) and not sync_step)
        return loss, err, extra

    # ---------------------------------------------------- hot-halo replicas
    def _forward_replica(self, params, pa, h0, reps, greps, fresh: bool,
                         bases=None, partial: bool = False):
        from ..models.gcn import gcn_forward_local_replica

        extra = {}
        if self.refresh_band is not None:
            extra["rep_base"] = bases
            if partial:
                extra["partial_step"] = True
                extra["band"] = float(self.refresh_band)
        out = gcn_forward_local_replica(
            params, h0, pa, reps, greps,
            activation=self.activation,
            final_activation=self.final_activation,
            ell_buckets=self._fwd_static["ell_buckets"],
            halo_dtype=self.halo_dtype,
            fresh=fresh,
            **self._rep_static,
            **extra,
        )
        if self.refresh_band is not None:
            logits, new_reps, new_bases, nships = out
            return logits.astype("float32"), new_reps, new_bases, nships
        logits, new_reps = out
        return logits.astype("float32"), new_reps, None, None

    def _one_step_replica(self, params, opt_state, carry, pa, h0, labels,
                          valid, fresh: bool, partial: bool = False,
                          telemetry: bool = False):
        """One per-chip training step under hot-halo replication.

        The gradient-replica carries ride jax's cotangent machinery exactly
        like the stale mode's ``ghalos``: the loss is differentiated w.r.t.
        ``(params, greps)`` and ``pspmm_replica``'s custom VJP returns, as
        the "gradient" of each ``greps[ℓ]``, the refreshed gradient-replica
        table on sync steps (the carry itself on replica steps).

        ``partial=True`` compiles the drift-banded PARTIAL refresh step
        (``--refresh-band``, ``pspmm_replica_partial``): the shrunken
        exchange plus the replica-only side channel of masked deltas; the
        program additionally returns the per-layer psum'd count of
        side-channel slots that actually carried a row — the booking
        figure ``CommStats.count_partial_refresh_step`` consumes.

        ``telemetry=True`` additionally returns ``(gnorm, gauges)`` — the
        replica drift gauges (``docs/replication.md``), psum'd to global
        scalars: ``drift_sq[ℓ]`` = ``Σ (rep_next − rep_in)²`` (the drift a
        refresh erased; identically zero on replica steps, whose carries
        pass through) and ``ref_sq[ℓ]`` = ``Σ rep_next²``, its normalizer.
        """
        reps, greps = carry["reps"], carry["greps"]
        bases = carry.get("rep_base")

        def loss_fn(ps, gr):
            logits, nr, nb, ns = self._forward_replica(
                ps, pa, h0, reps, gr, fresh, bases=bases, partial=partial)
            loss = self._loss_fn(logits, labels, valid)
            err = (masked_err_local(logits, labels, valid)
                   if self.loss_name == "bce" else loss)
            return loss, (err, nr, nb, ns)

        (loss, (err, nr, nb, ns)), (grads, ngr) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True)(params, greps)
        grads = jax.tree.map(lambda g: lax.psum(g, AXIS), grads)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        new_carry = {"reps": nr, "greps": list(ngr)}
        if nb is not None:
            new_carry["rep_base"] = nb
        import jax.numpy as jnp
        extra_out = ()
        if partial:
            # ACTUAL shipped side-channel rows per layer (global): the
            # booking figure — forward count; the gradient side channel
            # ships the same masked rows (count_partial books ×2)
            extra_out = (jnp.stack([lax.psum(s, AXIS) for s in ns]),)
        if not telemetry:
            return (params, opt_state, new_carry, loss, err) + extra_out
        gauges = {
            "drift_sq": jnp.stack([
                lax.psum(jnp.sum(jnp.square(n - o)), AXIS)
                for n, o in zip(nr, reps)]),
            "ref_sq": jnp.stack([
                lax.psum(jnp.sum(jnp.square(n)), AXIS) for n in nr]),
        }
        return (params, opt_state, new_carry, loss, err) + extra_out + (
            _global_grad_norm(grads), gauges)

    def _build_step_replica(self, fresh: bool, partial: bool = False,
                            telemetry: bool = False):
        def per_chip(params, opt_state, carry, pa, h0, labels, valid):
            carry, pa, h0, labels, valid = _unblock(
                (carry, pa, h0, labels, valid))
            out = self._one_step_replica(
                params, opt_state, carry, pa, h0, labels, valid, fresh,
                partial=partial, telemetry=telemetry)
            params, opt_state, carry = out[:3]
            return (params, opt_state, _reblock(carry)) + out[3:]

        n_extra = (1 if partial else 0) + (2 if telemetry else 0)
        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(AXIS), P(), P()) + (P(),) * n_extra,
        )
        return jax.jit(smapped, donate_argnums=(0, 1, 2))

    def _build_multi_replica(self, epochs: int):
        """``epochs`` REPLICA (non-refresh) steps as one on-device
        fori_loop; refresh steps are scheduled around the loop by
        ``run_epochs`` (cf. ``_build_multi_stale``)."""
        def per_chip(params, opt_state, carry, pa, h0, labels, valid, z):
            carry, pa, h0, labels, valid = _unblock(
                (carry, pa, h0, labels, valid))

            def body(i, st):
                params, opt_state, carry, losses, errs = st
                params, opt_state, carry, loss, err = \
                    self._one_step_replica(
                        params, opt_state, carry, pa, h0, labels, valid,
                        False)
                return (params, opt_state, carry, losses.at[i].set(loss),
                        errs.at[i].set(err))

            params, opt_state, carry, losses, errs = lax.fori_loop(
                0, epochs, body, (params, opt_state, carry, z, z))
            return params, opt_state, _reblock(carry), losses, errs

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                      P()),
            out_specs=(P(), P(), P(AXIS), P(), P()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1, 2))

    def _replica_sync_due(self) -> bool:
        """Carry init (step 0) + the periodic refresh schedule.  With
        ``sync_every=0`` only step 0 refreshes — replicas then age for the
        whole run (the drift gauges are the signal that that was too
        lax)."""
        if self._rep_step_idx == 0:
            return True
        return bool(self.sync_every) and \
            self._rep_step_idx % self.sync_every == 0

    def _replica_run_one(self, data: TrainData):
        """One replica-mode optimizer step (refresh or shrunken-wire per
        schedule).  Returns ``(loss, err, extra)`` with ``extra`` =
        ``(gnorm, gauges, refresh_age, sync_step, first, refresh_rows)``
        under telemetry.

        With ``--refresh-band`` set, the scheduled refresh steps (every
        refresh EXCEPT step 0, which must initialize the carries and
        baselines in full) run the PARTIAL program instead: the per-layer
        counts of actually-shipped side-channel rows come back as a step
        output and are booked at their true value
        (``CommStats.count_partial_refresh_step``)."""
        sync_step = self._replica_sync_due()
        age = self._rep_step_idx - self._last_refresh_idx
        first = sync_step and self._rep_step_idx == 0
        partial = (sync_step and not first
                   and self.refresh_band is not None)
        telemetry = self.recorder is not None or (
            self.controller is not None and sync_step)
        refresh_rows = None
        args = (self.params, self.opt_state, self.replica_carry, self.pa,
                data.h0, data.labels, data.train_valid)
        if telemetry:
            self._ensure_tel_programs()
            prog = (self._step_rep_partial_tel if partial
                    else self._step_rep_sync_tel if sync_step
                    else self._step_rep_tel)
            out = prog(*args)
            (self.params, self.opt_state, self.replica_carry, loss,
             err) = out[:5]
            if partial:
                refresh_rows = np.asarray(out[5]).astype(np.int64)
            gnorm, gauges = out[-2], out[-1]
            extra = (gnorm, gauges, age, sync_step, first, refresh_rows)
            if sync_step:
                self._controller_observe(gauges, kind="replica",
                                         first=first)
        else:
            prog = (self._step_rep_partial if partial
                    else self._step_rep_sync if sync_step
                    else self._step_rep)
            out = prog(*args)
            (self.params, self.opt_state, self.replica_carry, loss,
             err) = out[:5]
            if partial:
                refresh_rows = np.asarray(out[5]).astype(np.int64)
            extra = None
        if sync_step:
            self._last_refresh_idx = self._rep_step_idx
        self._rep_step_idx += 1
        # replica steps ship the shrunken wire (and the shrunken TRUE
        # volume — replicated rows genuinely leave the exchange); full
        # refresh steps ship the full exact exchange; PARTIAL refresh
        # steps ship the shrunken wire plus the side channel, booked at
        # the ACTUAL per-layer shipped rows read back above
        if partial:
            self.stats.count_partial_refresh_step(
                nlayers=self.nlayers,
                refresh_rows=[int(x) for x in refresh_rows],
                wire_rows=int(self.plan.partial_refresh_wire_rows))
        else:
            self.stats.count_step(nlayers=self.nlayers,
                                  replica=not sync_step)
        return loss, err, extra

    def _run_epochs_replica(self, data: TrainData, epochs: int, sync: bool):
        return self._run_epochs_carried(
            data, epochs, sync,
            sync_due=self._replica_sync_due, run_one=self._replica_run_one,
            multi=self._multi_rep, build_multi=self._build_multi_replica,
            carry_attr="replica_carry", idx_attr="_rep_step_idx",
            count_kwargs={"replica": True})

    def _ensure_tel_programs(self) -> None:
        """Compile the telemetry step variants on first need — attached
        recorder (``attach_recorder``) or an active controller (which
        reads the drift gauges at sync/refresh steps even without a run
        directory).  ``jax.jit`` wrappers are lazy, so building them
        eagerly costs nothing until dispatch."""
        if getattr(self, "_step_tel", None) is None:
            self._step_tel = self._build_step(telemetry=True)
        if self.halo_staleness and \
                getattr(self, "_step_stale_tel", None) is None:
            self._step_stale_tel = self._build_step_stale(
                fresh=False, telemetry=True)
            self._step_sync_tel = self._build_step_stale(
                fresh=True, telemetry=True)
        if self.replica_budget and not self.halo_staleness and \
                getattr(self, "_step_rep_tel", None) is None:
            self._step_rep_tel = self._build_step_replica(
                fresh=False, telemetry=True)
            self._step_rep_sync_tel = self._build_step_replica(
                fresh=True, telemetry=True)
            if self.refresh_band is not None:
                self._step_rep_partial_tel = self._build_step_replica(
                    fresh=False, partial=True, telemetry=True)

    def _controller_observe(self, gauges, kind: str,
                            first: bool = False) -> None:
        """Feed a sync/refresh step's measured drift to the controller and
        apply its (possibly unchanged) ``sync_every`` target.  The
        INITIALIZING refresh is skipped — its in-graph gauge compares
        against the zero-init carry, so it measures initialization
        magnitude, not drift (the PR-10 lesson).  Every retune decision is
        appended to the manifest ``comm_schedule.controller`` log."""
        if self.controller is None or first:
            return
        d = np.sqrt(np.maximum(
            np.asarray(gauges["drift_sq"], np.float64), 0))
        r = np.sqrt(np.maximum(np.asarray(gauges["ref_sq"], np.float64), 0))
        rel = float(np.max(d / np.maximum(r, 1e-30))) if d.size else 0.0
        step_idx = (self._rep_step_idx if kind == "replica"
                    else self._stale_step_idx)
        self.sync_every = self.controller.observe(step_idx, rel)
        self.comm_decision["controller"] = self.controller.log()
        if self.recorder is not None:
            self.recorder.set_comm_schedule(self.comm_decision)

    @staticmethod
    def _replica_fields(gauges: dict, age: int, sync_step: bool,
                        replica_rows: int,
                        first_refresh: bool = False,
                        refresh_rows=None,
                        refresh_wire_rows: int | None = None) -> dict:
        """Host-side rendering of the in-graph replica gauges into the
        schema's ``replica`` block (``obs.schema.REPLICA_KEYS``): per-layer
        ‖replica − fresh‖ at each refresh (zero between refreshes — fresh
        values only exist on the wire when a refresh ships them) plus the
        refresh age of the consumed tables.  ``first_refresh`` (step 0)
        reports ZERO drift: the in-graph gauge there compares against the
        zero-initialized carry, so it measures initialization magnitude,
        not drift any refresh erased — feeding it to the operator would
        dominate every max/mean in the rendered report."""
        import numpy as np

        d = np.sqrt(np.maximum(np.asarray(gauges["drift_sq"], np.float64),
                               0))
        r = np.sqrt(np.maximum(np.asarray(gauges["ref_sq"], np.float64), 0))
        if first_refresh:
            d = np.zeros_like(d)
        out = {
            "refresh_age": int(age),
            "sync_step": bool(sync_step),
            "replica_rows": int(replica_rows),
            "replica_drift_rms": [float(x) for x in d],
            "replica_drift_rel": [float(x / max(y, 1e-30))
                                  for x, y in zip(d, r)],
        }
        if refresh_rows is not None:
            # drift-banded PARTIAL refresh (--refresh-band): the ACTUAL
            # per-layer side-channel rows this step shipped (each consumer
            # copy counts, like every send-volume gauge) — the per-step
            # face of CommStats' partial_refresh_* totals, which must
            # reconcile exactly (docs/replication.md)
            out["refresh_kind"] = "partial"
            out["refresh_rows"] = [int(x) for x in refresh_rows]
            out["refresh_wire_rows"] = int(refresh_wire_rows or 0)
        elif sync_step:
            out["refresh_kind"] = "full"
        return out

    def _build_step(self, mesh=None, telemetry: bool = False):
        def per_chip(params, opt_state, pa, h0, labels, valid):
            pa, h0, labels, valid = _unblock((pa, h0, labels, valid))
            return self._one_step(params, opt_state, pa, h0, labels, valid,
                                  telemetry=telemetry)

        smapped = jax.shard_map(
            per_chip,
            mesh=mesh if mesh is not None else self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(), P()) + ((P(),) if telemetry else ()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def lower_step(self, mesh=None, fin: int | None = None,
                   kind: str = "step"):
        """AOT-lower ONE train step — no compilation, no execution.

        ``mesh`` may be an arbitrary mesh, including a device-less
        ``jax.experimental.topologies`` mesh (e.g. an 8-chip v5e slice this
        host does not have); ``None`` uses the trainer's own mesh.  Inputs
        are ShapeDtypeStructs shaped like this trainer's live arrays, so
        the lowered module is exactly the program ``step()`` runs, just
        targeted at the given topology.

        ``kind`` selects which of the trainer's step programs to lower:
        ``'step'`` the exact-mode step; ``'stale'`` / ``'sync'`` the
        pipelined stale-mode step and its periodic full-sync flavor
        (``halo_staleness=1`` trainers only); ``'rep'`` / ``'rep_sync'``
        the hot-halo-replication step (shrunken wire) and its refresh
        flavor (``replica_budget>0`` trainers only).  The carry-threading
        kinds include the carry inputs and lower on the trainer's own mesh
        — those builders are mesh-bound.

        Two consumers: the overlap evidence test
        (``tests/test_overlap_hlo.py``) compiles the real multi-chip TPU
        program and asserts the async all-to-all start/done schedule
        brackets the local slot passes — the compiled-schedule form of the
        reference's Irecv/compute/Waitany overlap
        (``Parallel-GCN/main.c:238-299``); and the static-analysis HLO
        audit (``sgcn_tpu/analysis``) lowers every supported mode on the
        virtual 8-dev mesh and checks the collective census / wire dtype /
        donation contracts of the lowered module."""
        from jax.sharding import NamedSharding

        if kind not in ("step", "stale", "sync", "rep", "rep_sync",
                        "rep_partial"):
            raise ValueError(f"unknown step kind {kind!r}")
        if kind in ("stale", "sync") and not self.halo_staleness:
            raise ValueError(
                f"kind={kind!r} lowers the stale-mode programs; this "
                "trainer runs exact mode (halo_staleness=0)")
        if kind in ("rep", "rep_sync") and not (self.replica_budget
                                               and not self.halo_staleness):
            raise ValueError(
                f"kind={kind!r} lowers the replica-mode programs; this "
                "trainer runs without standalone replication (the composed "
                "replica × stale programs lower via kind='stale'/'sync')")
        if kind == "rep_partial" and self.refresh_band is None:
            raise ValueError(
                "kind='rep_partial' lowers the --refresh-band partial "
                "refresh program; this trainer runs full refreshes")
        if kind != "step" and mesh not in (None, self.mesh):
            raise ValueError(
                "carry-threading step programs are built against the "
                "trainer's own mesh; pass mesh=None for "
                "kind='stale'/'sync'/'rep'/'rep_sync'")
        mesh = self.mesh if mesh is None else mesh
        fin = self.fin if fin is None else fin
        rep = NamedSharding(mesh, P())
        shd = NamedSharding(mesh, P(AXIS))
        k, b = self.plan.k, self.plan.b

        def sds(x, sharding):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

        params = jax.tree.map(lambda x: sds(x, rep), self.params)
        opt_state = jax.tree.map(lambda x: sds(x, rep), self.opt_state)
        pa = jax.tree.map(lambda x: sds(x, shd), self.pa)
        h0 = jax.ShapeDtypeStruct((k, b, fin), np.float32, sharding=shd)
        labels = jax.ShapeDtypeStruct((k, b), np.int32, sharding=shd)
        valid = jax.ShapeDtypeStruct((k, b), np.float32, sharding=shd)
        if kind != "step":
            live = (self.halo_carry if kind in ("stale", "sync")
                    else self.replica_carry)
            carry = jax.tree.map(lambda x: sds(x, shd), live)
            prog = {"stale": getattr(self, "_step_stale", None),
                    "sync": getattr(self, "_step_sync", None),
                    "rep": getattr(self, "_step_rep", None),
                    "rep_sync": getattr(self, "_step_rep_sync", None),
                    "rep_partial": getattr(self, "_step_rep_partial",
                                           None)}[kind]
            return prog.lower(params, opt_state, carry, pa, h0, labels,
                              valid)
        return self._build_step(mesh=mesh).lower(
            params, opt_state, pa, h0, labels, valid)

    def _build_multi(self, epochs: int):
        """Compile `epochs` training steps as ONE on-device fori_loop.

        One host dispatch per call instead of one per epoch: through this
        box's tunnel a dispatch costs ~110 ms, which at bench scale is larger
        than the epoch itself — the loop makes multi-epoch timing reflect
        device time only (a host-attached TPU pays µs either way).  Semantics
        are identical to `epochs` sequential ``step()`` calls; per-epoch
        losses come back as an array (the reference's per-epoch loss print,
        ``GPU/PGCN.py:223-224``, reads them after the run).

        The per-epoch loss/err accumulators enter as a REPLICATED argument
        (``z``) rather than an in-body ``jnp.zeros`` literal: the loop carry
        must hold one replication type throughout, and a literal's type is
        untracked while the psum'd losses written into it are replicated —
        shard_map's check_rep rejects that pairing (observed on jaxlib
        0.4.37; an argument with ``P()`` spec is tracked replicated from the
        start).  Same math either way.
        """
        def per_chip(params, opt_state, pa, h0, labels, valid, z):
            pa, h0, labels, valid = _unblock((pa, h0, labels, valid))

            def body(i, carry):
                params, opt_state, losses, errs = carry
                params, opt_state, loss, err = self._one_step(
                    params, opt_state, pa, h0, labels, valid)
                return (params, opt_state, losses.at[i].set(loss),
                        errs.at[i].set(err))

            params, opt_state, losses, errs = lax.fori_loop(
                0, epochs, body, (params, opt_state, z, z))
            return params, opt_state, losses, errs

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
            out_specs=(P(), P(), P(), P()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def run_epochs(self, data: TrainData, epochs: int, sync: bool = True):
        """Run ``epochs`` steps in one device program; return per-epoch losses.

        ``sync=False`` returns the on-device loss array without blocking.

        Stale mode runs the same on-device loop over PIPELINED steps, with
        the full-sync steps (carry init + every ``sync_every``-th step)
        dispatched individually around the loop segments.

        With a recorder attached, epochs dispatch as individual ``step()``
        calls so each emits its JSONL event — per-step observability is
        exactly what the fused loop cannot provide (documented trade;
        ``attach_recorder``)."""
        if self.recorder is not None:
            losses = np.asarray([self.step(data) for _ in range(epochs)],
                                np.float32)
            return losses
        if self.halo_staleness:
            return self._run_epochs_stale(data, epochs, sync)
        if self.replica_budget:
            return self._run_epochs_replica(data, epochs, sync)
        if epochs not in self._multi:
            self._multi[epochs] = self._build_multi(epochs)
        self.params, self.opt_state, losses, errs = self._multi[epochs](
            self.params, self.opt_state, self.pa, data.h0, data.labels,
            data.train_valid, np.zeros((epochs,), np.float32),
        )
        self.last_err = errs[-1]        # keep step()'s scalar contract
        for _ in range(epochs):
            self.stats.count_step(nlayers=self.nlayers)
        return np.asarray(losses) if sync else losses

    def _run_epochs_stale(self, data: TrainData, epochs: int, sync: bool):
        return self._run_epochs_carried(
            data, epochs, sync,
            sync_due=self._stale_sync_due, run_one=self._stale_run_one,
            multi=self._multi_stale, build_multi=self._build_multi_stale,
            carry_attr="halo_carry", idx_attr="_stale_step_idx",
            # composed replica × stale: the fused stale steps ship the
            # shrunken wire AND hide it — book both
            count_kwargs={"hidden": True,
                          "replica": bool(self.replica_budget)})

    def _run_epochs_carried(self, data: TrainData, epochs: int, sync: bool,
                            *, sync_due, run_one, multi, build_multi,
                            carry_attr: str, idx_attr: str,
                            count_kwargs: dict):
        """The shared carried-epoch loop of the stale and replica modes:
        sync/refresh steps (per ``sync_due``) dispatch individually through
        ``run_one`` (which also advances the step index and books stats);
        the stretches between them run as ONE on-device fori_loop over the
        ``build_multi`` program, with the carry threading through
        ``carry_attr``.  One implementation — the two modes differ only in
        which carry, which sync predicate, and how ``count_step`` books
        the fused steps (hidden vs replica)."""
        import jax.numpy as jnp

        parts, err_parts = [], []
        left = epochs
        while left > 0:
            if sync_due():
                loss, err, _ = run_one(data)
                parts.append(jnp.reshape(loss, (1,)))
                err_parts.append(jnp.reshape(err, (1,)))
                left -= 1
                continue
            run = left
            if self.sync_every:
                until_sync = (self.sync_every
                              - getattr(self, idx_attr) % self.sync_every)
                run = min(left, until_sync)
            if run not in multi:
                multi[run] = build_multi(run)
            (self.params, self.opt_state, carry, losses,
             errs) = multi[run](
                self.params, self.opt_state, getattr(self, carry_attr),
                self.pa, data.h0, data.labels, data.train_valid,
                np.zeros((run,), np.float32),
            )
            setattr(self, carry_attr, carry)
            setattr(self, idx_attr, getattr(self, idx_attr) + run)
            for _ in range(run):
                self.stats.count_step(nlayers=self.nlayers, **count_kwargs)
            parts.append(losses)
            err_parts.append(errs)
            left -= run
        losses = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        errs = (err_parts[0] if len(err_parts) == 1
                else jnp.concatenate(err_parts))
        self.last_err = errs[-1]
        return np.asarray(losses) if sync else losses

    def _build_eval(self):
        def per_chip(params, pa, h0, labels, valid):
            pa, h0, labels, valid = _unblock((pa, h0, labels, valid))
            logits = self._forward(params, pa, h0)
            # eval loss uses the SAME objective as training, so train/eval
            # losses are comparable under --loss bce too (the MPI stack
            # reports the one flavor it trains with,
            # Parallel-GCN/main.c:318-335)
            loss = self._loss_fn(logits, labels, valid)
            acc = masked_accuracy_local(logits, labels, valid)
            return loss, acc, logits[None]

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(AXIS)),
        )
        return jax.jit(smapped)

    # ------------------------------------------------------ run telemetry
    def attach_recorder(self, recorder) -> None:
        """Attach a ``sgcn_tpu.obs.RunRecorder``: compiles telemetry step
        variants (grad-norm out; drift gauges in stale mode) and switches
        ``step``/``run_epochs`` to per-step event emission.  ``run_epochs``
        then dispatches one program per step instead of the fused on-device
        epoch loop — per-step wall times and loss readbacks are exactly what
        the fused loop cannot surface; detach (``recorder=None``) to get the
        one-dispatch path back."""
        self.recorder = recorder
        self.spans.recorder = recorder   # span exits now emit span events
        if getattr(self, "comm_decision", None):
            # the schedule-selection inputs (resolve_comm_schedule) land in
            # the run manifest, so an 'auto' pick is reconstructible from
            # the run directory alone (docs/observability.md)
            recorder.set_comm_schedule(self.comm_decision)
        if getattr(self, "memory", None) is not None:
            # the analytic footprint (model-only here — the measured join
            # needs a compiled program; the audit and the serve engine add
            # it) lands in the manifest's schema-v6 memory block
            recorder.set_memory(self.memory.block())
        self._ensure_tel_programs()

    def _step_cost_model(self, sync_step: bool = True):
        """Per-step-kind analytic cost: under ``--halo-delta`` the FEATURE
        wire is bf16 on stale steps but full f32 on (re-base) sync steps,
        while the gradient wire keeps ``--halo-dtype`` — so the cost model
        takes a per-direction wire-itemsize split and is cached per step
        kind (the obs glossary documents the split).  Under
        ``--replica-budget`` a non-sync step prices the SHRUNKEN exchange
        (``step_cost(replica=True)``): replicated rows leave both the true
        and the wire volume, which is exactly what ``count_step``'s
        replica booking accumulates — the gauges reconcile per step."""
        key = bool(sync_step)
        if key not in self._cost_cache:
            from ..obs.attribution import step_cost
            wire = None
            if self.model == "gcn":
                if self.halo_delta and sync_step:
                    # the re-base wire ships the FULL f32 row regardless of
                    # --halo-dtype (ops/pspmm.py fresh-delta path) — must
                    # match count_step's wire_itemsize=4 override exactly
                    fwd = 4
                elif self.halo_dtype == "bfloat16" or self.halo_delta:
                    fwd = 2
                else:
                    fwd = None
                bwd = 2 if self.halo_dtype == "bfloat16" else None
                if fwd is not None or bwd is not None:
                    wire = (fwd, bwd)
            self._cost_cache[key] = step_cost(
                self.plan, self.fin, self.widths,
                compute_dtype=self.compute_dtype,
                wire_itemsize=wire,
                comm_schedule=self.comm_schedule,
                model=self.model,
                replica=bool(self.replica_budget) and not sync_step)
        return self._cost_cache[key]

    def _record_step_event(self, loss: float, err, gnorm, wall_s: float,
                           drift: dict | None,
                           replica: dict | None = None) -> None:
        from ..obs.attribution import roofline_fields
        from ..obs.tracing import measured_vs_model_block

        roofline = mvm = None
        # same honesty gate as bench.py: the gather model describes the
        # bucketed slot-pass aggregators (GCN ELL, GAT combined-edge) — for
        # the Pallas VMEM kernel it would describe a program that didn't
        # run, so omit it rather than mislead.  GAT attributes against its
        # own table-form-aware model (attribution.step_cost(model='gat')),
        # which is what makes the wire gauges reconcile with CommStats'.
        if "pallas_tb" not in self._fwd_static:
            sync_like = drift is None or bool(drift.get("sync_step"))
            if replica is not None:
                # replica steps price the shrunken exchange; FULL refresh
                # steps the full one; PARTIAL refresh steps the shrunken
                # exchange plus the side channel at the step's ACTUAL
                # shipped rows (add_partial_refresh — CommStats books the
                # identical figures, so the two reconcile per step).
                # Exposure is NOT affected — every replica-mode exchange
                # has a same-step consumer (unlike staleness)
                partial = replica.get("refresh_kind") == "partial"
                sync_like = bool(replica.get("sync_step")) and not partial
            cost = self._step_cost_model(sync_like)
            if replica is not None and partial:
                from ..obs.attribution import add_partial_refresh
                bwd_item = (self.stats.wire_itemsize_bwd
                            if self.stats.wire_itemsize_bwd is not None
                            else self.stats.wire_itemsize)
                cost = add_partial_refresh(
                    cost, replica["refresh_rows"],
                    replica["refresh_wire_rows"],
                    self.stats.wire_itemsize, bwd_item)
            ex_step = 2 * self.nlayers      # this step's exchanges
            exposed_step = 0 if (drift is not None
                                 and not drift.get("sync_step")) else ex_step
            roofline = roofline_fields(cost, wall_s,
                                       exchanges=ex_step,
                                       exposed_exchanges=exposed_step)
            # measured-vs-analytic reconciliation: the span-measured step
            # time joined against the same cost model, per component —
            # wall_s here IS the step span's duration, so the block's
            # phase_total_s reconciles with PhaseTimer.report() exactly
            mvm = measured_vs_model_block(cost, wall_s)
        self.recorder.record_step(
            step=self._step_count, loss=loss, wall_s=wall_s,
            err=float(err) if self.loss_name == "bce" else None,
            grad_norm=float(gnorm) if gnorm is not None else None,
            comm=self.stats.report(),
            phases=self.timer.report() or None,
            drift=drift,
            replica=replica,
            roofline=roofline,
            measured_vs_model=mvm,
        )

    @staticmethod
    def _drift_fields(gauges: dict, age: int, sync_step: bool,
                      rr_sizes: tuple | None = None) -> dict:
        """Host-side rendering of the in-graph gauge scalars (see
        ``_one_step_stale``) into the schema's drift block.

        ``rr_sizes`` (composed stale × ragged mode only): adds the
        per-round staleness-age vector ``round_age`` — for each ring round,
        the age of the buffer the step CONSUMED (0 on a sync step: received
        this step; the staleness age on a stale step: carried from t−1;
        null for rounds with S_d = 0, which ship nothing).  Uniform today
        (all rounds share one sync schedule) but per-round by construction,
        so ``--sync-every`` tuning stays observable if round scheduling
        ever diverges (``scripts/obs_report.py`` renders it)."""
        import numpy as np

        d = np.sqrt(np.maximum(np.asarray(gauges["drift_sq"], np.float64), 0))
        r = np.sqrt(np.maximum(np.asarray(gauges["ref_sq"], np.float64), 0))
        q = np.sqrt(np.maximum(np.asarray(gauges["qerr_sq"], np.float64), 0))
        out = {
            "staleness_age": int(age),
            "sync_step": bool(sync_step),
            "halo_drift_rms": [float(x) for x in d],
            "halo_drift_rel": [float(x / max(y, 1e-30))
                               for x, y in zip(d, r)],
            "halo_quant_err_rms": [float(x) for x in q],
        }
        if rr_sizes is not None:
            out["round_age"] = [None if sd == 0
                                else (0 if sync_step else int(age))
                                for sd in rr_sizes]
        return out

    # ------------------------------------------------- checkpoint/resume state
    # The carry attribute (at most one exists) whose leaves a full-state
    # checkpoint must persist: the stale-halo carry subsumes the replica
    # tables under the composed mode, so the two are mutually exclusive.
    def _carry_attr(self) -> str | None:
        if self.halo_staleness:
            return "halo_carry"
        if self.replica_budget:
            return "replica_carry"
        return None

    def resume_state(self) -> tuple[dict, list]:
        """``(state, carry_leaves)`` — everything beyond (params, opt_state)
        a bit-identical resume needs (``docs/resilience.md``):

          * the step counters that drive the sync/refresh SCHEDULE
            (``_stale_step_idx``/``_rep_step_idx`` and their last-sync
            anchors) — without them a resumed stale run re-runs the
            initializing full-sync and diverges from the uninterrupted
            trajectory on the very first step;
          * the EFFECTIVE ``sync_every`` plus the controller's retune log
            (a mid-run retune is algorithmic state, not configuration);
          * the cumulative CommStats gauges, so the end-of-run comm report
            reconciles across the seam;
          * the stale/replica carry leaves (host copies, f32) — the
            PipeGCN/CaPGNN algorithmic state itself.

        ``state`` is JSON-able; ``carry_leaves`` is a flat list of numpy
        arrays in ``jax.tree`` order for the live carry structure."""
        state: dict = {
            "step_count": int(self._step_count),
            "sync_every": int(self.sync_every),
            "comm_stats": self.stats.state(),
        }
        if self.halo_staleness:
            state["stale_step_idx"] = int(self._stale_step_idx)
            state["last_sync_idx"] = int(self._last_sync_idx)
        if self.replica_budget and not self.halo_staleness:
            state["rep_step_idx"] = int(self._rep_step_idx)
            state["last_refresh_idx"] = int(self._last_refresh_idx)
        if self.controller is not None:
            state["controller"] = self.controller.state()
        carry_leaves: list = []
        attr = self._carry_attr()
        if attr is not None:
            live = jax.tree.leaves(getattr(self, attr))
            if any(not getattr(x, "is_fully_addressable", True)
                   for x in live):
                # multi-process mesh: the carry is P(AXIS)-sharded across
                # hosts, so the coordinator cannot fetch it — fail with
                # the repo's standard clean deferral instead of the
                # cryptic non-addressable-devices RuntimeError np.asarray
                # would raise mid-save (params/opt_state are replicated
                # and stay checkpointable; exact mode is unaffected)
                raise ValueError(
                    "full-state checkpointing of the stale/replica carry "
                    "is single-process for now: the carry is sharded "
                    "across hosts and the coordinator cannot fetch it — "
                    "run exact mode for multi-host durable checkpoints, "
                    "or checkpoint carried modes from a single-process "
                    "run (docs/resilience.md)")
            state["carry"] = attr
            carry_leaves = [np.asarray(x) for x in live]
            state["n_carry"] = len(carry_leaves)
        return state, carry_leaves

    def restore_resume_state(self, state: dict, carry_leaves=None) -> None:
        """Restore ``resume_state()`` output onto a trainer built with the
        SAME flags (plan, mode levers, widths) — the checkpoint loader
        validates shape/mode agreement and raises clear errors before
        calling this; here the carry is re-sharded exactly like its
        zero-init was."""
        self._step_count = int(state.get("step_count", 0))
        if "sync_every" in state:
            self.sync_every = int(state["sync_every"])
        if self.halo_staleness:
            self._stale_step_idx = int(state.get("stale_step_idx", 0))
            self._last_sync_idx = int(state.get("last_sync_idx", 0))
        if self.replica_budget and not self.halo_staleness:
            self._rep_step_idx = int(state.get("rep_step_idx", 0))
            self._last_refresh_idx = int(state.get("last_refresh_idx", 0))
        if self.controller is not None and state.get("controller"):
            self.controller.load_state(state["controller"])
            self.comm_decision["controller"] = self.controller.log()
        if state.get("comm_stats"):
            self.stats.load_state(state["comm_stats"])
        attr = self._carry_attr()
        if attr is not None and carry_leaves:
            live = getattr(self, attr)
            treedef = jax.tree.structure(live)
            carry = jax.tree.unflatten(treedef, list(carry_leaves))
            setattr(self, attr, shard_stacked(self.mesh, carry))

    # ------------------------------------------------------------------- api
    def step(self, data: TrainData, sync: bool = True):
        """One training step.  ``sync=True`` (default) blocks on the loss
        scalar and returns a float — the per-epoch readback the reference's
        loss print implies (``GPU/PGCN.py:223-224``).  ``sync=False`` returns
        the on-device loss array so callers can pipeline many steps and pay
        one host round-trip at the end (the tunneled dev chip has ~90 ms
        round-trip latency that would otherwise swamp epoch timings).

        With a recorder attached, every step additionally appends one JSONL
        event (loss, grad-norm, wall time, cumulative comm split, roofline
        attribution, stale-mode drift gauges) — the readback this implies
        makes ``sync=False`` behave like ``sync=True`` for timing purposes."""
        if self.halo_staleness:
            # under a recorder, the step span brackets dispatch AND the loss
            # readback (the sync point), so its duration is the measured
            # step time the event's wall_s and measured_vs_model block both
            # carry; nullcontext keeps ONE copy of the step bookkeeping for
            # the plain path (which stays readback-free under sync=False)
            cm = (self.spans.span("step", step=self._step_count + 1)
                  if self.recorder is not None else contextlib.nullcontext())
            with cm as sp:
                loss, err, extra = self._stale_run_one(data)
                if self.recorder is not None:
                    loss = float(loss)
            self.last_err = err
            self._step_count += 1
            if self.recorder is not None:
                gnorm, gauges, age, sync_step = extra
                self._record_step_event(
                    loss, err, gnorm, sp.dur_s,
                    drift=self._drift_fields(
                        gauges, age, sync_step,
                        rr_sizes=(self.plan.rr_sizes
                                  if self.comm_schedule == "ragged"
                                  else None)))
                return loss
            return float(loss) if sync else loss
        if self.replica_budget:
            cm = (self.spans.span("step", step=self._step_count + 1)
                  if self.recorder is not None else contextlib.nullcontext())
            with cm as sp:
                loss, err, extra = self._replica_run_one(data)
                if self.recorder is not None:
                    loss = float(loss)
            self.last_err = err
            self._step_count += 1
            if self.recorder is not None:
                gnorm, gauges, age, sync_step, first, rrows = extra
                self._record_step_event(
                    loss, err, gnorm, sp.dur_s, drift=None,
                    replica=self._replica_fields(
                        gauges, age, sync_step, self.plan.replica_rows,
                        first_refresh=first, refresh_rows=rrows,
                        refresh_wire_rows=(
                            int(self.plan.partial_refresh_wire_rows)
                            if rrows is not None else None)))
                return loss
            return float(loss) if sync else loss
        if self.recorder is not None:
            with self.spans.span("step", step=self._step_count + 1) as sp:
                self.params, self.opt_state, loss, err, gnorm = \
                    self._step_tel(
                        self.params, self.opt_state, self.pa, data.h0,
                        data.labels, data.train_valid,
                    )
                loss = float(loss)      # readback = the span's sync point
            self.last_err = err
            self.stats.count_step(nlayers=self.nlayers)
            self._step_count += 1
            self._record_step_event(loss, err, gnorm, sp.dur_s, drift=None)
            return loss
        self.params, self.opt_state, loss, err = self._step(
            self.params, self.opt_state, self.pa, data.h0, data.labels,
            data.train_valid,
        )
        self.last_err = err   # the MPI stack's `err` metric under loss='bce'
        self.stats.count_step(nlayers=self.nlayers)
        self._step_count += 1
        return float(loss) if sync else loss

    def evaluate(self, data: TrainData) -> tuple[float, float]:
        with self.spans.span("eval") as sp:
            loss, acc, _ = self._eval(
                self.params, self.pa, data.h0, data.labels, data.eval_valid
            )
            loss, acc = float(loss), float(acc)
        self.stats.count_forward(nlayers=self.nlayers)
        if self.recorder is not None:
            self.recorder.record_eval(step=self._step_count, loss=loss,
                                      acc=acc, wall_s=sp.dur_s)
        return loss, acc

    def predict(self, data: TrainData) -> np.ndarray:
        """Global (n, nout) logits in original vertex order."""
        _, _, logits = self._eval(
            self.params, self.pa, data.h0, data.labels, data.eval_valid
        )
        self.stats.count_forward(nlayers=self.nlayers)
        return self.plan.gather_rows(np.asarray(logits))

    @property
    def nlayers(self) -> int:
        return len(self.params)

    def fit(
        self,
        data: TrainData,
        epochs: int = 5,
        warmup: int = 1,
        verbose: bool = True,
    ) -> dict:
        """Epoch loop with reference-style timing: ``warmup`` untimed epochs,
        then wall-clock over the timed ones (``GPU/PGCN.py:202-228``).

        Phase boundaries route through ``self.spans`` (the measured-span
        layer over the CAGNET-vocabulary ``PhaseTimer``) with a ``sync=``
        callable at each block_until_ready boundary — the SAME accounting
        the per-step JSONL events snapshot, so ``report()['phases']`` and
        the event stream cannot disagree.  Under a recorder, ``step()``
        opens its own nested ``step`` span inside each epoch's
        ``train_step`` span, so the epoch totals read from the timer's
        INCLUSIVE side (the nested span claims the self time)."""
        data = TrainData(**shard_stacked(self.mesh, vars(data)))
        history: list[float] = []
        # fit() may be re-entered — measure the delta, inclusive of any
        # nested step spans the telemetry path opens
        t_prior = self.timer.inclusive_total("train_step")
        with self.spans.span("warmup", sync=lambda: self.params):
            for _ in range(warmup):
                self.step(data)
        for ep in range(epochs):
            with self.spans.span("train_step", sync=lambda: self.params):
                loss = self.step(data)
            history.append(loss)
            if verbose:
                print(f"epoch {ep}: loss {loss:.6f}", flush=True)
        elapsed = self.timer.inclusive_total("train_step") - t_prior
        report = self.stats.report()
        report.update(
            epochs=epochs,
            elapsed_s=elapsed,
            epoch_s=elapsed / max(epochs, 1),
            loss_history=history,
            phases=self.timer.report(),
        )
        if self.loss_name == "bce":
            # rank-0 err line of the MPI stack (Parallel-GCN/main.c:322-323)
            report["err"] = float(self.last_err)
        if self.recorder is not None:
            self.recorder.record_summary(
                {k: v for k, v in report.items() if k != "loss_history"})
        return report
