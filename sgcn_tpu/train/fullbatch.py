"""Full-batch distributed GCN trainer over a 1D vertex-parallel mesh.

Reference equivalents: the epoch loop of ``GPU/PGCN.py:162-238`` (NCCL/Gloo)
and ``Parallel-GCN/main.c:166-453`` (MPI+GraphBLAS).  Structure preserved:

  * one graph part per chip; weights replicated; per-step gradient allreduce
    (here ``lax.psum`` over the mesh) — ``GPU/PGCN.py:150-154``;
  * synchronized initialization (shared PRNG seed instead of the reference's
    init-allreduce, ``GPU/PGCN.py:156-160``);
  * a warm-up step excluded from timing, per-epoch wall-clock aggregated MAX
    over ranks (``GPU/PGCN.py:202-228``) — under jit all chips run the same
    program, so host wall-clock of the blocking step IS the max;
  * end-of-run comm statistics in the reference's vocabulary
    (``GPU/PGCN.py:230-238``, ``Parallel-GCN/main.c:506-524``).

The whole train step — L forward exchanges+SpMMs, loss, L backward
exchanges+SpMMs, grad psum, Adam update — is ONE jitted ``shard_map`` program:
XLA schedules the collectives asynchronously against local compute, which is
the compiler-native form of the reference's Irecv/compute/Waitany overlap
(``Parallel-GCN/main.c:238-299``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models.gat import GAT_PLAN_FIELDS, gat_forward_local, init_gat_params
from ..models.gcn import (
    gcn_forward_local,
    gcn_plan_fields,
    init_gcn_params,
    masked_accuracy_local,
    masked_err_local,
    masked_sigmoid_bce_local,
    masked_softmax_xent_local,
)
from ..parallel.mesh import AXIS, make_mesh_1d, replicate, shard_stacked
from ..parallel.plan import CommPlan
from ..utils.stats import CommStats

# model registry: name → (param init, per-chip forward, plan→fields shipped
# to the device). GAT is the reference's PGAT capability (GPU/PGAT.py) on the
# same trainer scaffold — like the reference, only the nn.Module differs
# between PGCN.py and PGAT.py. GCN ships the ELL fast-path arrays for
# symmetric Â (split COO otherwise); GAT the combined edge list its
# edge-softmax needs.
MODELS = {
    # name -> (init, forward, plan->shipped array fields, plan->static kwargs)
    "gcn": (init_gcn_params, gcn_forward_local, gcn_plan_fields,
            lambda plan: ({"ell_buckets": plan.ell_buckets}
                          if plan.symmetric else {})),
    "gat": (init_gat_params, gat_forward_local, lambda plan: GAT_PLAN_FIELDS,
            # ensure_cell: the combined-edge layout is built lazily — only
            # GAT ships it, and it duplicates the edge storage
            lambda plan: {"cell_buckets": plan.ensure_cell().cell_buckets}),
}

# loss registry: 'xent' is the torch stack's log-softmax+NLL
# (GPU/PGCN.py:204-205), 'bce' the MPI stack's sigmoid+BCE
# (Parallel-GCN/main.c:70-90) whose reported metric is `err`.
LOSSES = {
    "xent": masked_softmax_xent_local,
    "bce": masked_sigmoid_bce_local,
}


@dataclass
class TrainData:
    """Stacked per-chip training data (leading axis k, sharded over the mesh)."""

    h0: Any        # (k, B, f) input features
    labels: Any    # (k, B) int32
    train_valid: Any  # (k, B) float32 — 1 on real rows in the train split
    eval_valid: Any   # (k, B) float32 — 1 on real rows in the eval split


def make_train_data(
    plan: CommPlan,
    features: np.ndarray,
    labels: np.ndarray,
    train_mask: np.ndarray | None = None,
    eval_mask: np.ndarray | None = None,
) -> TrainData:
    """Scatter global (n, f) features and (n,) int labels into per-chip blocks."""
    n = plan.n
    h0 = plan.scatter_rows(features.astype(np.float32))
    lab = plan.scatter_rows(labels.reshape(n, 1).astype(np.int32))[..., 0]
    if train_mask is None:
        train_mask = np.ones(n, dtype=np.float32)
    if eval_mask is None:
        eval_mask = train_mask
    tv = plan.scatter_rows(train_mask.reshape(n, 1).astype(np.float32))[..., 0]
    ev = plan.scatter_rows(eval_mask.reshape(n, 1).astype(np.float32))[..., 0]
    tv = tv * plan.row_valid
    ev = ev * plan.row_valid
    return TrainData(h0=h0, labels=lab, train_valid=tv, eval_valid=ev)


def make_train_data_multihost(
    plan: CommPlan,
    mesh,
    features: np.ndarray,
    labels: np.ndarray,
    train_mask: np.ndarray | None = None,
    eval_mask: np.ndarray | None = None,
) -> TrainData:
    """Multi-process data placement: each process materializes blocks ONLY
    for its own chips and assembles the global sharded arrays with
    ``jax.make_array_from_process_local_data`` — the supported multi-host
    path (a ``device_put`` of host-local data to a global sharding is not).

    ``features``/``labels``/masks are indexed globally, but only rows owned
    by this process's chips are READ — each host may leave remote rows as
    zeros / memory-mapped, exactly like each MPI rank reading only its own
    ``H.r`` shard (``Parallel-GCN/main.c:456-504``; SLURM deployment
    ``GPU/pytorch.3node.slurm:46-56`` + ``GPU/PGCN.py:241-260``).

    Returns a ``TrainData`` of global jax.Arrays, drop-in for ``step`` /
    ``run_epochs`` / ``evaluate``.
    """
    import jax

    from ..parallel.mesh import local_chip_slice
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = plan.n
    sl = local_chip_slice(mesh)
    chips = range(plan.k)[sl]
    if train_mask is None:
        train_mask = np.ones(n, dtype=np.float32)
    if eval_mask is None:
        eval_mask = train_mask

    sh = NamedSharding(mesh, P(AXIS))

    def put(local, gshape):
        if jax.process_count() == 1:
            return jax.device_put(local, sh)
        return jax.make_array_from_process_local_data(sh, local, gshape)

    scatter = lambda x, dt: plan.scatter_rows(  # noqa: E731 — local shorthand
        np.asarray(x, dtype=dt).reshape(n, -1), chips=chips)
    f = features.shape[1]
    rv = plan.row_valid[sl]
    h0 = put(scatter(features, np.float32), (plan.k, plan.b, f))
    lab = put(scatter(labels, np.int32)[..., 0], (plan.k, plan.b))
    tv = put(scatter(train_mask, np.float32)[..., 0] * rv, (plan.k, plan.b))
    ev = put(scatter(eval_mask, np.float32)[..., 0] * rv, (plan.k, plan.b))
    return TrainData(h0=h0, labels=lab, train_valid=tv, eval_valid=ev)


def _plan_arrays(plan: CommPlan, fields) -> dict:
    return {f: getattr(plan, f) for f in fields}


def _unblock(tree):
    """Strip the leading per-chip block axis shard_map hands us (size 1)."""
    return jax.tree.map(lambda x: x[0], tree)


class FullBatchTrainer:
    """Distributed full-batch trainer (PGCN-equivalent, ``-b jax`` backend)."""

    def __init__(
        self,
        plan: CommPlan,
        fin: int,
        widths: list[int],
        mesh=None,
        lr: float = 0.01,
        activation: str = "relu",
        final_activation: str = "none",
        optimizer: optax.GradientTransformation | None = None,
        seed: int = 0,
        model: str = "gcn",
        loss: str = "xent",
        compute_dtype: str | None = None,
        remat: bool = False,
        halo_dtype: str | None = None,
    ):
        """``compute_dtype='bfloat16'`` runs forward/backward (including the
        halo exchange — half the ICI bytes) in bf16 with f32 master params
        and f32 loss/grad reduction; the reference stacks are f32-only, this
        is the TPU-native mixed-precision option (MXU eats bf16).

        ``halo_dtype='bfloat16'`` narrows ONLY the wire: the a2a send buffer
        is cast after the send-side gather and upcast after the halo gather
        (both directions — the symmetric backward's gradient exchange too),
        so ICI bytes halve while every table, activation and accumulation
        stays f32.  The single-chip bf16 lesson (BASELINE.md: casts of the
        master arrays cost more than the halved HBM bytes buy) does not
        apply: only the (k, S, f) boundary buffer is cast.  GCN only — the
        GAT exchange ships its attention tables, which narrow via
        ``compute_dtype='bfloat16'`` (the packed one-gather path).

        ``remat=True`` wraps the forward in ``jax.checkpoint`` so layer
        activations are recomputed in the backward pass instead of stored —
        the HBM-for-FLOPs trade for deep stacks / huge vertex counts (no
        reference analogue; the MPI code stores every layer's H and Z,
        ``Parallel-GCN/main.c:553-607``)."""
        if halo_dtype is not None and model != "gcn":
            raise ValueError(
                "halo_dtype is a GCN-trainer lever; for GAT use "
                "compute_dtype='bfloat16' (the packed exchange already "
                "ships half-width rows)")
        self.halo_dtype = halo_dtype
        self.plan = plan
        self.mesh = mesh if mesh is not None else make_mesh_1d(plan.k)
        self.activation = activation
        self.final_activation = final_activation
        self.compute_dtype = compute_dtype
        self.remat = remat
        init_fn, self._forward_fn, fields_fn, static_fn = MODELS[model]
        self.plan_fields = fields_fn(plan)
        self._fwd_static = static_fn(plan)   # e.g. the ELL bucket structure
        if model == "gcn":
            # plan-driven kernel choice (VERDICT r3 #9): per-chip tables in
            # the VMEM regime switch the aggregator to the Pallas kernel
            from ..ops.pallas_spmm import (PALLAS_PLAN_FIELDS,
                                           use_pallas_spmm)
            if use_pallas_spmm(plan, fin, widths):
                plan.ensure_pallas_tiles()
                self.plan_fields = PALLAS_PLAN_FIELDS
                self._fwd_static = {
                    "pallas_tb": plan.pallas_tb,
                    "pallas_emulate": jax.default_backend() != "tpu",
                }
        if model == "gat":
            # pre-flight the measured single-chip capacity edge: a clear
            # error beats a compile OOM or a dead TPU worker — BOTH were
            # observed at products scale (models/gat.py::check_gat_memory;
            # static_fn above already ran ensure_cell, so tail size is known)
            from ..models.gat import check_gat_memory
            check_gat_memory(
                plan.b, int(plan.halo_counts.max()), fin, widths,
                nnz=int(plan.nnz.max()),
                tail=int(plan.ctail_nnz.max()) if plan.ctail_nnz is not None
                else 0,
                dtype=compute_dtype)
        self.model = model
        self.loss_name = loss
        self._loss_fn = LOSSES[loss]
        dims = list(zip([fin] + widths[:-1], widths))
        self.params = init_fn(jax.random.PRNGKey(seed), dims)
        self.opt = optimizer if optimizer is not None else optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self.params = replicate(self.mesh, self.params)
        self.opt_state = replicate(self.mesh, self.opt_state)
        self.last_err = None
        arrays = _plan_arrays(plan, self.plan_fields)
        if model == "gat":
            # attention IGNORES Â's values (scores replace them), so the
            # edge masks ship as int8 — the f32 forms are ~0.6 GB of
            # per-chip arguments at products scale, part of the round-4 OOM
            # margin.  Mask on w != 0: plan padding carries weight exactly 0
            # by construction, so this keeps every real edge even for a
            # signed/unnormalized weighted graph (ADVICE r4 — `> 0` silently
            # dropped negative-weight edges).
            for f in ("cell_w", "ctail_w"):
                arrays[f] = (arrays[f] != 0).astype(np.int8)
        self.pa = shard_stacked(self.mesh, arrays)
        self.stats = CommStats.from_plan(plan)
        self._step = self._build_step()
        self._eval = self._build_eval()
        self._multi = {}        # epochs -> compiled on-device epoch loop

    # ------------------------------------------------------------------ build
    def _forward(self, params, pa, h0):
        if self.compute_dtype is not None:
            import jax.numpy as jnp
            dt = jnp.dtype(self.compute_dtype)
            params = jax.tree.map(lambda w: w.astype(dt), params)
            h0 = h0.astype(dt)
            pa = {k: v.astype(dt) if v.dtype == jnp.float32 else v
                  for k, v in pa.items()}
        extra = ({"halo_dtype": self.halo_dtype}
                 if self.halo_dtype is not None else {})
        out = self._forward_fn(
            params, h0, pa,
            activation=self.activation,
            final_activation=self.final_activation,
            symmetric=self.plan.symmetric,
            **self._fwd_static,
            **extra,
        )
        return out.astype("float32")

    def _one_step(self, params, opt_state, pa, h0, labels, valid):
        """One per-chip training step (shared by _build_step/_build_multi)."""
        fwd = (jax.checkpoint(self._forward, static_argnums=())
               if self.remat else self._forward)

        def loss_fn(ps):
            logits = fwd(ps, pa, h0)
            loss = self._loss_fn(logits, labels, valid)
            err = (masked_err_local(logits, labels, valid)
                   if self.loss_name == "bce" else loss)
            return loss, err

        (loss, err), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        # dense weight-grad allreduce — GPU/PGCN.py:150-154 /
        # Parallel-GCN/main.c:422-425 (psum of local partials = full grad)
        grads = jax.tree.map(lambda g: lax.psum(g, AXIS), grads)
        updates, opt_state = self.opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, err

    def _build_step(self, mesh=None):
        def per_chip(params, opt_state, pa, h0, labels, valid):
            pa, h0, labels, valid = _unblock((pa, h0, labels, valid))
            return self._one_step(params, opt_state, pa, h0, labels, valid)

        smapped = jax.shard_map(
            per_chip,
            mesh=mesh if mesh is not None else self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(), P()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def lower_step(self, mesh, fin: int):
        """AOT-lower ONE train step for an arbitrary mesh — including a
        device-less ``jax.experimental.topologies`` mesh (e.g. an 8-chip v5e
        slice this host does not have).  Inputs are ShapeDtypeStructs shaped
        like this trainer's live arrays, so the lowered module is exactly the
        program ``step()`` runs, just targeted at the given topology.

        Used by the overlap evidence test (``tests/test_overlap_hlo.py``) to
        compile the real multi-chip TPU program and assert the async
        all-to-all start/done schedule brackets the local slot passes —
        the compiled-schedule form of the reference's Irecv/compute/Waitany
        overlap (``Parallel-GCN/main.c:238-299``) that does not need 8
        physical chips to demonstrate."""
        from jax.sharding import NamedSharding

        rep = NamedSharding(mesh, P())
        shd = NamedSharding(mesh, P(AXIS))
        k, b = self.plan.k, self.plan.b

        def sds(x, sharding):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

        params = jax.tree.map(lambda x: sds(x, rep), self.params)
        opt_state = jax.tree.map(lambda x: sds(x, rep), self.opt_state)
        pa = jax.tree.map(lambda x: sds(x, shd), self.pa)
        h0 = jax.ShapeDtypeStruct((k, b, fin), np.float32, sharding=shd)
        labels = jax.ShapeDtypeStruct((k, b), np.int32, sharding=shd)
        valid = jax.ShapeDtypeStruct((k, b), np.float32, sharding=shd)
        return self._build_step(mesh=mesh).lower(
            params, opt_state, pa, h0, labels, valid)

    def _build_multi(self, epochs: int):
        """Compile `epochs` training steps as ONE on-device fori_loop.

        One host dispatch per call instead of one per epoch: through this
        box's tunnel a dispatch costs ~110 ms, which at bench scale is larger
        than the epoch itself — the loop makes multi-epoch timing reflect
        device time only (a host-attached TPU pays µs either way).  Semantics
        are identical to `epochs` sequential ``step()`` calls; per-epoch
        losses come back as an array (the reference's per-epoch loss print,
        ``GPU/PGCN.py:223-224``, reads them after the run).
        """
        import jax.numpy as jnp

        def per_chip(params, opt_state, pa, h0, labels, valid):
            pa, h0, labels, valid = _unblock((pa, h0, labels, valid))

            def body(i, carry):
                params, opt_state, losses, errs = carry
                params, opt_state, loss, err = self._one_step(
                    params, opt_state, pa, h0, labels, valid)
                return (params, opt_state, losses.at[i].set(loss),
                        errs.at[i].set(err))

            z = jnp.zeros((epochs,), jnp.float32)
            params, opt_state, losses, errs = lax.fori_loop(
                0, epochs, body, (params, opt_state, z, z))
            return params, opt_state, losses, errs

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(), P()),
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def run_epochs(self, data: TrainData, epochs: int, sync: bool = True):
        """Run ``epochs`` steps in one device program; return per-epoch losses.

        ``sync=False`` returns the on-device loss array without blocking."""
        if epochs not in self._multi:
            self._multi[epochs] = self._build_multi(epochs)
        self.params, self.opt_state, losses, errs = self._multi[epochs](
            self.params, self.opt_state, self.pa, data.h0, data.labels,
            data.train_valid,
        )
        self.last_err = errs[-1]        # keep step()'s scalar contract
        for _ in range(epochs):
            self.stats.count_step(nlayers=self.nlayers)
        return np.asarray(losses) if sync else losses

    def _build_eval(self):
        def per_chip(params, pa, h0, labels, valid):
            pa, h0, labels, valid = _unblock((pa, h0, labels, valid))
            logits = self._forward(params, pa, h0)
            # eval loss uses the SAME objective as training, so train/eval
            # losses are comparable under --loss bce too (the MPI stack
            # reports the one flavor it trains with,
            # Parallel-GCN/main.c:318-335)
            loss = self._loss_fn(logits, labels, valid)
            acc = masked_accuracy_local(logits, labels, valid)
            return loss, acc, logits[None]

        smapped = jax.shard_map(
            per_chip,
            mesh=self.mesh,
            in_specs=(P(), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P(AXIS)),
        )
        return jax.jit(smapped)

    # ------------------------------------------------------------------- api
    def step(self, data: TrainData, sync: bool = True):
        """One training step.  ``sync=True`` (default) blocks on the loss
        scalar and returns a float — the per-epoch readback the reference's
        loss print implies (``GPU/PGCN.py:223-224``).  ``sync=False`` returns
        the on-device loss array so callers can pipeline many steps and pay
        one host round-trip at the end (the tunneled dev chip has ~90 ms
        round-trip latency that would otherwise swamp epoch timings)."""
        self.params, self.opt_state, loss, err = self._step(
            self.params, self.opt_state, self.pa, data.h0, data.labels,
            data.train_valid,
        )
        self.last_err = err   # the MPI stack's `err` metric under loss='bce'
        self.stats.count_step(nlayers=self.nlayers)
        return float(loss) if sync else loss

    def evaluate(self, data: TrainData) -> tuple[float, float]:
        loss, acc, _ = self._eval(
            self.params, self.pa, data.h0, data.labels, data.eval_valid
        )
        self.stats.count_forward(nlayers=self.nlayers)
        return float(loss), float(acc)

    def predict(self, data: TrainData) -> np.ndarray:
        """Global (n, nout) logits in original vertex order."""
        _, _, logits = self._eval(
            self.params, self.pa, data.h0, data.labels, data.eval_valid
        )
        self.stats.count_forward(nlayers=self.nlayers)
        return self.plan.gather_rows(np.asarray(logits))

    @property
    def nlayers(self) -> int:
        return len(self.params)

    def fit(
        self,
        data: TrainData,
        epochs: int = 5,
        warmup: int = 1,
        verbose: bool = True,
    ) -> dict:
        """Epoch loop with reference-style timing: ``warmup`` untimed epochs,
        then wall-clock over the timed ones (``GPU/PGCN.py:202-228``)."""
        data = TrainData(**shard_stacked(self.mesh, vars(data)))
        history: list[float] = []
        for _ in range(warmup):
            self.step(data)
        jax.block_until_ready(self.params)
        t0 = time.perf_counter()
        for ep in range(epochs):
            loss = self.step(data)
            history.append(loss)
            if verbose:
                print(f"epoch {ep}: loss {loss:.6f}", flush=True)
        jax.block_until_ready(self.params)
        elapsed = time.perf_counter() - t0
        report = self.stats.report()
        report.update(
            epochs=epochs,
            elapsed_s=elapsed,
            epoch_s=elapsed / max(epochs, 1),
            loss_history=history,
        )
        if self.loss_name == "bce":
            # rank-0 err line of the MPI stack (Parallel-GCN/main.c:322-323)
            report["err"] = float(self.last_err)
        return report
