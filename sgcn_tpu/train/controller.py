"""Adaptive communication controller — the runtime half of
``--comm-schedule auto`` (docs/comm_schedule.md).

``resolve_comm_schedule`` closes the PLAN-time loop (transport choice,
replica-aware wire scoring, the ``--replica-budget auto`` λ·degree knee);
this module closes the RUN-time loop: the trainers measure per-layer
drift at every sync/refresh step (the stale mode's ‖stale − fresh‖ and
the replica mode's ‖replica − fresh‖ relative RMS — the PR-3/PR-10 drift
gauges), and the controller retunes the EFFECTIVE ``--sync-every``
against a hysteresis band:

  * measured relative drift above ``upper`` → the carries are going stale
    faster than the sync schedule bounds — HALVE the sync interval (more
    frequent exact steps, floored at ``min_sync``);
  * below ``lower`` → the schedule is syncing for drift that is not
    there — DOUBLE the interval (fewer exposed full exchanges, capped at
    ``max_sync``; widening is what the composed modes convert directly
    into fewer exposed wire rows per step);
  * in between → hold.

Decisions are deterministic in the gauge sequence (no wall-clock, no
randomness — the band-crossing retune test drives ``observe`` with
injected gauges) and every decision is logged with its inputs; the
trainer writes the log into the run manifest's ``comm_schedule`` block
(``controller`` key), rendered by ``scripts/obs_report.py``.
"""

from __future__ import annotations

from dataclasses import dataclass, field

# default hysteresis band on the max-over-layers RELATIVE drift RMS: the
# cora-scale stale/replica runs measure O(1e-2..1e-1) relative drift when
# healthy; an order of magnitude above that says the carries have left the
# regime the PipeGCN/CaPGNN convergence story covers, an order below says
# the syncs are pure overhead.  Both ends are overridable per run.
DEFAULT_UPPER = 0.5
DEFAULT_LOWER = 0.02


@dataclass
class CommController:
    """Drift-banded ``sync_every`` retuner (see module docstring).

    ``observe(step, drift_rel_max)`` is the whole runtime surface: called
    at each NON-initializing sync/refresh step with the measured
    max-over-layers relative drift, it returns the sync interval to use
    from that step on (unchanged when the drift sits inside the band).
    """

    sync_every: int                      # current target (mutated)
    upper: float = DEFAULT_UPPER
    lower: float = DEFAULT_LOWER
    min_sync: int = 1
    max_sync: int = 256
    decisions: list = field(default_factory=list)

    def __post_init__(self):
        if self.sync_every < 1:
            raise ValueError(
                f"the controller retunes a periodic sync schedule; "
                f"sync_every must be >= 1, got {self.sync_every}")
        if not (0 <= self.lower < self.upper):
            raise ValueError(
                f"need 0 <= lower < upper, got [{self.lower}, {self.upper}]")
        self.initial_sync_every = self.sync_every

    def observe(self, step: int, drift_rel_max: float) -> int:
        """One sync-step observation → the (possibly retuned) interval."""
        old = self.sync_every
        if drift_rel_max > self.upper:
            new, rule = max(self.min_sync, old // 2), "drift above band"
        elif drift_rel_max < self.lower:
            new, rule = min(self.max_sync, old * 2), "drift below band"
        else:
            new, rule = old, "inside band"
        if new != old:
            self.decisions.append({
                "step": int(step),
                "drift_rel_max": float(drift_rel_max),
                "band": [float(self.lower), float(self.upper)],
                "rule": rule,
                "sync_every": [int(old), int(new)],
            })
            self.sync_every = new
        return self.sync_every

    def log(self) -> dict:
        """The manifest-ready ``comm_schedule.controller`` block."""
        return {
            "kind": "drift-banded sync_every retune",
            "band": [float(self.lower), float(self.upper)],
            "initial_sync_every": int(self.initial_sync_every),
            "sync_every": int(self.sync_every),
            "retunes": list(self.decisions),
        }

    # ----------------------------------------------------- checkpoint state
    def state(self) -> dict:
        """JSON-able resume state (docs/resilience.md): the EFFECTIVE
        interval plus the retune log.  Without this a resumed run would
        restart at the CLI's ``--sync-every`` and silently discard every
        retune the controller already paid drift observations for."""
        return {
            "sync_every": int(self.sync_every),
            "initial_sync_every": int(self.initial_sync_every),
            "decisions": list(self.decisions),
        }

    def load_state(self, state: dict) -> None:
        """Restore ``state()`` — the retune log keeps accumulating across
        the resume seam, so the manifest's controller block stays the full
        history of the logical run."""
        self.sync_every = int(state["sync_every"])
        self.initial_sync_every = int(state.get("initial_sync_every",
                                                self.initial_sync_every))
        self.decisions = list(state.get("decisions", []))
