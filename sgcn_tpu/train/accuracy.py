"""Accuracy-parity experiment — does partitioning change predictive power?

Reference: ``GPU/PGCN-Accuracy.py`` (run on cora, ``README.md:110``): train the
partitioned model on real features/labels with a train/test split and check
the predictive performance matches non-partitioned training.  The reference
restricts per-batch communication to ``boundary ∩ batch``
(``:92-139,112-128``); in our mini-batch trainer that restriction is
structural (batch plans only exchange boundary-of-batch rows).

This module is the experiment harness: it trains (a) the single-device dense
oracle (DGL-baseline role), (b) the distributed full-batch trainer, and
optionally (c) the distributed mini-batch trainer, all from the same init
seed, and reports test accuracy for each.  The parity assertion itself lives
in the test suite (SURVEY.md §4: the reference's notion of correctness).
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

from ..baselines.oracle import DenseOracle
from ..parallel.plan import build_comm_plan
from .fullbatch import FullBatchTrainer, make_train_data
from .minibatch import MiniBatchTrainer


def train_test_split_masks(n: int, train_frac: float = 0.6,
                           seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Random vertex-level split (the reference uses fixed random batches of
    256 for training and the rest for testing, ``GPU/PGCN-Accuracy.py:228-251``)."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    ntrain = int(n * train_frac)
    train = np.zeros(n, dtype=np.float32)
    test = np.zeros(n, dtype=np.float32)
    train[perm[:ntrain]] = 1.0
    test[perm[ntrain:]] = 1.0
    return train, test


def run_accuracy_parity(
    a: sp.spmatrix,
    features: np.ndarray,
    labels: np.ndarray,
    partvec: np.ndarray,
    k: int,
    widths: list[int],
    train_mask: np.ndarray,
    test_mask: np.ndarray,
    epochs: int = 15,
    batch_size: int | None = None,
    lr: float = 0.01,
    seed: int = 0,
    verbose: bool = False,
) -> dict:
    """Train oracle + distributed trainers on the same split; report test acc."""
    n = a.shape[0]
    fin = features.shape[1]
    results: dict = {}

    oracle = DenseOracle(a, fin, widths, lr=lr, seed=seed)
    for _ in range(epochs):
        oracle.step(features, labels, train_mask)
    pred = oracle.predict(features).argmax(axis=1)
    results["oracle_test_acc"] = float(
        ((pred == labels) * test_mask).sum() / test_mask.sum())

    plan = build_comm_plan(a, partvec, k)
    tr = FullBatchTrainer(plan, fin, widths, lr=lr, seed=seed)
    data = make_train_data(plan, features, labels, train_mask, test_mask)
    for _ in range(epochs):
        tr.step(data)
    _, acc = tr.evaluate(data)
    results["fullbatch_test_acc"] = float(acc)

    if batch_size is not None:
        mb = MiniBatchTrainer(a, partvec, k, fin, widths,
                              batch_size=batch_size, lr=lr, seed=seed)
        mb.fit(features, labels, train_mask, epochs=epochs, verbose=verbose)
        _, acc = mb.evaluate_fullgraph(features, labels, test_mask)
        results["minibatch_test_acc"] = float(acc)

    return results
