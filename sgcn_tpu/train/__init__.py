from .fullbatch import (FullBatchTrainer, TrainData, make_train_data,
                        make_train_data_multihost)

__all__ = ["FullBatchTrainer", "TrainData", "make_train_data",
           "make_train_data_multihost"]
