from .fullbatch import FullBatchTrainer, TrainData, make_train_data

__all__ = ["FullBatchTrainer", "TrainData", "make_train_data"]
