"""Mini-batch distributed GCN trainer — per-batch sampled adjacency + plans.

Reference: ``GPU/PGCN-Mini-batch.py`` — pre-samples ``nbatches = 3·(n/batch+1)``
random vertex subsets before training (``:220-230``), builds a per-batch
sampled adjacency restricted to the batch (``sample_adjacency_matrix``
``:58-69``) and per-batch comm maps (``:228``), then loops batches through a
fixed layer stack; its partition vector comes from SHP as a pickle
(``:217-218``).  ``GPU/PGCN-Accuracy.py`` is the variant with real labels and
comm restricted to ``boundary ∩ batch`` (``:92-139``) — here that restriction
is structural: batch plans are built from the batch subgraph, so only
boundary-of-batch rows are exchanged, and training on a batch touches only
batch vertices.

TPU design: per-batch nnz/halo sizes vary, which under XLA would mean one
compilation per batch.  Every batch plan is therefore padded to the max
envelope across batches (``pad_comm_plan``) so ONE jitted shard_map train step
serves every batch — the XLA-native mirror of the reference's
pre-sample-everything strategy (SURVEY.md §7.3).
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass

import jax
import numpy as np
import optax
import scipy.sparse as sp

from ..parallel.mesh import make_mesh_1d, shard_stacked
from ..parallel.plan import build_comm_plan, pad_comm_plan, shared_ell_buckets
from ..utils.stats import CommStats
from .fullbatch import (FullBatchTrainer, TrainData, _plan_arrays,
                        _unblock, make_train_data)


def sample_batches(n: int, batch_size: int, nbatches: int | None = None,
                   seed: int = 0) -> list[np.ndarray]:
    """Pre-sample vertex subsets; default count = 3·(n//batch + 1)
    (``GPU/PGCN-Mini-batch.py:220-230``)."""
    rng = np.random.default_rng(seed)
    if nbatches is None:
        nbatches = 3 * (n // batch_size + 1)
    batch_size = min(batch_size, n)
    return [np.sort(rng.choice(n, size=batch_size, replace=False))
            for _ in range(nbatches)]


def sample_adjacency(a: sp.spmatrix, batch: np.ndarray) -> sp.csr_matrix:
    """Batch-restricted adjacency ``A[batch][:, batch]`` reindexed to
    ``0..|batch|-1`` (``GPU/PGCN-Mini-batch.py:58-69``)."""
    a = sp.csr_matrix(a)
    return a[batch][:, batch]


@dataclass
class Batch:
    vertices: np.ndarray
    plan: object          # padded CommPlan over the batch subgraph
    pa: dict              # sharded plan arrays
    data: TrainData       # sharded per-chip batch blocks
    stats: CommStats      # per-batch-plan counters (own send/recv volumes)


class MiniBatchTrainer:
    """PGCN-Mini-batch-equivalent trainer on the 1D vertex mesh."""

    def __init__(
        self,
        a: sp.spmatrix,
        partvec: np.ndarray,
        k: int,
        fin: int,
        widths: list[int],
        batch_size: int,
        nbatches: int | None = None,
        mesh=None,
        lr: float = 0.01,
        activation: str = "relu",
        model: str = "gcn",
        loss: str = "xent",
        optimizer: optax.GradientTransformation | None = None,
        seed: int = 0,
        pad_rows_to: int = 8,
        compute_dtype: str | None = None,
        comm_schedule: str | None = None,
        replica_budget: int = 0,
        memory_budget: int | None = None,
    ):
        if replica_budget:
            # the replica carries cache per-layer activations of ONE plan's
            # boundary rows across steps; every mini-batch step runs a
            # DIFFERENT batch plan (different vertex set, different halo
            # structure), so a carried replica has no stable identity to
            # refresh against — same exclusion family as staleness/delta
            # (analysis/modes.py records the decision; docs/replication.md)
            raise ValueError(
                "replica_budget is a full-batch training lever: the "
                "mini-batch trainer re-plans per batch, so replica carries "
                "have no stable identity across batch plans — run the "
                "full-batch trainer for hot-halo replication")
        self.a = sp.csr_matrix(a)
        n = self.a.shape[0]
        self.partvec = np.asarray(partvec, dtype=np.int64)
        self.k = k
        self.mesh = mesh if mesh is not None else make_mesh_1d(k)
        self.batches_idx = sample_batches(n, batch_size, nbatches, seed=seed)

        # build per-batch plans, then pad all to the shared envelope
        raw = []
        for bv in self.batches_idx:
            sub = sample_adjacency(self.a, bv)
            pv = self.partvec[bv]
            # remap part ids unchanged: chips keep their global rank even if a
            # batch misses some part entirely
            raw.append(build_comm_plan(sub, pv, k, pad_rows_to=pad_rows_to))
        env = tuple(max(getattr(p, f) for p in raw)
                    for f in ("b", "s", "r", "e", "el", "eh", "tl"))
        shared = shared_ell_buckets(raw, env[0])
        self.plans = [pad_comm_plan(p, *env, ell_buckets=shared) for p in raw]
        if model == "gat":
            # the combined-edge (GAT) layout is lazy; build it ONCE per plan
            # with a shared bucket structure AND a shared tail length (the
            # spill is derivable from degree profiles without materializing)
            cshared = shared_ell_buckets(self.plans, env[0], combined=True)
            caps = np.concatenate(
                [np.full(nb, wb, np.int64) for nb, wb in cshared])
            ctl_shared = 1
            for p in self.plans:
                for chip in range(k):
                    deg = np.bincount(p.edge_dst[chip][: int(p.nnz[chip])],
                                      minlength=p.b)
                    ctl_shared = max(ctl_shared, int(
                        np.maximum(deg - caps[: p.b], 0).sum()))
            for p in self.plans:
                p.ensure_cell(buckets=cshared, ctl=ctl_shared)
        # one compiled step serves every batch, so the symmetric fast path is
        # only safe if every batch plan is symmetric (sampled subgraphs of a
        # symmetric graph are, but keep the guard exact)
        if not all(p.symmetric for p in self.plans):
            for p in self.plans:
                p.symmetric = False

        # one compiled step serves every batch, so the ragged per-round
        # envelope must be SHARED across batch plans, exactly like the
        # B/S/R/E envelope above: resolve the schedule over the whole batch
        # set (the shared rule in parallel/plan.py), then pad every plan's
        # round sizes to the elementwise max
        from ..parallel.plan import resolve_comm_schedule
        self.comm_decision: dict = {}   # selection inputs → run manifest
        comm_schedule = resolve_comm_schedule(
            comm_schedule, self.plans, model, fin=fin, widths=list(widths),
            compute_dtype=compute_dtype, decision=self.comm_decision)
        if comm_schedule == "ragged":
            # EVERY plan needs the layout (the fused sweep stacks the ragged
            # arrays across batches), padded to the shared round envelope;
            # k=1 plans have zero rounds and stack trivially
            for p in self.plans:
                p.ensure_ragged()
            if k > 1:
                shared_s = tuple(int(x) for x in np.max(
                    [p.rr_sizes for p in self.plans], axis=0))
                shared_e = tuple(int(x) for x in np.max(
                    [p.rr_edge_sizes for p in self.plans], axis=0))
                for p in self.plans:
                    p.ensure_ragged(rr_sizes=shared_s,
                                    rr_edge_sizes=shared_e)

        # one inner trainer = one compiled step for every batch.
        # allow_pallas=False: the VMEM kernel family's tile layout is
        # per-plan (per-class Emax_c statics, ptile_* arrays built by
        # ensure_pallas_tiles) — plans[0]'s compiled step cannot serve the
        # other batches' plans, whose tile arrays would never be built, so
        # the shared envelope stays on the slot-pass/ELL aggregators
        self.inner = FullBatchTrainer(
            self.plans[0], fin, widths, mesh=self.mesh, lr=lr,
            activation=activation, model=model, loss=loss,
            optimizer=optimizer, seed=seed,
            compute_dtype=compute_dtype, comm_schedule=comm_schedule,
            allow_pallas=False, memory_budget=memory_budget)
        # the inner trainer's plan IS the shared envelope every batch pads
        # to, so its analytic footprint (obs/memory.py) covers every batch's
        # step — the --memory-budget gate above already held it to account
        self.memory = self.inner.memory
        # checkpoints save through `inner`, whose plan is a padded per-BATCH
        # plan — its digest varies with batch_size/nbatches/pad envelope, so
        # it is not a stable run identity; suppress it (utils/checkpoint.py
        # honors the sentinel) rather than make every cross-batch-shape
        # resume a digest error.  Model config is still recorded + verified.
        self.inner.checkpoint_plan = None
        self.nlayers = len(widths)
        self._fullgraph_eval = None   # built lazily, cached across calls
        self.recorder = None          # run telemetry (sgcn_tpu.obs)
        self._gstep = 0               # completed batch steps (events are
        #                               1-based, like FullBatchTrainer's)
        self._comm_cum = None         # running cross-batch comm cumulative

    def attach_recorder(self, recorder) -> None:
        """Attach a ``sgcn_tpu.obs.RunRecorder``: every ``step(batch)``
        appends one JSONL event (loss, wall time, merged comm split across
        the per-batch counters).  The fused epoch sweep stays available but
        emits no per-step events — use the stepwise ``fit`` under
        telemetry."""
        self.recorder = recorder
        # span events ride the inner trainer's SpanTimer (one timer, one
        # span stack for both trainers — docs/observability.md)
        self.inner.spans.recorder = recorder
        if getattr(self, "comm_decision", None):
            recorder.set_comm_schedule(self.comm_decision)
        if getattr(self, "memory", None) is not None:
            recorder.set_memory(self.memory.block())

    def _comm_snapshot(self, stats: CommStats) -> dict:
        """O(k) running equivalent of ``CommStats.merged_report`` over every
        batch counter that has passed through ``step()``: one step advances
        exactly one batch's counters by a fixed per-step delta, so the
        cross-batch cumulative is maintained incrementally instead of
        re-merging all B counters each step (O(B²) per epoch).  Covers
        RECORDED steps only — attach the recorder before training (the CLI
        does) or the snapshot starts from the attach point."""
        d = 2 * self.nlayers
        per = (stats.send_volume_per_exchange, stats.send_msgs_per_exchange,
               stats.recv_volume_per_exchange, stats.recv_msgs_per_exchange)
        if self._comm_cum is None:
            self._comm_cum = {
                "arrs": [np.zeros_like(p, dtype=np.int64) for p in per],
                "exchanges": 0, "send_volume": 0, "wire_rows": 0,
            }
        c = self._comm_cum
        for acc, p in zip(c["arrs"], per):
            acc += p.astype(np.int64) * d
        c["exchanges"] += d
        c["send_volume"] += int(per[0].sum()) * d
        c["wire_rows"] += stats.wire_rows_per_exchange * d
        rep = CommStats.report_from_cumulative(*c["arrs"])
        rep.update(                 # mini-batch steps are never pipelined
            exchanges=c["exchanges"],
            exposed_exchanges=c["exchanges"], hidden_exchanges=0,
            exposed_send_volume=c["send_volume"], hidden_send_volume=0,
            # the same wire gauges the full-batch snapshot carries
            # (docs/observability.md): the per-exchange figures are the
            # CURRENT batch's (wire is uniform — all batch plans share one
            # padded envelope; true rows vary per batch), the cumulative
            # ones cover every recorded step
            comm_schedule=stats.schedule,
            true_rows_per_exchange=int(per[0].sum()),
            wire_rows_per_exchange=stats.wire_rows_per_exchange,
            wire_rows_total=c["wire_rows"],
            padding_efficiency=(c["send_volume"] / c["wire_rows"]
                                if c["wire_rows"] else 1.0),
        )
        return rep

    # ------------------------------------------------------------------- data
    def make_batches(self, features: np.ndarray, labels: np.ndarray,
                     train_mask: np.ndarray | None = None) -> list[Batch]:
        """Scatter global features/labels into per-batch per-chip blocks."""
        out = []
        for bv, plan in zip(self.batches_idx, self.plans):
            tm = train_mask[bv] if train_mask is not None else None
            data = make_train_data(plan, features[bv], labels[bv], tm)
            out.append(Batch(
                vertices=bv,
                plan=plan,
                pa=shard_stacked(self.mesh,
                                 _plan_arrays(plan, self.inner.plan_fields)),
                data=TrainData(**shard_stacked(self.mesh, vars(data))),
                stats=CommStats.from_plan(
                    plan, schedule=self.inner.comm_schedule,
                    # same per-layer wire lane widths as the inner trainer's
                    # counters, so per-batch byte gauges stay comparable
                    lane_widths=self.inner.stats.lane_widths,
                    wire_itemsize=self.inner.stats.wire_itemsize,
                    wire_itemsize_bwd=self.inner.stats.wire_itemsize_bwd),
            ))
        return out

    # ------------------------------------------------------------------- api
    def lower_step(self):
        """AOT-lower the ONE shared-envelope train step every batch runs
        (no compile, no execution) — the mini-batch entry point of the
        static-analysis HLO audit (``sgcn_tpu/analysis``): the program
        ``step(batch)`` dispatches is the inner trainer's step over the
        padded batch envelope (shared B/S/R/E + ragged round sizes), so its
        collective census / wire dtype / donation contracts are audited on
        exactly that envelope."""
        return self.inner.lower_step()

    def step(self, batch: Batch) -> float:
        tr = self.inner
        # under a recorder, the step span brackets dispatch AND the loss
        # readback, so its duration is the measured step time the event
        # carries; without one, nullcontext keeps the SAME body (one copy
        # of the step bookkeeping for both paths)
        cm = (tr.spans.span("step", step=self._gstep + 1)
              if self.recorder is not None else contextlib.nullcontext())
        with cm as sp:
            tr.params, tr.opt_state, loss, tr.last_err = tr._step(
                tr.params, tr.opt_state, batch.pa, batch.data.h0,
                batch.data.labels, batch.data.train_valid)
            loss = float(loss)
        # per-batch counters advance exactly like the full-batch trainer's —
        # the reference's mini-batch code shares one counter dict across
        # batches (GPU/PGCN-Mini-batch.py), so end-of-run stats carry the
        # same 8-number vocabulary
        batch.stats.count_step(nlayers=self.nlayers)
        self._gstep += 1
        if self.recorder is not None:
            self.recorder.record_step(
                step=self._gstep, loss=loss, wall_s=sp.dur_s,
                comm=self._comm_snapshot(batch.stats))
        return loss

    def fit(self, features: np.ndarray, labels: np.ndarray,
            train_mask: np.ndarray | None = None, epochs: int = 1,
            warmup: int = 1, verbose: bool = True) -> dict:
        """Epoch = one pass over all pre-sampled batches (reference epoch
        structure, ``GPU/PGCN-Mini-batch.py:231-306``).  Timing routes
        through the inner trainer's ``PhaseTimer`` (one phase-accounting
        code path for both trainers)."""
        timer = self.inner.timer
        spans = self.inner.spans
        batches = self.make_batches(features, labels, train_mask)
        with spans.span("warmup", sync=lambda: self.inner.params):
            for _ in range(warmup):
                self.step(batches[0])
        history = []
        # inclusive: under a recorder each batch step opens a nested span
        # that claims the self time (utils/timers.py nesting contract)
        t_prior = timer.inclusive_total("train_step")
        for ep in range(epochs):
            ep_loss = 0.0
            with spans.span("train_step", sync=lambda: self.inner.params):
                for b in batches:
                    ep_loss += self.step(b)
            ep_loss /= len(batches)
            history.append(ep_loss)
            if verbose:
                print(f"epoch {ep}: batch-avg loss {ep_loss:.6f}", flush=True)
        elapsed = timer.inclusive_total("train_step") - t_prior
        report = CommStats.merged_report([b.stats for b in batches])
        report.update(
            epochs=epochs,
            nbatches=len(batches),
            elapsed_s=elapsed,
            epoch_s=elapsed / max(epochs, 1),
            loss_history=history,
            phases=timer.report(),
            # legacy alias of total_send_volume (rows shipped across all
            # exchanges) — derived, not independently counted
            total_exchanged_rows=report["total_send_volume"],
        )
        if self.recorder is not None:
            self.recorder.record_summary(
                {k: v for k, v in report.items() if k != "loss_history"})
        return report

    # ------------------------------------------------------- fused epoch path
    def _stack_inputs(self, features, labels, train_mask=None):
        """Stack every batch's plan arrays and data along a new axis 1:
        (k, nb, ...) — shard axis stays leading, so one shard_map program
        can ``fori_loop`` over batches on-device."""
        per_plan = [_plan_arrays(p, self.inner.plan_fields)
                    for p in self.plans]
        pa = {f: np.stack([d[f] for d in per_plan], axis=1)
              for f in self.inner.plan_fields}
        datas = []
        for bv, p in zip(self.batches_idx, self.plans):
            tm = train_mask[bv] if train_mask is not None else None
            datas.append(make_train_data(p, features[bv], labels[bv], tm))
        # eval_valid is never consumed by the fused train program — alias it
        # to train_valid instead of stacking/shipping a second mask array
        sh = shard_stacked(self.mesh, dict(
            h0=np.stack([d.h0 for d in datas], axis=1),
            labels=np.stack([d.labels for d in datas], axis=1),
            train_valid=np.stack([d.train_valid for d in datas], axis=1)))
        return (shard_stacked(self.mesh, pa),
                TrainData(**sh, eval_valid=sh["train_valid"]))

    def _build_fused(self, epochs: int):
        """Compile ``epochs`` full passes over ALL batches as ONE program.

        The reference dispatches one step per batch from Python
        (``GPU/PGCN-Mini-batch.py:231-306``); under a high-latency host link
        that dominates wall-clock, so the whole epoch loop runs on-device —
        same semantics, one dispatch (cf. ``FullBatchTrainer.run_epochs``).
        """
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        tr = self.inner
        nb = len(self.plans)

        # the loss/err accumulators enter as REPLICATED arguments rather
        # than in-body jnp.zeros literals: a fori carry must keep one
        # replication type, and a literal's is untracked while the psum'd
        # losses written into it are replicated — shard_map's check_rep
        # rejects the pair (observed on jaxlib 0.4.37; same fix as
        # FullBatchTrainer._build_multi)
        def per_chip(params, opt_state, pa_s, h0, lab, val, z_ep, z_nb):
            pa_s, h0, lab, val = _unblock((pa_s, h0, lab, val))

            def batch_body(i, carry):
                params, opt_state, losses, _ = carry
                pa_i = jax.tree.map(lambda x: x[i], pa_s)
                params, opt_state, loss, err = tr._one_step(
                    params, opt_state, pa_i, h0[i], lab[i], val[i])
                return params, opt_state, losses.at[i].add(loss), err

            def epoch_body(e, carry):
                params, opt_state, ep_losses, err = carry
                params, opt_state, s, err = lax.fori_loop(
                    0, nb, batch_body, (params, opt_state, z_nb, err))
                return params, opt_state, ep_losses.at[e].set(s.mean()), err

            return lax.fori_loop(0, epochs, epoch_body,
                                 (params, opt_state, z_ep, z_ep.sum()))

        smapped = jax.shard_map(
            per_chip, mesh=self.mesh,
            in_specs=(P(), P(), P("v"), P("v"), P("v"), P("v"), P(), P()),
            out_specs=(P(), P(), P(), P()))
        return jax.jit(smapped, donate_argnums=(0, 1))

    def run_epochs_fused(self, features, labels, train_mask=None,
                         epochs: int = 1, sync: bool = True):
        """Run ``epochs`` full batch sweeps in one device program; returns
        per-epoch batch-averaged losses.  Identical trajectory to
        ``epochs × len(batches)`` sequential ``step()`` calls."""
        if not hasattr(self, "_fused"):
            self._fused = {}
            self._fused_inputs = None
            self._fused_key = None
        # cheap content probe so a call with DIFFERENT data rebuilds the
        # stacked device inputs instead of silently training on stale ones
        key = (np.asarray(features).shape, np.asarray(labels).shape,
               None if train_mask is None else np.asarray(train_mask).shape,
               float(np.asarray(features).ravel()[:16].sum()),
               int(np.asarray(labels).ravel()[:16].sum()),
               None if train_mask is None
               else float(np.asarray(train_mask).sum()))
        if self._fused_inputs is None or key != self._fused_key:
            self._fused_inputs = self._stack_inputs(features, labels,
                                                    train_mask)
            self._fused_key = key
        if epochs not in self._fused:
            self._fused[epochs] = self._build_fused(epochs)
        pa_s, data = self._fused_inputs
        tr = self.inner
        tr.params, tr.opt_state, losses, tr.last_err = self._fused[epochs](
            tr.params, tr.opt_state, pa_s, data.h0, data.labels,
            data.train_valid, np.zeros((epochs,), np.float32),
            np.zeros((len(self.plans),), np.float32))
        # same 8-number comm accounting as the stepwise path (one counter
        # set per batch plan, merged on report)
        if not hasattr(self, "_fused_stats"):
            self._fused_stats = [
                CommStats.from_plan(p, schedule=self.inner.comm_schedule)
                for p in self.plans]
        for _ in range(epochs):
            for st in self._fused_stats:
                st.count_step(nlayers=self.nlayers)
        return np.asarray(losses) if sync else losses

    def fused_stats_report(self) -> dict:
        return CommStats.merged_report(getattr(self, "_fused_stats", []))

    # full-graph evaluation path (accuracy-parity experiments evaluate on the
    # whole graph after mini-batch training — GPU/PGCN-Accuracy.py role)
    def evaluate_fullgraph(self, features: np.ndarray, labels: np.ndarray,
                           eval_mask: np.ndarray | None = None):
        if self._fullgraph_eval is None:
            plan = build_comm_plan(self.a, self.partvec, self.k)
            self._fullgraph_eval = (plan, FullBatchTrainer(
                plan, features.shape[1], self._widths_from_params(),
                mesh=self.mesh, activation=self.inner.activation,
                model=self.inner.model, loss=self.inner.loss_name,
                compute_dtype=self.inner.compute_dtype))
        plan, tr = self._fullgraph_eval
        tr.params = self.inner.params
        data = make_train_data(plan, features, labels,
                               np.ones(self.a.shape[0], np.float32),
                               eval_mask)
        data = TrainData(**shard_stacked(self.mesh, vars(data)))
        loss, acc, _ = tr._eval(tr.params, tr.pa, data.h0, data.labels,
                                data.eval_valid)
        return float(loss), float(acc)

    def _widths_from_params(self) -> list[int]:
        if self.inner.model == "gcn":
            return [int(w.shape[1]) for w in self.inner.params]
        return [int(p["w"].shape[1]) for p in self.inner.params]
