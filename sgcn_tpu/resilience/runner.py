"""The resumable training loop — steps, durable checkpoints, kill points.

``run_resumable`` is the driver behind the trainer CLI's
``--checkpoint-every N`` / ``--resume auto`` flags: it runs optimizer steps
``start_step .. total_steps-1`` one dispatch at a time (the per-step
granularity checkpointing needs — the fused multi-epoch program cannot stop
mid-loop), saves a durable full-state checkpoint every ``checkpoint_every``
steps through a ``CheckpointManager``, and calls the fault-injection kill
point (``faults.after_checkpoint_save``) immediately after each committed
save — which is exactly where a preemption that the checkpoint survives
would land.

The resume CONTRACT this loop upholds (pinned by
``tests/test_resilience.py`` across the full mode matrix): for every
supported mode family, *train s steps → checkpoint → new process → resume →
train t−s steps* yields losses and params ``==`` (f32 bit-for-bit) the
uninterrupted t-step run, with cumulative CommStats totals that reconcile
across the seam.
"""

from __future__ import annotations

import os
import time

from . import faults


def save_and_record(manager, state_holder, step: int, recorder=None) -> str:
    """The ONE durable-commit protocol both trainers share: atomic save
    through the manager, the schema-v4 checkpoint event (emitted AFTER the
    rename — the event certifies the file was on disk), then the
    fault-injection kill point.  Returns the committed path."""
    t0 = time.perf_counter()
    path = manager.save(state_holder, step=step)
    if recorder is not None:
        recorder.record_checkpoint(
            step=step, path=path,
            wall_s=time.perf_counter() - t0,
            bytes=os.path.getsize(path))
    # the kill point: a fault-injected run dies HERE, after the save
    # committed — the closest a test gets to a preemption
    faults.after_checkpoint_save(path, step)
    return path


def run_resumable(trainer, data, total_steps: int, *, manager=None,
                  checkpoint_every: int = 0, start_step: int = 0,
                  verbose: bool = True) -> dict:
    """Run steps ``start_step..total_steps-1``; returns the end-of-run
    report (``CommStats.report()`` + ``steps``/``start_step``/``elapsed_s``
    + the full-precision per-step ``losses`` list — resumed runs report
    the steps THEY ran; the uninterrupted baseline's tail must match them
    float-for-float)."""
    if checkpoint_every < 0:
        raise ValueError(f"checkpoint_every must be >= 0, "
                         f"got {checkpoint_every}")
    if checkpoint_every and manager is None:
        raise ValueError("checkpoint_every > 0 needs a CheckpointManager")
    if not 0 <= start_step <= total_steps:
        raise ValueError(
            f"start_step {start_step} outside [0, {total_steps}] — the "
            "checkpoint is ahead of this run's schedule (asked for fewer "
            "total steps than were already trained?)")
    from ..parallel.mesh import shard_stacked

    data = type(data)(**shard_stacked(trainer.mesh, vars(data)))
    losses: list[float] = []
    t0 = time.perf_counter()
    for i in range(start_step, total_steps):
        loss = float(trainer.step(data))
        losses.append(loss)
        done = i + 1
        if verbose:
            print(f"step {done}: loss {loss:.6f}", flush=True)
        if manager is not None and checkpoint_every \
                and done % checkpoint_every == 0:
            save_and_record(manager, trainer, done,
                            recorder=getattr(trainer, "recorder", None))
    elapsed = time.perf_counter() - t0
    report = trainer.stats.report()
    steps_run = total_steps - start_step
    report.update(
        steps=total_steps,
        start_step=start_step,
        steps_run=steps_run,
        elapsed_s=elapsed,
        # deliberately NOT named epoch_s: fit()'s epoch_s excludes warmup
        # and compile, while this loop's first step pays the XLA compile —
        # publishing it under the same key would poison any cross-run
        # epoch-time comparison (the honest-measurement discipline)
        step_s_wall=elapsed / max(steps_run, 1),
        losses=losses,
    )
    phases = trainer.timer.report()
    if phases:
        report["phases"] = phases
    if trainer.loss_name == "bce" and trainer.last_err is not None:
        # last_err is None on a zero-remaining-steps resume (the schedule
        # was already complete; the loop body never ran)
        report["err"] = float(trainer.last_err)
    if getattr(trainer, "recorder", None) is not None:
        # same end-of-run summary event fit() emits (loss lists excluded,
        # mirroring fit's loss_history exclusion) — adding --checkpoint-dir
        # must not silently drop the summary from the obs stream
        trainer.recorder.record_summary(
            {k: v for k, v in report.items() if k != "losses"})
    return report
