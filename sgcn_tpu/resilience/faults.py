"""Deterministic fault injection — the harness that PROVES the resume story.

A checkpoint/resume layer that has never been killed mid-flight is a story,
not a contract.  This module gives the integration tests (and an operator
doing a game-day drill) env/CLI-driven faults that are deterministic in the
step sequence — no wall-clock, no randomness beyond a fixed seed — so the
crash-resume bit-identity test (``tests/test_resilience.py``) kills a REAL
training run at a named step, resumes it in a new process, and pins ``==``
parity against the uninterrupted run.

``$SGCN_FAULT`` grammar (one fault per process):

  * ``kill-after-save:<step>`` — hard ``os._exit(FAULT_EXIT_CODE)`` the
    moment the durable checkpoint at optimizer step ``<step>`` has been
    fully written (fsync'd, renamed, rotated).  The hard exit is the point:
    no atexit handlers, no buffered-write flushes — the closest a test can
    get to a preemption.
  * ``corrupt-after-save:<step>[:<mode>]`` — after the step-``<step>`` save
    completes, corrupt that checkpoint file in place (``bitflip`` default,
    or ``truncate``) and THEN hard-exit: the resume must detect the
    corruption via the checksum loader and fall back to the previous intact
    checkpoint — the fallback path, driven end to end by the harness, never
    by hand-staged files.
  * ``stall:<phase>:<seconds>`` — sleep injection at a named phase hook
    (``maybe_stall``): the heartbeat-stall fault.  The multichip dryrun
    hooks ``'dryrun'``; a stalled child stops heartbeating, which is
    exactly what the parent's stalled-vs-slow classifier
    (``classify_stall``) must distinguish from a merely slow child whose
    heartbeats keep advancing.
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass

# distinctive exit code the hard kill uses — tests assert it so an ordinary
# crash (rc 1) or an external timeout (rc 124) can never masquerade as a
# successful fault injection
FAULT_EXIT_CODE = 43
FAULT_ENV = "SGCN_FAULT"

CORRUPT_MODES = ("bitflip", "truncate")


@dataclass(frozen=True)
class FaultSpec:
    kind: str                    # 'kill-after-save'|'corrupt-after-save'|'stall'
    step: int | None = None      # the triggering optimizer step (save faults)
    phase: str | None = None     # the triggering phase hook (stall)
    seconds: float | None = None  # stall duration
    mode: str = "bitflip"        # corruption flavor


def _grammar_error(text: str) -> ValueError:
    return ValueError(
        f"unparseable {FAULT_ENV}={text!r} — grammar: "
        "'kill-after-save:<step>', 'corrupt-after-save:<step>[:<mode>]' "
        f"(mode in {CORRUPT_MODES}), 'stall:<phase>:<seconds>'")


def parse_fault(text: str) -> FaultSpec:
    """Parse one ``$SGCN_FAULT`` value; raises ``ValueError`` with the
    grammar on anything malformed — a typo'd fault spec silently injecting
    nothing would make a green harness test meaningless."""
    parts = text.split(":")
    kind = parts[0]
    try:
        if kind == "kill-after-save" and len(parts) == 2:
            return FaultSpec(kind=kind, step=int(parts[1]))
        if kind == "corrupt-after-save" and len(parts) in (2, 3):
            mode = parts[2] if len(parts) == 3 else "bitflip"
            if mode not in CORRUPT_MODES:
                raise _grammar_error(text)
            return FaultSpec(kind=kind, step=int(parts[1]), mode=mode)
        if kind == "stall" and len(parts) == 3:
            return FaultSpec(kind=kind, phase=parts[1],
                             seconds=float(parts[2]))
    except ValueError as e:
        raise _grammar_error(text) from e
    raise _grammar_error(text)


def active_fault() -> FaultSpec | None:
    """The process's injected fault, or None.  Parsed fresh each call (two
    lookups per checkpoint — negligible next to the save itself)."""
    text = os.environ.get(FAULT_ENV)
    return parse_fault(text) if text else None


def _hard_exit() -> None:
    # flush what the run already printed (the test reads the partial log),
    # then die without cleanup — atexit/finally handlers running would make
    # this a graceful shutdown, not a preemption
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(FAULT_EXIT_CODE)


def corrupt_file(path: str, mode: str = "bitflip", seed: int = 0) -> None:
    """Deterministically damage one file in place.

    ``bitflip`` inverts a single byte two-thirds of the way in (past the
    zip directory headers of an ``.npz``, inside array data — the damage a
    checksum must catch because the container still parses); ``truncate``
    cuts the file to 60% (the kill-mid-write shape — the container itself
    no longer parses).  ``seed`` perturbs the bitflip offset so tests can
    hit several positions deterministically."""
    if mode not in CORRUPT_MODES:
        raise ValueError(f"corruption mode {mode!r} not in {CORRUPT_MODES}")
    size = os.path.getsize(path)
    if size < 4:
        raise ValueError(f"{path}: {size} bytes — nothing to corrupt")
    if mode == "truncate":
        with open(path, "r+b") as fh:
            fh.truncate(int(size * 0.6))
        return
    off = (2 * size // 3 + seed * 37) % size
    with open(path, "r+b") as fh:
        fh.seek(off)
        b = fh.read(1)
        fh.seek(off)
        fh.write(bytes([b[0] ^ 0xFF]))


def after_checkpoint_save(path: str, step: int) -> None:
    """The kill point — called by the durable-checkpoint writer
    (``resilience.runner``/the trainer CLI) immediately after the step-
    ``step`` save has been fully committed.  No-op without a matching
    ``$SGCN_FAULT``."""
    f = active_fault()
    if f is None or f.step != step:
        return
    if f.kind == "corrupt-after-save":
        corrupt_file(path, mode=f.mode)
        _hard_exit()
    if f.kind == "kill-after-save":
        _hard_exit()


def maybe_stall(phase: str) -> None:
    """The stall hook — a named phase (e.g. the dryrun's step phase) sleeps
    for the injected duration, emitting no heartbeats meanwhile.  No-op
    without a matching ``stall:<phase>:...`` fault."""
    f = active_fault()
    if f is not None and f.kind == "stall" and f.phase == phase:
        time.sleep(f.seconds)


# --------------------------------------------------- stalled-vs-slow reader
def classify_stall(rundir: str, now: float | None = None,
                   threshold_s: float = 60.0,
                   exclude_pid: int | None = None
                   ) -> tuple[str, float | None]:
    """Classify a deadline-blown child from its heartbeat trail:
    ``('slow', age)`` when the last heartbeat in
    ``rundir/heartbeat.jsonl`` is fresher than ``threshold_s`` (the child
    was advancing, just not fast enough), ``('stalled', age)`` when it is
    older (the child stopped making progress), and
    ``('stalled', None)`` when no heartbeat was ever observed — a child
    that never reached its first phase is indistinguishable from a wedged
    one, so it classifies as stalled.  ``exclude_pid`` drops the CALLER's
    own pings (parent and child share one heartbeat file — a child that
    wedged before its first heartbeat must not be judged "slow" off the
    parent's spawn ping).  Pure file read: usable from the parent's
    timeout handler without touching the dead child."""
    from ..obs.schema import HEARTBEAT_NAME

    now = time.time() if now is None else float(now)
    path = os.path.join(rundir, HEARTBEAT_NAME)
    last_ts = None
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if exclude_pid is not None and ev.get("pid") == exclude_pid:
                    continue
                ts = ev.get("ts")
                if isinstance(ts, (int, float)):
                    last_ts = float(ts)
    except OSError:
        return "stalled", None
    if last_ts is None:
        return "stalled", None
    age = max(0.0, now - last_ts)
    return ("slow" if age <= threshold_s else "stalled"), age
