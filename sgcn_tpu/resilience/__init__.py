"""Preemption tolerance: durable checkpoints, bit-identical resume, faults.

The reference artifact has NO checkpointing (SURVEY §5.4); this package is
the layer that makes long runs survivable on preemptible hardware
(``docs/resilience.md``):

  * ``atomic``      — temp + fsync + rename write discipline (checkpoints
    AND the obs run manifest ride it);
  * ``checkpoint``  — ``CheckpointManager``: step-stamped directory,
    keep-last-K rotation, newest-INTACT discovery with corruption fallback;
  * ``runner``      — ``run_resumable``: the per-step training loop behind
    ``--checkpoint-every`` / ``--resume auto``, with the kill point where
    fault injection lands;
  * ``faults``      — deterministic env-driven fault injection
    (kill-after-save, corrupt-after-save, heartbeat stall) + the
    stalled-vs-slow heartbeat classifier.

Attribute access is lazy (PEP 562) so importing ``sgcn_tpu.resilience``
never drags in the trainer stack — ``utils/checkpoint.py`` imports
``resilience.atomic`` from inside the package and an eager ``__init__``
would cycle.
"""

from __future__ import annotations

_EXPORTS = {
    "atomic_write": ".atomic",
    "atomic_write_json": ".atomic",
    "CheckpointManager": ".checkpoint",
    "run_resumable": ".runner",
    "FaultSpec": ".faults",
    "FAULT_EXIT_CODE": ".faults",
    "parse_fault": ".faults",
    "active_fault": ".faults",
    "after_checkpoint_save": ".faults",
    "corrupt_file": ".faults",
    "maybe_stall": ".faults",
    "classify_stall": ".faults",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(_EXPORTS[name], __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
