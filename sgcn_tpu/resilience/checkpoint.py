"""Durable checkpoint directory: rotation, latest-intact discovery, fallback.

One ``CheckpointManager`` owns one directory of step-stamped checkpoints
(``ckpt_<step:08d>.npz``).  The write side is already atomic
(``utils.checkpoint.save_checkpoint`` rides ``resilience.atomic``); this
layer adds the directory-level policies a preemptible run needs:

  * **rotation** — keep the newest ``keep_last`` checkpoints, delete older
    ones AFTER a new save commits (never before: a kill between delete and
    write must not leave the run with fewer restore points than promised);
  * **latest-intact discovery** — ``load_latest`` walks the directory
    newest-first, fully verifying each candidate (structure + per-array
    checksums) and falling back to the previous checkpoint on corruption
    with a LOUD warning naming the damaged file; only when NO intact
    checkpoint exists does it raise;
  * **resume provenance** — the chosen step/path and the list of
    checkpoints that had to be skipped come back to the caller, so the run
    report (and the obs ``resume`` event) can say exactly what happened.

No jax at module scope (CLIs initialize the backend env first).
"""

from __future__ import annotations

import os
import re
import warnings

from ..utils.checkpoint import CheckpointCorruptError, load_checkpoint

_CKPT_RE = re.compile(r"^ckpt_(\d{8})\.npz$")


class CheckpointManager:
    """See module docstring."""

    def __init__(self, directory: str, keep_last: int = 3):
        if keep_last < 1:
            raise ValueError(
                f"keep_last must be >= 1, got {keep_last} — a manager that "
                "keeps zero checkpoints cannot resume anything")
        self.dir = directory
        self.keep_last = int(keep_last)
        self._swept = False
        os.makedirs(directory, exist_ok=True)

    def path_for(self, step: int) -> str:
        if step < 0 or step > 10 ** 8 - 1:
            raise ValueError(f"step {step} outside the 8-digit stamp range")
        return os.path.join(self.dir, f"ckpt_{step:08d}.npz")

    def checkpoints(self) -> list[tuple[int, str]]:
        """``[(step, path), ...]`` sorted ascending by step — every file in
        the directory matching the stamp pattern, intact or not."""
        out = []
        for name in os.listdir(self.dir):
            m = _CKPT_RE.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dir, name)))
        return sorted(out)

    def save(self, trainer, step: int) -> str:
        """One atomic full-state save + rotation; returns the committed
        path.  Rotation runs strictly AFTER the new checkpoint is durable
        and never touches the file just written — a reused directory
        holding HIGHER-stamped checkpoints from a previous run must not
        make rotation (which orders by step) delete this run's fresh save.
        That situation itself gets a loud warning: ``--resume auto``
        prefers the highest stamp, so stale higher-stamped files from
        another run would shadow this run's checkpoints."""
        from ..utils.checkpoint import save_checkpoint
        from .atomic import sweep_temp_litter

        if not self._swept:
            # sweep temp litter from previous KILLED saves on the first
            # save of this run — here rather than __init__ because only
            # the coordinator calls save(): every rank constructs a
            # manager (restores run everywhere), and a restarting
            # non-writer rank sweeping a shared filesystem could unlink a
            # live coordinator's in-flight temp.  Without the sweep,
            # repeated mid-save preemptions grow the directory past the
            # keep_last disk bound.
            sweep_temp_litter(self.dir, "ckpt_")
            self._swept = True
        path = save_checkpoint(trainer, self.path_for(step), step=step)
        cands = self.checkpoints()
        if any(s > step for s, _ in cands):
            warnings.warn(
                f"checkpoint dir {self.dir!r} holds checkpoints stamped "
                f"PAST this run's step {step} (from a previous run?) — "
                "--resume auto would restore those, not this run's; use "
                "a fresh --checkpoint-dir per logical run",
                RuntimeWarning, stacklevel=2)
        for _, old in cands[:-self.keep_last]:
            if old == path:
                continue
            try:
                os.remove(old)
            except OSError:
                pass                    # a vanished file is already rotated
        return path

    def load_latest(self, trainer, verify: bool = True
                    ) -> tuple[int, str, list[str]]:
        """Restore the newest INTACT checkpoint into ``trainer``; returns
        ``(step, path, skipped)`` where ``skipped`` lists the corrupt
        files that were passed over (newest first).  Raises
        ``FileNotFoundError`` on an empty directory and
        ``CheckpointCorruptError`` when every candidate is damaged.
        Provenance/shape mismatches of an INTACT checkpoint (plain
        ``ValueError``) propagate immediately — falling back PAST a valid
        checkpoint that merely disagrees with the trainer would mask a
        config bug as a resume."""
        cands = self.checkpoints()
        if not cands:
            raise FileNotFoundError(
                f"--resume auto: no ckpt_*.npz in {self.dir!r} — nothing "
                "to resume (run with --checkpoint-every N first)")
        skipped: list[str] = []
        for step, path in reversed(cands):
            try:
                # load_checkpoint verifies EVERYTHING (checksums of leaves
                # AND carries, shapes, provenance) before its first
                # assignment, so corruption surfaces here with the trainer
                # untouched — one read pass, no separate verify sweep
                got = load_checkpoint(trainer, path, verify=verify)
            except CheckpointCorruptError as e:
                warnings.warn(
                    f"resume: {path!r} is corrupt ({e}); falling back to "
                    "the previous intact checkpoint", RuntimeWarning,
                    stacklevel=2)
                skipped.append(path)
                continue
            return int(got), path, skipped
        raise CheckpointCorruptError(
            f"--resume auto: all {len(cands)} checkpoint(s) in "
            f"{self.dir!r} are corrupt ({skipped}) — nothing intact to "
            "resume from")
