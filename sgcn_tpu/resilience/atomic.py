"""Atomic, durable file writes — the ONE write discipline every artifact
that must survive a kill rides (checkpoints, run manifests).

A preemption can land anywhere, including mid-``write()``: a plain
``open(path, "w")`` overwrite leaves a truncated file that the next process
then fails to parse (or worse, half-parses).  The classic fix is the only
one that is atomic on POSIX: write the full content to a TEMP file in the
SAME directory, ``flush`` + ``fsync`` it (durability — rename alone only
orders metadata), then ``os.replace`` onto the destination (atomicity — a
reader sees the old file or the new file, never a mix), and best-effort
``fsync`` the directory so the rename itself survives a power cut.

Deliberately dependency-free (stdlib only, no package imports): both
``utils/checkpoint.py`` and ``obs/recorder.py`` sit below this module's
consumers in the import graph, so this file must never import back into
the package.
"""

from __future__ import annotations

import contextlib
import json
import os


def _fsync_dir(path: str) -> None:
    """Best-effort directory fsync so a completed rename survives a power
    cut.  Some filesystems refuse O_RDONLY dir fds — never fatal: the
    rename is still atomic, only its durability window widens."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb"):
    """``with atomic_write(path) as fh: fh.write(...)`` — the temp + fsync +
    rename discipline (module docstring).  On ANY exception inside the
    block the temp file is removed and the destination is untouched — a
    kill or a failed writer can never leave a half-written artifact under
    the real name."""
    if "r" in mode or "a" in mode or "+" in mode:
        raise ValueError(f"atomic_write is write-only, got mode {mode!r}")
    tmp = f"{path}.tmp.{os.getpid()}"
    fh = open(tmp, mode)
    try:
        yield fh
        fh.flush()
        os.fsync(fh.fileno())
    except BaseException:
        fh.close()
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    fh.close()
    os.replace(tmp, path)
    _fsync_dir(path)


def sweep_temp_litter(directory: str, prefix: str) -> None:
    """Remove stranded ``<prefix>*.tmp.<pid>`` files a killed writer left
    behind — the ONE sweep policy both litter sites share (checkpoint
    directories and obs run directories).

    MUST only be called from the single legitimate writer of
    ``directory`` (the coordinator's save path, the recorder owner): the
    pid suffix makes temp names unique per process, but another HOST
    cannot tell a dead writer's temp from a live one's — a restarted
    non-writer rank sweeping a shared filesystem could unlink the
    coordinator's in-flight temp mid-save."""
    try:
        names = os.listdir(directory)
    except OSError:
        return
    for name in names:
        if name.startswith(prefix) and ".tmp." in name:
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


def atomic_write_json(path: str, obj, indent: int | None = 1) -> None:
    """Atomically (re)write one JSON document — the manifest-rewrite path
    (``obs.recorder.RunRecorder``): a kill during ``set_profile``/
    ``set_plan`` must leave the PREVIOUS manifest parseable, never a
    truncated one."""
    with atomic_write(path, "w") as fh:
        json.dump(obj, fh, indent=indent)
