"""sgcn_tpu — TPU-native framework for scalable GCN training on partitioned graphs.

A ground-up JAX/XLA re-design of the capabilities of the reference artifact for
"Scalable Graph Convolutional Network Training on Distributed-Memory Systems"
(arXiv:2212.05009): full-batch and mini-batch GCN/GAT training over a
vertex-partitioned graph, one partition per chip, with

  * per-chip sparse adjacency blocks and segment-sum SpMM compiled under ``jit``,
  * boundary-vertex ("halo") feature exchange as a static padded ``all_to_all``
    over the ICI mesh, driven by a precomputed communication plan
    (``sgcn_tpu.parallel``, ``sgcn_tpu.ops``),
  * replicated dense weights whose gradients reduce via ``lax.psum``
    (``sgcn_tpu.train``),
  * a single-device dense oracle for parity testing (``sgcn_tpu.baselines``),
  * comm-volume / message-count / phase-time observability (``sgcn_tpu.utils``).

Consult each subpackage's docstring for what it provides; SURVEY.md §7 at the
repo root is the full build plan.

The package is importable both as ``sgcn_tpu`` and via the canonical repo-name
symlink. See SURVEY.md at the repo root for the reference structural analysis.
"""

from .utils import compat as _compat  # noqa: F401 — installs jax API aliases

__version__ = "0.1.0"
