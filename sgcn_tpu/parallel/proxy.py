"""Single-chip shard proxy: run ONE chip's share of a k-way plan on one device.

Purpose (VERDICT r4 item 1): the north-star config is an 8-chip
ogbn-products epoch, but this box tunnels to ONE physical chip.  Every
per-chip array in a ``CommPlan`` is padded to identical shapes across chips
(``pad_comm_plan``), so chip ``c``'s per-device program — send-side gather,
halo gather, bucketed local SpMM, dense matmuls, loss, backward, Adam — is
the SAME compiled program on every chip; only gather index *contents* differ.
Measuring that program on the real chip therefore measures the compute half
of the k-chip epoch directly; the collectives (halo ``all_to_all``, grad
``psum``) are the only parts a single device cannot time, and their cost is
modeled from the plan's exact exchange bytes (``scripts/shard_epoch_model.py``).

Mechanism: ``dataclasses.replace`` the plan with ``k=1`` and every stacked
``(k, ...)`` array sliced to ``[chip:chip+1]``, then train normally on a
1-device mesh.  The mesh axis still exists, so the per-chip code is
UNCHANGED: ``all_to_all``/``psum`` over a size-1 axis are identities (the
halo buffer still materializes — ``ops.pspmm.halo_exchange`` pins it with an
``optimization_barrier`` on size-1 axes), and the halo table the proxy
gathers from has the real halo's shape; its *contents* are the chip's own
sent rows instead of its peers' rows, which changes no shape, no gather
count, and no flop — only the numerical values flowing through the (value-
independent-cost) program.

The reference has no analogue: its per-rank cost is only observable on a
full MPI/NCCL job (``Parallel-GCN/main.c:441-445`` times MAX over live
ranks).  Here the padded-uniform plan makes one rank's program a faithful
stand-in, MAX over ranks included (all ranks run the same-shape program).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .plan import _GLOBAL_ARRAY_FIELDS, PER_CHIP_ARRAY_FIELDS, CommPlan


def shard_proxy_plan(plan: CommPlan, chip: int = 0) -> CommPlan:
    """A ``k=1`` view of ``plan`` carrying only chip ``chip``'s arrays.

    Slicing is driven by the plan's EXPLICIT per-chip field classification
    (``plan.PER_CHIP_ARRAY_FIELDS``): each listed field is verified to carry
    the stacked leading ``k`` axis and sliced to ``[chip:chip+1]``;
    global-vertex arrays (``owner``, ``local_idx``) and scalars pass
    through.  Any UNclassified dataclass field that happens to look
    per-chip-stacked fails loudly instead of being silently sliced (or
    silently passed through whole) — the old ``shape[0] == plan.k``
    inference mis-slices exactly those cases (round-5 advisor finding).

    The result trains on a 1-device mesh with the chip's exact padded
    shapes: ``send_idx`` stays ``(1, k, S)`` (per-chip view ``(k, S)``), so
    the send buffer and the ``(k*S, f)`` receive window are full-size.
    """
    if not 0 <= chip < plan.k:
        raise ValueError(f"chip {chip} out of range for k={plan.k}")
    # record the true chip identity: sliced send_counts row 0 self-sends at
    # column `chip`, which the comm-stat properties must zero (not [0, 0])
    repl: dict = {"k": 1, "chip_ids": np.array([chip])}
    for name in PER_CHIP_ARRAY_FIELDS:
        v = getattr(plan, name)
        if v is None:              # lazy layout (cell/pallas) not built
            continue
        if not (isinstance(v, np.ndarray) and v.ndim >= 1
                and v.shape[0] == plan.k):
            raise ValueError(
                f"CommPlan.{name} is classified per-chip-stacked but has "
                f"shape {getattr(v, 'shape', None)} (k={plan.k}) — "
                "PER_CHIP_ARRAY_FIELDS is out of sync with the dataclass")
        repl[name] = v[chip: chip + 1]
    for fld in dataclasses.fields(plan):
        if fld.name in PER_CHIP_ARRAY_FIELDS or fld.name in _GLOBAL_ARRAY_FIELDS:
            continue
        v = getattr(plan, fld.name)
        if isinstance(v, np.ndarray) and v.ndim >= 1 and v.shape[0] == plan.k:
            raise ValueError(
                f"CommPlan.{fld.name} looks per-chip-stacked (leading axis "
                f"{plan.k}) but is not classified in PER_CHIP_ARRAY_FIELDS — "
                "add it there (sliced) or to _GLOBAL_ARRAY_FIELDS "
                "(passed through) before proxying")
    return dataclasses.replace(plan, **repl)


def shard_proxy_data(plan: CommPlan, chip: int, features: np.ndarray,
                     labels: np.ndarray):
    """Chip ``chip``'s ``TrainData`` block under the ORIGINAL k-way plan.

    Built with ``plan.scatter_rows(..., chips=[chip])`` so only the chip's
    owned rows are materialized (the multi-host placement path).
    """
    from ..train.fullbatch import TrainData

    n = plan.n
    h0 = plan.scatter_rows(features.astype(np.float32), chips=[chip])
    lab = plan.scatter_rows(
        labels.reshape(n, 1).astype(np.int32), chips=[chip])[..., 0]
    rv = plan.row_valid[chip: chip + 1]
    return TrainData(h0=h0, labels=lab, train_valid=rv, eval_valid=rv)
