from .plan import CommPlan, build_comm_plan, pad_comm_plan, relabel_plan
from .mesh import make_mesh_1d, shard_stacked, replicate

__all__ = ["CommPlan", "build_comm_plan", "pad_comm_plan", "relabel_plan",
           "make_mesh_1d", "shard_stacked", "replicate"]
