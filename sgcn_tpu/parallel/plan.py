"""Communication-plan construction: partition vector → static all_to_all layout.

The reference computes, at trainer start-up, per-rank send/recv index maps from
the adjacency nonzero pattern and the part vector: a rank must *receive* the
feature rows of every remote vertex its local nonzeros reference, and *send*
each of its owned boundary vertices to exactly the ranks whose nonzeros
reference it (``GPU/PGCN.py:37-51``; offline flavor ``GCN-HP/main.cpp:147-211``
emitting ``conn.r`` / ``buff.r``).  The exchange itself is ragged point-to-point
(``GPU/PGCN.py:85-119``, ``Parallel-GCN/main.c:238-266``).

On TPU, shapes under ``jit`` are static, so we lower the ragged exchange to a
**padded all_to_all layout** computed once per (graph, partvec):

  * vertices are relabeled so chip ``p`` owns local slots ``0..B-1``
    (``B`` = max part size, parts padded with dummy vertices),
  * ``send_idx[p, q, s]`` — the ``S`` local rows chip ``p`` ships to chip ``q``
    (padded with 0; ``send_counts[p, q]`` masks the tail),
  * one ``lax.all_to_all`` of a ``(k, S, f)`` buffer per layer replaces the
    whole two-phase send/recv protocol (deadlock-freedom is structural),
  * ``halo_src[p, r]`` gathers chip ``p``'s ``R`` halo rows out of the received
    ``(k*S, f)`` buffer, in (owner, vertex-id) order,
  * the local adjacency block becomes padded edge lists ``(dst, src, w)`` with
    ``src`` indexing the concatenated ``[local rows; halo rows]`` table —
    SpMM is a masked segment-sum, fully fused by XLA.

The transposed (backward) exchange is obtained for free: JAX transposes
``all_to_all`` to the reverse all_to_all and gathers to scatter-adds, which is
exactly the reference's swap of send/recv maps for the gradient
(``GPU/PGCN.py:93-97``, ``Parallel-GCN/main.c:350-372``).

Everything here is offline numpy; nothing is traced.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import scipy.sparse as sp

# Every CommPlan array field stacked per chip along a leading ``k`` axis —
# THE explicit classification anything slicing a plan per chip must use
# (``parallel/proxy.py::shard_proxy_plan``), instead of inferring per-chip-ness
# from a ``shape[0] == plan.k`` coincidence (round-5 advisor finding: a
# global-vertex field of an n==k graph, or a future (k_something, ...) field,
# would silently mis-slice).  Optional fields (the lazy cell/pallas layouts)
# are listed too and skipped while ``None``.  Fields NOT here and not in
# ``_GLOBAL_ARRAY_FIELDS`` must never carry a leading per-chip axis — the
# proxy enforces that loudly.
PER_CHIP_ARRAY_FIELDS = (
    "part_sizes",
    "send_idx", "send_counts", "halo_src", "halo_counts",
    "edge_dst", "edge_src", "edge_w", "nnz", "row_valid",
    "ledge_dst", "ledge_src", "ledge_w",
    "hedge_dst", "hedge_src", "hedge_w", "lnnz", "hnnz",
    "ell_idx", "ell_w", "ltail_dst", "ltail_src", "ltail_w", "ltail_nnz",
    "cell_idx", "cell_w", "ctail_dst", "ctail_src", "ctail_w", "ctail_nnz",
    "ptile_lsrc", "ptile_lld", "ptile_lw",
    "ptile_hsrc", "ptile_hld", "ptile_hw", "ptile_hrsrc",
    "ptile_csrc", "ptile_cld", "ptile_cw", "ptile_crsrc",
    "rsend_idx", "rhalo_dst", "redge_dst", "redge_src", "redge_w",
    "nrep_send_idx", "nrep_send_counts", "nrep_halo_src",
    "rep_slots", "rep_counts", "nrep_rsend_idx", "nrep_rhalo_dst",
    "rep_ring_pos", "nrep_ring_dst",
    "rep_rows", "rep_row_counts", "ronly_send_idx", "ronly_send_counts",
    "ronly_base_pos", "rep_recv_src",
)

# Auto-selection threshold for SGCN_COMM_SCHEDULE=auto: below this dense-a2a
# padding efficiency (Σ send_counts / (k²·S)) the per-round-sized ragged
# ppermute ring ships strictly fewer wire bytes by a margin worth its k−1
# rounds; above it the single dense all_to_all's one-shot latency wins.
RAGGED_AUTO_EFFICIENCY = 0.5

# Global-vertex-indexed arrays (plus the proxy's chip-identity record):
# pass through a per-chip slice untouched.
_GLOBAL_ARRAY_FIELDS = ("owner", "local_idx", "chip_ids")

# Plan arrays the COMPOSED stale × ragged step ships to devices
# (``ops.pspmm.pspmm_stale_ragged``): the ragged ring's send/edge layout —
# the round-structured carries replace the dense send_idx/halo_src pair
# entirely (receives live in the carry, the fold rides redge_*).  Kept as
# its own contract tuple (same lint coverage as the model tuples,
# ``tests/test_plan_contract.py``) even though it currently equals the
# ragged GCN forward's field set — the two evolve for different reasons.
STALE_PLAN_FIELDS_RAGGED = (
    "rsend_idx", "ell_idx", "ell_w",
    "ltail_dst", "ltail_src", "ltail_w",
    "redge_dst", "redge_src", "redge_w",
)

# Plan arrays the hot-halo REPLICATION step ships (``--replica-budget B``,
# ``ops.pspmm.pspmm_replica`` / ``pspmm_replica_ragged``): the UNION of the
# full exchange layout (the sync/refresh program is exactly the exact
# program plus the replica-carry gathers) and the shrunken no-replica
# layout (``ensure_replicas`` — top-B boundary rows by λ·degree leave the
# per-layer wire; their halo slots fill from the carried replica table).
# jit prunes whichever half a given program does not consume; the
# plan-contract lint (tests/test_plan_contract.py, via analysis/registry)
# covers both tuples.
REPLICA_PLAN_FIELDS = (
    "send_idx", "halo_src",
    "nrep_send_idx", "nrep_halo_src", "rep_slots",
    "ell_idx", "ell_w", "ltail_dst", "ltail_src", "ltail_w",
    "hedge_dst", "hedge_src", "hedge_w",
)
REPLICA_PLAN_FIELDS_RAGGED = (
    "rsend_idx", "nrep_rsend_idx", "nrep_rhalo_dst", "rep_slots",
    "rep_ring_pos",
    "ell_idx", "ell_w", "ltail_dst", "ltail_src", "ltail_w",
    "hedge_dst", "hedge_src", "hedge_w",
    "redge_dst", "redge_src", "redge_w",
)

# Plan arrays the COMPOSED replica × stale step ships
# (``ops.pspmm.pspmm_replica_stale`` / ``pspmm_replica_stale_ragged``,
# docs/comm_schedule.md): the stale halo carry subsumes the replica tables
# (replica slots/positions propagate through it between syncs), so unlike
# the pure replica mode there is no separate rep/grep carry — the shipped
# fields are the full exchange layout (sync steps) plus the SHRUNKEN
# no-replica layout (stale steps, which scatter their receives back into
# the carried table).  The a2a tuple currently EQUALS ``REPLICA_PLAN_FIELDS``
# — kept as its own contract tuple anyway (the STALE_PLAN_FIELDS_RAGGED
# precedent): the pure-replica step ships per-slot rep gathers the
# composed mode may drop, so the two evolve for different reasons.  The
# ragged flavor rides the ring-envelope carry of ``pspmm_stale_ragged``:
# ``nrep_ring_dst`` maps each shrunken receive slot to its position in
# the FULL ring's round-major concat.
REPLICA_STALE_PLAN_FIELDS = (
    "send_idx", "halo_src",
    "nrep_send_idx", "nrep_halo_src", "rep_slots",
    "ell_idx", "ell_w", "ltail_dst", "ltail_src", "ltail_w",
    "hedge_dst", "hedge_src", "hedge_w",
)
REPLICA_STALE_PLAN_FIELDS_RAGGED = (
    "rsend_idx", "nrep_rsend_idx", "nrep_ring_dst",
    "ell_idx", "ell_w", "ltail_dst", "ltail_src", "ltail_w",
    "redge_dst", "redge_src", "redge_w",
)

# Plan arrays the PARTIAL refresh step ships (``--refresh-band``,
# ``ops.pspmm.pspmm_replica_partial``, docs/replication.md): the shrunken
# replica-step layout plus the replica-only side channel — the owned
# replicated rows and their sender-side baseline positions
# (``rep_rows``/``ronly_base_pos``), the replica-only per-pair buckets
# (``ronly_*``: exactly the rows ``ensure_replicas`` deleted from the
# ``nrep_*`` layout), and the receive routing of refreshed rows into the
# carried replica table (``rep_recv_src``).
REPLICA_PARTIAL_PLAN_FIELDS = REPLICA_PLAN_FIELDS + (
    "rep_rows", "rep_row_counts",
    "ronly_send_idx", "ronly_send_counts", "ronly_base_pos",
    "rep_recv_src",
)


@dataclass
class CommPlan:
    """Static halo-exchange + local-SpMM plan for one (graph, partvec) pair.

    All per-chip arrays are stacked along a leading ``k`` axis so they can be
    sharded over a 1D device mesh with ``PartitionSpec('v')``.
    """

    n: int                    # global vertex count
    k: int                    # number of parts / chips
    b: int                    # padded local rows per chip (max part size)
    s: int                    # padded send-bucket size per (src, dst) pair
    r: int                    # padded halo rows per chip
    e: int                    # padded local nnz per chip

    # vertex relabeling
    owner: np.ndarray         # (n,) chip owning each global vertex
    local_idx: np.ndarray     # (n,) local slot of each global vertex on its owner
    part_sizes: np.ndarray    # (k,) true part sizes (<= b)

    # halo exchange layout (stacked over chips)
    send_idx: np.ndarray      # (k, k, S) int32: local rows p sends to q
    send_counts: np.ndarray   # (k, k) int32: valid prefix of send_idx[p, q]
    halo_src: np.ndarray      # (k, R) int32: flat (q*S + t) recv-buffer gather
    halo_counts: np.ndarray   # (k,) int32: valid halo rows per chip

    # local sparse block as padded edge lists (sorted by dst for segment_sum)
    edge_dst: np.ndarray      # (k, E) int32 local row in [0, B)
    edge_src: np.ndarray      # (k, E) int32 index into [local; halo] in [0, B+R)
    edge_w: np.ndarray        # (k, E) float32, 0 on padding
    nnz: np.ndarray           # (k,) true local nnz

    row_valid: np.ndarray     # (k, B) float32 1/0 mask of real (non-pad) rows

    # The same edges split by source locality — the overlap structure of the
    # reference's forward (``Parallel-GCN/main.c:238-299``): the local-src
    # segment-sum depends only on ``h``, so XLA can run it while the halo
    # all_to_all is in flight, then the halo-src segment-sum folds the remote
    # contribution in (``AH = Â·H_local + Σ Â·Ĥ_r``).  ``ledge_src`` indexes
    # local rows [0, B); ``hedge_src`` indexes the halo block [0, R).
    el: int                   # padded local-src nnz per chip
    eh: int                   # padded halo-src nnz per chip
    ledge_dst: np.ndarray     # (k, EL) int32
    ledge_src: np.ndarray     # (k, EL) int32
    ledge_w: np.ndarray       # (k, EL) float32, 0 on padding
    hedge_dst: np.ndarray     # (k, EH) int32
    hedge_src: np.ndarray     # (k, EH) int32
    hedge_w: np.ndarray       # (k, EH) float32, 0 on padding
    lnnz: np.ndarray          # (k,) true local-src nnz
    hnnz: np.ndarray          # (k,) true halo-src nnz

    # The local-src edges again, in BUCKETED ELL layout.  Rows are stored in
    # degree buckets: bucket j covers the next ``nb_j`` rows at fixed width
    # ``wb_j`` (``ell_buckets = ((nb_0, wb_0), ...)``, Σ nb_j = B), and row
    # r's in-edges occupy ``wb_j`` flat slots starting at its bucket base.
    # The hot SpMM is, per bucket, ONE 2D-index gather + dense weighted
    # reduce over the width axis — no segment machinery, no scatter.  Under
    # ``row_order='degree'`` (the trainer default) rows are relabeled
    # descending by local in-degree, so bucket widths hug the degree profile
    # and padding drops from the single-width ELL's ~1.7× (Poisson graphs)
    # to ~1.1-1.2×; the gather is row-rate-bound on v5e (~350-400 Mrows/s
    # regardless of index pattern or dtype), so fewer gathered rows is the
    # only lever that pays.  Under ``row_order='id'`` a single bucket plus
    # the COO overflow tail reproduces the classic ELL+tail layout.
    ell_k: int                # max bucket width (informational; >= 1)
    tl: int                   # padded tail length
    ell_buckets: tuple        # ((nb, wb), ...) static bucket structure
    ell_idx: np.ndarray       # (k, ET) int32 flat local src, 0 on padding
    ell_w: np.ndarray         # (k, ET) float32 flat, 0 on padding
    ltail_dst: np.ndarray     # (k, TL) int32
    ltail_src: np.ndarray     # (k, TL) int32
    ltail_w: np.ndarray       # (k, TL) float32, 0 on padding
    ltail_nnz: np.ndarray     # (k,) true tail nnz
    row_order: str            # 'degree' (bucketed) or 'id' (emit-compatible)

    # True when the global adjacency is numerically symmetric (Â = Âᵀ) —
    # verified at plan-build time.  Lets the SpMM backward reuse the forward
    # structure (Âᵀg = Âg) instead of JAX's mechanical transpose, whose
    # scatter-add is ~3.6× slower than the gather form on v5e.  The
    # reference makes the same assumption (backward uses A, not Aᵀ —
    # Parallel-GCN/main.c:374-404).
    symmetric: bool

    # The COMBINED edge list (src in [0, B+R), local ‖ halo) in the same
    # bucketed width-major layout — for ops that must see every in-edge of a
    # row at once: the GAT edge-softmax normalizes over local AND halo
    # neighbors together, so it streams these slots with an online-softmax
    # (running max / denominator) instead of segment machinery.  Built
    # LAZILY (``ensure_cell()``) — only the GAT model ships these arrays,
    # and they duplicate the edge storage.
    ctl: int | None = None            # padded combined-tail length
    cell_buckets: tuple | None = None  # ((nb, wb), ...) static structure
    cell_idx: np.ndarray | None = None   # (k, CET) int32 flat src
    cell_w: np.ndarray | None = None     # (k, CET) float32, 0 on padding
    ctail_dst: np.ndarray | None = None  # (k, CTL) int32
    ctail_src: np.ndarray | None = None  # (k, CTL) int32
    ctail_w: np.ndarray | None = None    # (k, CTL) float32, 0 on padding
    ctail_nnz: np.ndarray | None = None  # (k,) true combined-tail nnz

    # Pallas dst-tile layout (lazy, ``ensure_pallas_tiles``): the local-src
    # and halo-src edge families regrouped into tb-row tiles, tiles binned
    # into DEGREE-ALIGNED CLASSES (``tile_classes_from_buckets`` over the
    # plan's ell_buckets histogram) each padded to its OWN Emax_c, stored
    # FLAT per chip (class c owns the next T_c·Emax_c slots) with the
    # static structure in ``pallas_lclasses``/``pallas_hclasses`` — for
    # the VMEM-resident SpMM kernel (``ops/pallas_spmm.py``), selected by
    # the trainer when per-chip tables fit the kernel's VMEM budget, which
    # is exactly what k-way sharding produces as k grows.  The ragged
    # variant (``ensure_pallas_ragged_tiles``) re-bases the halo tile
    # sources from halo RANKS to RING positions (the round-major receive
    # concat of the ppermute ring), so the kernel folds receive buffers
    # directly — no HBM halo table.  The combined-edge family
    # (``ensure_pallas_cell_tiles``, GAT) carries 0/1 MASK weights
    # (attention ignores Â's values) over [local ‖ halo] sources.
    pallas_tb: int | None = None          # static tile height
    pallas_lclasses: tuple | None = None  # ((T_c, Emax_c), ...) local
    pallas_hclasses: tuple | None = None  # ((T_c, Emax_c), ...) halo
    ptile_lsrc: np.ndarray | None = None  # (k, ΣT_c·Emax_c) int32
    ptile_lld: np.ndarray | None = None   # (k, ΣT_c·Emax_c) int32 local dst
    ptile_lw: np.ndarray | None = None    # (k, ΣT_c·Emax_c) float32
    ptile_hsrc: np.ndarray | None = None  # (k, ΣT_c·Emax_c) int32 halo rank
    ptile_hld: np.ndarray | None = None   # (k, ΣT_c·Emax_c) int32
    ptile_hw: np.ndarray | None = None    # (k, ΣT_c·Emax_c) float32
    ptile_hrsrc: np.ndarray | None = None  # (k, ΣT_c·Emax_c) int32 RING pos
    pallas_ctb: int | None = None          # static combined tile height
    pallas_cclasses: tuple | None = None   # ((T_c, Emax_c), ...) combined
    ptile_csrc: np.ndarray | None = None   # (k, ·) int32 src in [0, B+R)
    ptile_cld: np.ndarray | None = None    # (k, ·) int32 local dst
    ptile_cw: np.ndarray | None = None     # (k, ·) float32 0/1 edge mask
    ptile_crsrc: np.ndarray | None = None  # (k, ·) int32 src in
    #                                        [0, B+ΣS_d): halo part re-based
    #                                        to B + ring position

    # Ragged ppermute-ring exchange layout (lazy, ``ensure_ragged``): the
    # reference's point-to-point halo protocol re-expressed as k−1 rounds of
    # ``lax.ppermute`` where round d carries chip p → chip (p+d)%k in a
    # buffer statically sized to S_d = max_p send_counts[p, (p+d)%k] — a
    # PER-ROUND pad instead of the dense all_to_all's global S, so skewed
    # partitions stop paying k²·S wire slots for a Σ(λ−1) exchange.  All
    # round segments are flattened along the trailing axis (round d's slots
    # start at Σ_{d'<d} S_{d'}); ``rr_sizes``/``rr_edge_sizes`` are the
    # static per-round offsets the op unrolls over (rounds with S_d = 0 are
    # skipped at trace time).  ``redge_*`` is the halo-src edge family split
    # per owner (= per round) at plan time with src re-based to the round's
    # receive buffer — the fold-as-you-arrive structure of the reference's
    # post-Irecv accumulate loop (``Parallel-GCN/main.c:238-299``).
    rr_sizes: tuple | None = None        # (k-1,) static per-round send size S_d
    rr_edge_sizes: tuple | None = None   # (k-1,) static per-round edge pad
    rsend_idx: np.ndarray | None = None  # (k, ΣS_d) int32 local rows to ship
    rhalo_dst: np.ndarray | None = None  # (k, ΣS_d) int32 halo rank per recv
    #                                      slot (r = padding, dropped)
    redge_dst: np.ndarray | None = None  # (k, ΣE_d) int32 local dst row
    redge_src: np.ndarray | None = None  # (k, ΣE_d) int32 round recv-buffer row
    redge_w: np.ndarray | None = None    # (k, ΣE_d) float32, 0 on padding

    # Hot-halo replication layout (lazy, ``ensure_replicas``): the top-B
    # boundary rows by λ·degree (λ = consumer chips per row, degree = remote
    # edges consuming it — both straight from the comm plan) are promoted to
    # PERSISTENT REPLICAS on their consumer chips (CaPGNN-style,
    # arXiv:2508.13716).  Replicated rows leave the per-layer wire entirely:
    # the ``nrep_*`` layout is the send/receive structure with those rows
    # deleted (per-pair buckets re-packed to the shrunken pad ``nrep_s``;
    # per-round ring sizes shrunk to ``nrep_rr_sizes``), and ``rep_slots``
    # names the halo-table ranks each chip fills from its carried replica
    # table instead.  Refresh rides the FULL exchange on sync steps — the
    # sync program IS the exact program plus carry gathers (``rep_ring_pos``
    # locates each replica row in the full ring's round-major receive
    # concat), which is what makes ``--sync-every 1`` f32-bit-identical to
    # the no-replica path (docs/replication.md).
    replica_budget: int | None = None     # the budget B ensure_replicas ran at
    rp: int | None = None                 # padded replica slots per chip
    replica_rows: int = 0                 # global replicated rows (<= B)
    replica_send_saving: int = 0          # Σ λ_v — true rows off the wire
    #                                       per exchange
    rep_slots: np.ndarray | None = None   # (k, RP) halo ranks; r = pad (drop)
    rep_counts: np.ndarray | None = None  # (k,) true replica slots per chip
    nrep_s: int | None = None             # shrunken per-pair bucket pad
    nrep_send_idx: np.ndarray | None = None     # (k, k, S') int32
    nrep_send_counts: np.ndarray | None = None  # (k, k) int32
    nrep_halo_src: np.ndarray | None = None     # (k, R) int32; replica slots
    #                                             point at 0 (overwritten)
    nrep_rr_sizes: tuple | None = None          # shrunken per-round sizes
    nrep_rsend_idx: np.ndarray | None = None    # (k, ΣS'_d) int32
    nrep_rhalo_dst: np.ndarray | None = None    # (k, ΣS'_d) int32; r = pad
    rep_ring_pos: np.ndarray | None = None      # (k, RP) int32 into the full
    #                                             (ΣS_d) ring concat
    nrep_ring_dst: np.ndarray | None = None     # (k, ΣS'_d) int32: each
    #                                             shrunken receive slot's
    #                                             position in the FULL ring
    #                                             concat (ΣS_d = pad, dropped)
    #                                             — the composed replica ×
    #                                             stale carry scatter map
    # Partial-refresh side channel (``--refresh-band``): the SENDER's view
    # of its own replicated rows (local ids + per-pair replica-only buckets
    # = exactly the rows deleted from ``nrep_*``) and the RECEIVER's routing
    # of refreshed rows into the carried replica table.
    rs: int | None = None                       # padded owned-replicated rows
    rep_rows: np.ndarray | None = None          # (k, RS) int32 local row ids
    rep_row_counts: np.ndarray | None = None    # (k,) int32 true counts
    ronly_s: int | None = None                  # replica-only bucket pad
    ronly_send_idx: np.ndarray | None = None    # (k, k, RS') int32 local rows
    ronly_send_counts: np.ndarray | None = None  # (k, k) int32
    ronly_base_pos: np.ndarray | None = None    # (k, k, RS') int32 into
    #                                             rep_rows (baseline row)
    rep_recv_src: np.ndarray | None = None      # (k, RP) int32 flat
    #                                             (o·RS' + pos) receive index
    #                                             per carried replica slot

    # identities of the chips this (possibly sliced) plan's rows describe —
    # set by the shard proxy (``parallel/proxy.py``) so the comm-stat
    # properties zero each row's TRUE self-slot rather than assuming row i
    # talks to itself at column i.  None = the full square plan.
    chip_ids: np.ndarray | None = None

    def _pallas_family(self, dst, src, w, tb: int, class_tiles):
        """Stack one edge family's per-chip tile classes into flat
        ``(k, ΣT_c·Emax_c)`` arrays (per class, Emax_c padded to the max
        across chips so the arrays shard) + the static class structure."""
        from ..ops.pallas_spmm import build_dst_tile_classes

        per = [build_dst_tile_classes(dst[p], src[p], w[p], self.b, tb,
                                      class_tiles)
               for p in range(self.k)]
        fills = (0, tb - 1, 0.0)           # src, local dst, weight pads
        dtypes = (np.int32, np.int32, np.float32)
        flats: list[list] = [[], [], []]
        classes = []
        for c, tc in enumerate(class_tiles):
            emax = max(x[c][0].shape[1] for x in per)
            classes.append((int(tc), int(emax)))
            for i in range(3):
                flats[i].append(np.stack([
                    np.pad(x[c][i], ((0, 0), (0, emax - x[c][i].shape[1])),
                           constant_values=fills[i]).astype(dtypes[i])
                    .reshape(-1) for x in per]))
        return tuple(np.concatenate(f, axis=1) for f in flats) \
            + (tuple(classes),)

    def ensure_pallas_tiles(self, tb: int = 256) -> "CommPlan":
        """Build the Pallas dst-tile layout on first use.

        Per chip, ``build_dst_tile_classes`` regroups the dst-sorted
        local-src and halo-src edge lists into ``tb``-row tiles binned
        into degree-aligned classes (``tile_classes_from_buckets`` over
        ``ell_buckets`` — each class pads to its OWN Emax_c instead of the
        hub tile's global max); per class, Emax_c is padded to the max
        across chips so the flat arrays stack into the usual (k, ...)
        sharded form.  Padding edges carry weight 0 (no-ops in the
        kernel).
        """
        if self.pallas_tb == tb and self.ptile_lsrc is not None:
            return self
        from ..ops.pallas_spmm import tile_classes_from_buckets

        class_tiles = tile_classes_from_buckets(self.ell_buckets, self.b, tb)
        (self.ptile_lsrc, self.ptile_lld, self.ptile_lw,
         self.pallas_lclasses) = self._pallas_family(
            self.ledge_dst, self.ledge_src, self.ledge_w, tb, class_tiles)
        (self.ptile_hsrc, self.ptile_hld, self.ptile_hw,
         self.pallas_hclasses) = self._pallas_family(
            self.hedge_dst, self.hedge_src, self.hedge_w, tb, class_tiles)
        self.pallas_tb = tb
        self.ptile_hrsrc = None            # ring re-base follows the layout
        return self

    def _ring_pos_of_rank(self) -> np.ndarray:
        """(k, R+1) map halo rank → position in the ragged ring's
        round-major receive concat (``ensure_ragged``'s rhalo_dst,
        inverted; the extra slot absorbs the pad rank R)."""
        if self.rhalo_dst is None:
            raise ValueError(
                "ring positions need the ragged layout (ensure_ragged)")
        st = self.rsend_idx.shape[1]
        pos = np.zeros((self.k, self.r + 1), np.int64)
        ar = np.arange(st)
        for p in range(self.k):
            pos[p, self.rhalo_dst[p]] = ar
        return pos

    def ensure_pallas_ragged_tiles(self) -> "CommPlan":
        """Re-base the halo tile sources from halo RANKS to RING positions
        (``ptile_hrsrc``) so the Pallas kernel reads the ppermute ring's
        round-major receive concat directly — same tiles, same per-tile
        edge order as the a2a flavor's, which is the f32 bit-parity
        contract of ``pspmm_pallas_ragged``; no (R, f) halo table is ever
        materialized.  Needs ``ensure_pallas_tiles`` + ``ensure_ragged``.
        """
        if self.ptile_hrsrc is not None:
            return self
        if self.ptile_hsrc is None:
            raise ValueError(
                "ragged pallas tiles need the tile layout first "
                "(ensure_pallas_tiles)")
        pos = self._ring_pos_of_rank()
        self.ptile_hrsrc = np.stack([
            pos[p][self.ptile_hsrc[p]] for p in range(self.k)
        ]).astype(np.int32)
        return self

    def ensure_pallas_cell_tiles(self, tb: int = 256) -> "CommPlan":
        """Build the COMBINED-edge Pallas tile layout on first use (GAT):
        the ``[local ‖ halo]``-sourced edge family in the same
        degree-binned tile classes (histogram: ``cell_buckets``), with 0/1
        MASK weights — the GAT slot passes aggregate by edge presence, not
        Â's values (``models/gat.py``)."""
        if self.pallas_ctb == tb and self.ptile_csrc is not None:
            return self
        from ..ops.pallas_spmm import tile_classes_from_buckets

        self.ensure_cell()
        class_tiles = tile_classes_from_buckets(self.cell_buckets, self.b,
                                                tb)
        mask = (np.asarray(self.edge_w) != 0).astype(np.float32)
        (self.ptile_csrc, self.ptile_cld, self.ptile_cw,
         self.pallas_cclasses) = self._pallas_family(
            self.edge_dst, self.edge_src, mask, tb, class_tiles)
        self.pallas_ctb = tb
        self.ptile_crsrc = None            # ring re-base follows the layout
        return self

    def ensure_pallas_cell_ragged_tiles(self) -> "CommPlan":
        """Combined-tile sources for the ragged ring: local sources stay,
        halo sources (≥ B) re-base to ``B +`` their ring position — the
        kernel table is ``[local table ‖ ring concat]``, no halo-table
        scatter (cf. ``ensure_pallas_ragged_tiles``)."""
        if self.ptile_crsrc is not None:
            return self
        if self.ptile_csrc is None:
            raise ValueError(
                "ragged pallas cell tiles need the combined tile layout "
                "first (ensure_pallas_cell_tiles)")
        pos = self._ring_pos_of_rank()
        out = []
        for p in range(self.k):
            src = self.ptile_csrc[p]
            halo = src >= self.b
            out.append(np.where(halo, self.b + pos[p][np.where(
                halo, src - self.b, 0)], src))
        self.ptile_crsrc = np.stack(out).astype(np.int32)
        return self

    def ensure_cell(self, buckets: tuple | None = None,
                    ctl: int | None = None,
                    max_buckets: int | None = None) -> "CommPlan":
        """Build the combined-edge bucketed layout on first use (GAT).

        ``max_buckets`` overrides the bucket-count cap (A/B lever).  Keep
        the default: the round-4 trace showed ~2,500 small slot gathers and
        suggested merging buckets, but the A/B measured the 2-bucket layout
        WORSE (18.8 s vs 15.9 s products ER GAT) — the scheduler overlaps
        the unrolled small gathers well, and wider buckets pay real padded
        rows.  Recorded so the next round does not retry it.
        """
        if (self.cell_buckets is None
                or buckets not in (None, self.cell_buckets)
                or (ctl is not None and ctl != self.ctl)):
            if max_buckets is None:
                max_buckets = 6
            fields = _cell_fields(_build_ell(
                self.edge_dst, self.edge_src, self.edge_w, self.nnz, self.b,
                row_order=self.row_order, buckets=buckets, tl=ctl,
                max_buckets=max_buckets))
            for name, val in fields.items():
                setattr(self, name, val)
        return self

    # -------------------------------------------------------- ragged schedule
    def ragged_round_sizes(self) -> tuple:
        """Natural per-round send sizes S_d = max_p send_counts[p, (p+d)%k]
        for d = 1..k−1 — the static buffer sizes of the ragged ppermute ring
        (round d carries chip p → chip (p+d)%k).  Needs the full square
        plan; a shard-proxy slice keeps the tuple built before slicing."""
        sc = np.asarray(self.send_counts)
        if sc.ndim != 2 or sc.shape[0] != sc.shape[1]:
            raise ValueError(
                f"ragged_round_sizes needs the full square plan "
                f"(send_counts {sc.shape}); build the ragged layout with "
                "ensure_ragged() BEFORE shard_proxy_plan slicing")
        k = sc.shape[0]
        idx = np.arange(k)
        return tuple(int(sc[idx, (idx + d) % k].max()) for d in range(1, k))

    def padding_efficiency(self) -> float:
        """Σ send_counts / (k²·S): the fraction of the dense all_to_all's
        padded wire slots that carry real boundary rows.  The auto-select
        gauge of ``SGCN_COMM_SCHEDULE=auto`` (``RAGGED_AUTO_EFFICIENCY``)
        and the ``padding_efficiency`` field of the obs event stream.  On a
        shard-proxy slice the numerator covers the rows in view and the
        denominator scales with them, so the figure stays comparable."""
        wire = self.wire_rows_per_exchange("a2a")
        return float(self.send_counts.sum()) / wire if wire else 1.0

    def wire_rows_per_exchange(self, schedule: str = "a2a",
                               replica: bool = False) -> int:
        """Padded rows the selected schedule puts on the wire per exchange,
        over the chips in view (full plan: all k).  Dense a2a ships the
        whole (k, S) buffer per chip = k²·S rows; the ragged ring ships
        Σ_d S_d rows per chip = k·Σ_d S_d — the padded-vs-true accounting
        the roofline and CommStats report against ``predicted_send_volume``
        (= Σ(λ−1), the true rows).  ``replica=True`` prices the shrunken
        NO-REPLICA exchange of a ``--replica-budget`` step
        (``ensure_replicas``): the ``nrep_*`` pads replace ``s`` /
        ``rr_sizes``."""
        rows, peers = np.asarray(self.send_counts).shape
        if replica and self.rep_slots is None:
            raise ValueError("build the replication layout first "
                             "(ensure_replicas)")
        if schedule == "a2a":
            return int(rows * peers * (self.nrep_s if replica else self.s))
        if schedule == "ragged":
            if replica:
                if self.nrep_rr_sizes is None:
                    raise ValueError(
                        "ragged replica wire needs ensure_ragged() before "
                        "ensure_replicas()")
                sizes = self.nrep_rr_sizes
            else:
                sizes = (self.rr_sizes if self.rr_sizes is not None
                         else self.ragged_round_sizes())
            return int(rows * sum(sizes))
        raise ValueError(f"unknown comm schedule {schedule!r}")

    def wire_buffer_shapes(self, schedule: str = "a2a",
                           replica: bool = False) -> list:
        """Static per-DISPATCH wire-buffer shapes of ONE halo exchange,
        WITHOUT the trailing lane axis (the per-layer table width is the
        model's business — ``models.gcn.exchange_widths`` /
        ``models.gat.gat_exchange_lane_widths``).

        ``'a2a'``: one dispatch of the globally-padded ``(peers, S)`` bucket
        per exchange.  ``'ragged'``: one dispatch of ``(S_d,)`` per LIVE
        round (``ops.pspmm.ragged_live_rounds`` — empty rounds ship nothing
        and vanish from the traced program).  ``replica=True``: the
        shrunken no-replica exchange of a ``--replica-budget`` step — the
        ``nrep_s`` pad / live rounds of ``nrep_rr_sizes`` (same elision
        rule).  This is the shape side of the compiled-program wire
        contract the HLO audit (``sgcn_tpu/analysis``) checks against
        every lowered step.
        """
        if replica and self.rep_slots is None:
            raise ValueError("build the replication layout first "
                             "(ensure_replicas)")
        if schedule == "a2a":
            peers = int(np.asarray(self.send_counts).shape[1])
            return [(peers, self.nrep_s if replica else self.s)]
        if schedule == "ragged":
            # deferred: ops.pspmm imports jax; this module stays numpy-only
            from ..ops.pspmm import ragged_live_rounds

            if replica:
                if self.nrep_rr_sizes is None:
                    raise ValueError(
                        "ragged replica wire needs ensure_ragged() before "
                        "ensure_replicas()")
                sizes = self.nrep_rr_sizes
            else:
                sizes = (self.rr_sizes if self.rr_sizes is not None
                         else self.ragged_round_sizes())
            return [(int(sizes[d - 1]),)
                    for d in ragged_live_rounds(sizes)]
        raise ValueError(f"unknown comm schedule {schedule!r}")

    def ensure_ragged(self, rr_sizes: tuple | None = None,
                      rr_edge_sizes: tuple | None = None) -> "CommPlan":
        """Build the ragged ppermute-ring layout on first use.

        ``rr_sizes`` / ``rr_edge_sizes`` force larger per-round envelopes
        (the mini-batch trainer pads every batch plan to shared round sizes
        so one compiled step serves all batches, like ``pad_comm_plan``).

        Receive-side invariant: the plan's halo order is (owner, vertex) and
        each send list p→q is id-sorted, so round d's received rows land
        EXACTLY in chip q's contiguous per-owner halo slice, in order — the
        per-round edge split (``redge_*``) therefore re-bases hedge src
        straight to the round's receive buffer, and because ``hedge_*`` is
        sorted by (dst, round, recv-pos) at build time, folding round
        contributions into the output accumulator in round order applies
        per-row updates in the SAME sequence as the dense path's single
        halo-src segment-sum — the f32 bit-parity contract of the two
        schedules (tests/test_ragged.py).
        """
        if (self.rr_sizes is not None
                and rr_sizes in (None, self.rr_sizes)
                and rr_edge_sizes in (None, self.rr_edge_sizes)):
            return self
        nat_sizes = self.ragged_round_sizes()
        k, s, r = self.k, self.s, self.r
        sc = np.asarray(self.send_counts)
        if rr_sizes is None:
            rr_sizes = nat_sizes
        elif (len(rr_sizes) != len(nat_sizes)
                or any(a < b for a, b in zip(rr_sizes, nat_sizes))):
            raise ValueError(
                f"forced rr_sizes {rr_sizes} smaller than natural "
                f"{nat_sizes}")
        rr_sizes = tuple(int(x) for x in rr_sizes)
        owner_rank = np.asarray(self.halo_src) // s       # (k, R) owner per
        pos_rank = np.asarray(self.halo_src) % s          # halo rank + pos
        st = max(1, sum(rr_sizes))
        rsend_idx = np.zeros((k, st), np.int32)
        rhalo_dst = np.full((k, st), r, np.int32)         # r = dropped pad
        off = 0
        for d, sd in enumerate(rr_sizes, start=1):
            for p in range(k):
                cnt = int(sc[p, (p + d) % k])             # send side: p → p+d
                rsend_idx[p, off: off + cnt] = self.send_idx[p, (p + d) % k,
                                                             :cnt]
                o = (p - d) % k                           # recv side: o → p
                rc = int(sc[o, p])
                if rc:
                    hs = int(self.halo_counts[p])
                    ranks = np.nonzero(owner_rank[p, :hs] == o)[0]
                    if len(ranks) != rc:                  # plan invariant
                        raise ValueError(
                            f"halo sublist of owner {o} on chip {p} has "
                            f"{len(ranks)} rows, send list says {rc}")
                    rhalo_dst[p, off: off + rc] = ranks.astype(np.int32)
            off += sd
        # per-round halo-src edge families: hedge is (dst, round, pos)-sorted
        # at build time, so each round's subsequence is (dst, pos)-sorted
        per_chip_rounds: list[list] = []
        for q in range(k):
            cnt = int(self.hnnz[q])
            d_ = self.hedge_dst[q, :cnt]
            s_ = self.hedge_src[q, :cnt]
            w_ = self.hedge_w[q, :cnt]
            fold = (q - owner_rank[q, s_]) % k            # arrival round
            per_chip_rounds.append(
                [(d_[fold == d], pos_rank[q, s_[fold == d]], w_[fold == d])
                 for d in range(1, k)])
        nat_es = tuple(
            max((len(per_chip_rounds[q][d][0]) for q in range(k)), default=0)
            for d in range(max(k - 1, 0)))
        if rr_edge_sizes is None:
            rr_edge_sizes = nat_es
        elif (len(rr_edge_sizes) != len(nat_es)
                or any(a < b for a, b in zip(rr_edge_sizes, nat_es))):
            raise ValueError(
                f"forced rr_edge_sizes {rr_edge_sizes} smaller than natural "
                f"{nat_es}")
        rr_edge_sizes = tuple(int(x) for x in rr_edge_sizes)
        et = max(1, sum(rr_edge_sizes))
        redge_dst = np.full((k, et), self.b - 1, np.int32)
        redge_src = np.zeros((k, et), np.int32)
        redge_w = np.zeros((k, et), np.float32)
        off = 0
        for d, ed in enumerate(rr_edge_sizes):
            for q in range(k):
                dd, ss, ww = per_chip_rounds[q][d]
                redge_dst[q, off: off + len(dd)] = dd
                redge_src[q, off: off + len(ss)] = ss
                redge_w[q, off: off + len(ww)] = ww
            off += ed
        self.rr_sizes = rr_sizes
        self.rr_edge_sizes = rr_edge_sizes
        self.rsend_idx = rsend_idx
        self.rhalo_dst = rhalo_dst
        self.redge_dst = redge_dst
        self.redge_src = redge_src
        self.redge_w = redge_w
        return self

    # ----------------------------------------------------- hot-halo replicas
    def replica_scores(self) -> tuple:
        """Per (owner chip, local row): ``(λ, consumer-edge count)`` of every
        owned row, straight from the comm plan — λ is the number of consumer
        chips the row ships to per exchange (its send-list multiplicity) and
        the edge count is how many remote halo-src edges reference it (the
        aggregation work its replica would feed).  ``λ·edges`` is THE
        replica ranking (ISSUE/ROADMAP: λ·degree); the native partitioner's
        cache-aware objective ranks nets by the same quantity
        ((λ−1)·pins in hypergraph terms — the owner part is a pin there).
        Needs the full square plan."""
        sc = np.asarray(self.send_counts)
        if sc.ndim != 2 or sc.shape[0] != sc.shape[1]:
            raise ValueError(
                "replica selection needs the full square plan "
                f"(send_counts {sc.shape}); build replicas with "
                "ensure_replicas() BEFORE shard_proxy_plan slicing")
        k, b, s = self.k, self.b, self.s
        lam = np.zeros((k, b), np.int64)
        cons = np.zeros((k, b), np.int64)
        for q in range(k):
            hs = int(self.halo_counts[q])
            if not hs:
                continue
            hedge_cnt = np.bincount(self.hedge_src[q, : int(self.hnnz[q])],
                                    minlength=self.r)
            slots = np.asarray(self.halo_src[q, :hs])
            o = slots // s
            j = slots % s
            rows = self.send_idx[o, q, j]
            np.add.at(lam, (o, rows), 1)
            np.add.at(cons, (o, rows), hedge_cnt[:hs])
        return lam, cons

    def ensure_replicas(self, budget: int) -> "CommPlan":
        """Build the hot-halo replication layout for ``budget`` rows.

        Selects the top-``budget`` boundary rows globally by λ·degree
        (``replica_scores``; deterministic tie-break on (owner, row)), then
        derives the shrunken no-replica exchange layout: per-pair send
        buckets with those rows deleted (a2a) and, when the ragged layout
        exists, the shrunken per-round ring (``nrep_rr_sizes`` +
        send/receive maps).  Kept rows preserve their relative order on
        both ends, so the shrunken receive side stays aligned with the
        shrunken send side by construction.  A budget above the boundary
        row count clamps (everything replicated — the communication-free
        limit).  Idempotent per budget; call ``ensure_ragged()`` FIRST when
        the ragged schedule is in play (the ring shrink needs the round
        envelope, and ``rep_ring_pos`` indexes the full ring's concat).
        """
        if budget < 0:
            raise ValueError(f"replica budget must be >= 0, got {budget}")
        ring = self.rr_sizes is not None
        if (self.replica_budget == budget and self.rep_slots is not None
                and (not ring or self.nrep_rsend_idx is not None)):
            return self
        k, b, s, r = self.k, self.b, self.s, self.r
        sc = np.asarray(self.send_counts)
        lam, cons = self.replica_scores()
        score = (lam * cons).ravel()
        boundary = np.nonzero(lam.ravel() > 0)[0]
        order = boundary[np.lexsort((boundary, -score[boundary]))]
        chosen = order[:budget]
        rep_mask = np.zeros(k * b, bool)
        rep_mask[chosen] = True
        rep_mask = rep_mask.reshape(k, b)
        self.replica_rows = int(len(chosen))
        self.replica_send_saving = int(lam.ravel()[chosen].sum())
        # shrunken send buckets: kept entries keep their id-sorted order
        nrep_counts = np.zeros((k, k), np.int32)
        kept_lists: dict[tuple[int, int], np.ndarray] = {}
        for p in range(k):
            for q in range(k):
                cnt = int(sc[p, q])
                if not cnt:
                    continue
                rows = self.send_idx[p, q, :cnt]
                kept = np.nonzero(~rep_mask[p, rows])[0]
                kept_lists[(p, q)] = kept
                nrep_counts[p, q] = len(kept)
        nrep_s = max(1, int(nrep_counts.max()) if k else 1)
        nrep_send_idx = np.zeros((k, k, nrep_s), np.int32)
        for (p, q), kept in kept_lists.items():
            nrep_send_idx[p, q, : len(kept)] = self.send_idx[p, q, kept]
        # partial-refresh side channel (``--refresh-band``): the sender's
        # owned replicated rows (drift is measured against a baseline per
        # OWNED row, not per consumer copy) and the replica-only per-pair
        # buckets — exactly the complement of the kept lists above, order
        # preserved so the receive side stays aligned by construction
        rows_lists = [np.nonzero(rep_mask[p])[0] for p in range(k)]
        rs = max(1, max((len(x) for x in rows_lists), default=0))
        rep_rows = np.zeros((k, rs), np.int32)
        rep_row_counts = np.zeros(k, np.int32)
        for p in range(k):
            rep_rows[p, : len(rows_lists[p])] = rows_lists[p]
            rep_row_counts[p] = len(rows_lists[p])
        ronly_counts = (sc.astype(np.int32) - nrep_counts)
        ronly_s = max(1, int(ronly_counts.max()) if k else 1)
        ronly_send_idx = np.zeros((k, k, ronly_s), np.int32)
        ronly_base_pos = np.zeros((k, k, ronly_s), np.int32)
        for p in range(k):
            for q in range(k):
                cnt = int(sc[p, q])
                if not cnt:
                    continue
                rows_pq = self.send_idx[p, q, :cnt]
                deleted = np.nonzero(rep_mask[p, rows_pq])[0]
                if not len(deleted):
                    continue
                ronly_send_idx[p, q, : len(deleted)] = rows_pq[deleted]
                ronly_base_pos[p, q, : len(deleted)] = np.searchsorted(
                    rows_lists[p], rows_pq[deleted]).astype(np.int32)
        # receive side: shrunken halo gather + replica slot lists.  Ring
        # positions: round d's receive slice starts at Σ_{d'<d} S_d' and a
        # slot's within-round position is its send-list position j
        # (ensure_ragged's receive invariant).
        offsets = (np.concatenate([[0], np.cumsum(self.rr_sizes)])
                   if ring else None)
        nrep_halo_src = np.zeros((k, r), np.int32)
        rep_slot_lists, rep_ring_lists, rep_recv_lists = [], [], []
        for q in range(k):
            hs = int(self.halo_counts[q])
            if not hs:
                rep_slot_lists.append(np.zeros(0, np.int64))
                rep_ring_lists.append(np.zeros(0, np.int64))
                rep_recv_lists.append(np.zeros(0, np.int64))
                continue
            slots = np.asarray(self.halo_src[q, :hs])
            o = slots // s
            j = slots % s
            rows = self.send_idx[o, q, j]
            keep = ~rep_mask[o, rows]
            newpos = np.zeros(hs, np.int64)
            npos_del = np.zeros(hs, np.int64)
            for oo in np.unique(o):
                m = o == oo
                newpos[m] = np.cumsum(keep[m]) - 1
                npos_del[m] = np.cumsum(~keep[m]) - 1
            nrep_halo_src[q, :hs] = np.where(
                keep, o * nrep_s + newpos, 0).astype(np.int32)
            reps = np.nonzero(~keep)[0]
            rep_slot_lists.append(reps)
            # partial refresh routes each carried replica slot to its row's
            # position in the replica-only receive buffer (same ordering as
            # the ronly send buckets — deleted rows keep send-list order)
            rep_recv_lists.append(o[reps] * ronly_s + npos_del[reps])
            if ring:
                d = (q - o) % k
                rep_ring_lists.append(offsets[d[reps] - 1] + j[reps])
            else:
                rep_ring_lists.append(np.zeros(0, np.int64))
        rp = max(1, max((len(x) for x in rep_slot_lists), default=0))
        rep_slots = np.full((k, rp), r, np.int32)
        rep_ring_pos = np.zeros((k, rp), np.int32)
        rep_recv_src = np.zeros((k, rp), np.int32)
        for q in range(k):
            rep_slots[q, : len(rep_slot_lists[q])] = rep_slot_lists[q]
            rep_recv_src[q, : len(rep_recv_lists[q])] = rep_recv_lists[q]
            if ring:
                rep_ring_pos[q, : len(rep_ring_lists[q])] = \
                    rep_ring_lists[q]
        self.rep_counts = np.array([len(x) for x in rep_slot_lists],
                                   np.int64)
        self.rep_slots = rep_slots
        self.rp = rp
        self.nrep_s = nrep_s
        self.nrep_send_idx = nrep_send_idx
        self.nrep_send_counts = nrep_counts
        self.nrep_halo_src = nrep_halo_src
        self.rep_ring_pos = rep_ring_pos if ring else None
        self.rs = rs
        self.rep_rows = rep_rows
        self.rep_row_counts = rep_row_counts
        self.ronly_s = ronly_s
        self.ronly_send_idx = ronly_send_idx
        self.ronly_send_counts = ronly_counts
        self.ronly_base_pos = ronly_base_pos
        self.rep_recv_src = rep_recv_src
        if ring:
            idxk = np.arange(k)
            nrr = tuple(int(nrep_counts[idxk, (idxk + d) % k].max())
                        for d in range(1, k))
            st = max(1, sum(nrr))
            full_total = int(sum(self.rr_sizes))
            nrep_rsend_idx = np.zeros((k, st), np.int32)
            nrep_rhalo_dst = np.full((k, st), r, np.int32)
            # pad slots point one past the full ring concat — dropped by the
            # composed replica × stale carry scatter (mode='drop')
            nrep_ring_dst = np.full((k, st), full_total, np.int32)
            off = 0
            for d, sd in enumerate(nrr, start=1):
                for p in range(k):
                    q2 = (p + d) % k
                    cnt = int(nrep_counts[p, q2])
                    if cnt:
                        nrep_rsend_idx[p, off: off + cnt] = \
                            nrep_send_idx[p, q2, :cnt]
                    o = (p - d) % k
                    rc = int(nrep_counts[o, p])
                    if rc:
                        hs = int(self.halo_counts[p])
                        slots = np.asarray(self.halo_src[p, :hs])
                        oarr = slots // s
                        rows = self.send_idx[oarr, p, slots % s]
                        m = (oarr == o) & ~rep_mask[oarr, rows]
                        ranks = np.nonzero(m)[0]
                        if len(ranks) != rc:         # plan invariant
                            raise ValueError(
                                f"kept halo sublist of owner {o} on chip "
                                f"{p} has {len(ranks)} rows, shrunken send "
                                f"list says {rc}")
                        nrep_rhalo_dst[p, off: off + rc] = \
                            ranks.astype(np.int32)
                        # each kept receive slot's home in the FULL ring
                        # concat: its round offset + full send-list position
                        # (the ring receive invariant of ensure_ragged)
                        nrep_ring_dst[p, off: off + rc] = (
                            offsets[d - 1]
                            + (slots % s)[ranks]).astype(np.int32)
                off += sd
            self.nrep_rr_sizes = nrr
            self.nrep_rsend_idx = nrep_rsend_idx
            self.nrep_rhalo_dst = nrep_rhalo_dst
            self.nrep_ring_dst = nrep_ring_dst
        self.replica_budget = int(budget)
        return self

    def replica_carry_shapes(self, fin: int, widths,
                             partial: bool = False) -> dict:
        """Per-layer replica-carry shapes (WITHOUT the stacked leading k
        axis): one ``(RP, f_ℓ)`` feature-replica table and one gradient-
        replica table per layer, at the layer's EXCHANGED width
        (``models.gcn.exchange_widths`` — same lockstep rule as the stale
        carries).  ``partial=True`` (``--refresh-band``) adds the per-layer
        SENDER-side refresh baselines ``rep_base[ℓ]`` — one ``(RS, f_ℓ)``
        table of each chip's own replicated rows as of the last refresh,
        the reference the per-row drift band is measured against.
        Requires ``ensure_replicas()`` first."""
        from ..models.gcn import exchange_widths   # deferred: avoids a cycle

        if self.rep_slots is None:
            raise ValueError(
                "replica carries need the replication layout; call "
                "ensure_replicas() before replica_carry_shapes()")
        fs = exchange_widths(fin, list(widths))
        out = {
            "reps": [(self.rp, f) for f in fs],
            "greps": [(self.rp, f) for f in fs],
        }
        if partial:
            out["rep_base"] = [(self.rs, f) for f in fs]
        return out

    @property
    def partial_refresh_wire_rows(self) -> int:
        """Padded wire rows of ONE partial-refresh side-channel exchange
        (the replica-only a2a of ``--refresh-band`` refresh steps): the
        dense ``(k, RS')`` bucket per chip, on top of the shrunken
        ``nrep_*`` exchange those steps also ship."""
        if self.ronly_send_counts is None:
            raise ValueError("build the replication layout first "
                             "(ensure_replicas)")
        rows, peers = np.asarray(self.ronly_send_counts).shape
        return int(rows * peers * self.ronly_s)

    @property
    def replica_send_volume(self) -> np.ndarray:
        """Per-chip TRUE boundary rows shipped per NO-REPLICA exchange (k,)
        — ``predicted_send_volume`` minus each chip's replicated shipments
        (send lists never hold self-slots, so no diagonal correction)."""
        if self.nrep_send_counts is None:
            raise ValueError("build the replication layout first "
                             "(ensure_replicas)")
        return self.nrep_send_counts.astype(np.int64).sum(axis=1)

    # ------------------------------------------------------------ stale halo
    def stale_carry_shapes(self, fin: int, widths, delta: bool = False,
                           comm_schedule: str = "a2a") -> dict:
        """Per-layer carry shapes (WITHOUT the stacked leading k axis) for
        the pipelined stale-halo mode, SCHEDULE-AWARE.

        ``comm_schedule='a2a'`` (``ops.pspmm.pspmm_stale``):
        ``halos[ℓ]`` / ``ghalos[ℓ]`` are the ``(R, f_ℓ)`` feature- and
        gradient-halo buffers carried across steps, where ``f_ℓ`` is the
        layer's EXCHANGED row width under the trainer's project-first rule
        (``models.gcn.exchange_widths`` — the single shared encoding of that
        rule, so the carries stay in lockstep with the forward's schedule).
        ``bases[ℓ]``: the sender-side ``(k, S, f_ℓ)`` delta baseline when
        ``delta`` (the halo-delta cache), else a ``(1, 1, 1)`` placeholder
        so the carry pytree keeps one static structure per mode.

        ``comm_schedule='ragged'`` (``ops.pspmm.pspmm_stale_ragged``): the
        carries are ROUND-STRUCTURED — ``(Σ_d S_d, f_ℓ)`` round-major ring
        receive buffers (round d occupies its own ``rr_sizes[d-1]``-row
        slice), NOT the dense ``(R, f)`` halo table, and the delta baseline
        shrinks from ``(k, S, f_ℓ)`` to the same ``(Σ_d S_d, f_ℓ)`` ring
        envelope (placeholder ``(1, 1)``).  Requires ``ensure_ragged()``
        first — the round sizes ARE the carry layout.
        """
        from ..models.gcn import exchange_widths   # deferred: avoids a cycle

        fs = exchange_widths(fin, list(widths))
        if comm_schedule == "ragged":
            if self.rr_sizes is None:
                raise ValueError(
                    "round-structured stale carries need the ragged layout; "
                    "call ensure_ragged() before stale_carry_shapes("
                    "comm_schedule='ragged')")
            st = max(1, sum(self.rr_sizes))
            return {
                "halos": [(st, f) for f in fs],
                "ghalos": [(st, f) for f in fs],
                "bases": [((st, f) if delta else (1, 1)) for f in fs],
            }
        if comm_schedule != "a2a":
            raise ValueError(f"unknown comm_schedule {comm_schedule!r}")
        peers = self.send_idx.shape[1]   # == k on a full plan; kept explicit
                                         # so a shard-proxy slice stays right
        return {
            "halos": [(self.r, f) for f in fs],
            "ghalos": [(self.r, f) for f in fs],
            "bases": [((peers, self.s, f) if delta else (1, 1, 1))
                      for f in fs],
        }

    # ------------------------------------------------------------------ stats
    def offwire_send_counts(self) -> np.ndarray:
        """``send_counts`` with each row's SELF-slot zeroed — the rows that
        actually cross the wire.  On the full square plan row i's self-slot
        is column i; a shard-proxy slice records the true chip identity in
        ``chip_ids`` (row 0 of chip c's proxy self-sends at column c)."""
        off = self.send_counts.astype(np.int64).copy()
        if self.chip_ids is not None:
            off[np.arange(off.shape[0]), np.asarray(self.chip_ids)] = 0
        else:
            np.fill_diagonal(off, 0)
        return off

    @property
    def predicted_send_volume(self) -> np.ndarray:
        """Per-chip boundary rows shipped per exchange (k,).

        Matches the trainers' measured ``send_comm_volume``
        (``GPU/PGCN.py:105-114``, ``Parallel-GCN/main.c:264-265``) and the
        partitioners' connectivity metric Σ(λ−1)
        (``GCN-HP/main.cpp:335-345``).
        """
        return self.offwire_send_counts().sum(axis=1)

    @property
    def predicted_message_count(self) -> np.ndarray:
        """Per-chip count of non-empty peer messages (k,)."""
        return (self.offwire_send_counts() > 0).sum(axis=1)

    # --------------------------------------------------------- data placement
    def scatter_rows(self, x: np.ndarray, fill: float = 0.0,
                     chips=None) -> np.ndarray:
        """Global (n, f) row data → stacked per-chip (k, B, f) padded blocks.

        ``chips`` restricts the stack to those chip positions (multi-host
        placement builds only the local run, reading only rows those chips
        own)."""
        x = np.asarray(x)
        f = x.shape[1] if x.ndim > 1 else 1
        if chips is None:
            out = np.full((self.k, self.b, f), fill, dtype=x.dtype)
            out[self.owner, self.local_idx] = x.reshape(self.n, f)
            return out
        chips = list(chips)
        out = np.full((len(chips), self.b, f), fill, dtype=x.dtype)
        x2 = x.reshape(self.n, f)
        for i, p in enumerate(chips):
            sel = self.owner == p
            out[i, self.local_idx[sel]] = x2[sel]
        return out

    def gather_rows(self, blocks: np.ndarray) -> np.ndarray:
        """Stacked per-chip (k, B, f) blocks → global (n, f) row data."""
        return np.asarray(blocks)[self.owner, self.local_idx]

    # ------------------------------------------- receptive-set helpers (serve)
    def global_row_ids(self) -> np.ndarray:
        """(k, B) int64: the GLOBAL vertex id living in each (chip, local
        slot) — the inverse of ``(owner, local_idx)``; −1 on padding slots.
        The sub-graph serving path (``serve/subgraph.py``) uses this to
        express each chip's per-row fold recipes in global row space."""
        out = np.full((self.k, self.b), -1, dtype=np.int64)
        out[self.owner, self.local_idx] = np.arange(self.n, dtype=np.int64)
        return out

    def halo_global_rows(self) -> np.ndarray:
        """(k, R) int64: the GLOBAL vertex id each halo rank holds after one
        exchange; −1 on padding ranks.  Halo rank ``j`` of chip ``c`` gathers
        receive-buffer slot ``halo_src[c, j] = q·S + t``, which owner ``q``
        filled from its local row ``send_idx[q, c, t]`` — so the mapping is
        derivable from the plan alone, without running an exchange.  Needs
        the full square plan (a shard-proxy slice has no peers' send
        lists)."""
        si = np.asarray(self.send_idx)
        if si.ndim != 3 or si.shape[0] != si.shape[1]:
            raise ValueError(
                f"halo_global_rows needs the full square plan "
                f"(send_idx {si.shape}); compute it before "
                "shard_proxy_plan slicing")
        glob = self.global_row_ids()
        out = np.full((self.k, self.r), -1, dtype=np.int64)
        for c in range(self.k):
            hs = int(self.halo_counts[c])
            flat = np.asarray(self.halo_src[c, :hs], dtype=np.int64)
            q = flat // self.s
            t = flat % self.s
            out[c, :hs] = glob[q, si[q, c, t]]
        return out


def choose_replica_budget(plan, decision: dict | None = None) -> int:
    """Auto-tune the replica budget B from the plan's λ·degree curve — the
    ``--replica-budget auto`` rule.

    Ranks every boundary row by its replica score λ·edges
    (``replica_scores``, the quantity ``ensure_replicas`` selects on),
    then picks the KNEE of the descending score curve: the prefix length
    at which the normalized cumulative score sits farthest above the
    diagonal (max-gap elbow — deterministic, scale-free, and exactly the
    "few hub rows own most of the exchange" shape of a power-law
    boundary).  A flat curve (every boundary row equally hot) has its max
    gap at ~0 and picks a small B rather than replicating everything.
    Returns the chosen B; ``decision`` (filled in place) records the
    scoring inputs so the pick is reconstructible from the run manifest
    (``comm_schedule.replica_auto`` block)."""
    lam, cons = plan.replica_scores()
    score = (lam.astype(np.float64) * cons).ravel()
    boundary = np.sort(score[lam.ravel() > 0])[::-1]
    log = decision if decision is not None else {}
    m = int(len(boundary))
    log.update(rule="lambda-degree-knee", boundary_rows=m)
    if m == 0 or boundary[0] <= 0:
        log.update(chosen=0, score_covered=0.0)
        return 0
    cum = np.cumsum(boundary)
    gap = cum / cum[-1] - np.arange(1, m + 1) / m
    b = int(np.argmax(gap)) + 1
    log.update(chosen=b, score_total=float(cum[-1]),
               score_covered=float(cum[b - 1] / cum[-1]),
               knee_gap=float(gap[b - 1]))
    return b


def resolve_comm_schedule(schedule: str | None, plans, model: str,
                          halo_staleness: int = 0,
                          fin: int | None = None, widths=None,
                          compute_dtype: str | None = None,
                          replica_budget: int = 0,
                          decision: dict | None = None) -> str:
    """Resolve a ``comm_schedule`` knob to a concrete transport — THE one
    selection rule shared by both trainers (a second copy would drift).

    ``None`` reads ``$SGCN_COMM_SCHEDULE`` (default ``'a2a'``).  ``'auto'``
    is a PREFERENCE: it picks ``'ragged'`` only when every plan supports it
    (symmetric, full square counts or a pre-built ragged layout, k > 1) and
    the cost rule below says so; everything else resolves to ``'a2a'``
    silently.  An explicit ``'ragged'`` is a CONTRACT — callers validate it
    loudly themselves.

    TWO cost rules, because staleness changes what the wire costs:

    * **exact mode** (``halo_staleness=0``): the latency trade — the ring
      issues k−1 collectives where the dense schedule issues one, so ragged
      only pays when the aggregate dense padding efficiency falls below
      ``RAGGED_AUTO_EFFICIENCY``.  (The Pallas VMEM aggregator is
      schedule-agnostic since ``pspmm_pallas_ragged`` — the old "ragged
      forfeits the VMEM kernel" carve-out is gone: kernel choice is made
      per degree bucket AFTER the transport is picked,
      ``ops/pallas_spmm.py::choose_pallas_dispatch``.)
    * **stale mode** (``halo_staleness=1``): the exchange is HIDDEN — no
      same-step consumer, so its latency (the k−1 dispatches included) is
      off the critical path and the padding-efficiency threshold would be
      measuring a cost that is not being paid.  The only remaining cost is
      wire bytes (ICI occupancy/energy, and the sync steps' exposed
      exchange), so ragged wins whenever it ships strictly fewer wire rows
      than the dense pad.  (The stale trainer never selects the Pallas
      aggregator, so no VMEM exception applies.)

    The scored quantity is the wire-byte efficiency of the model's real
    exchange tables in both rules: every exchange of a plan ships the same
    row set at every lane width (GCN's ``exchange_widths`` rows, GAT's
    ``gat_exchange_lane_widths`` tables), so the per-layer lane weights
    multiply true and wire bytes uniformly and the byte ratio REDUCES
    EXACTLY to the row ratio — the lane arithmetic lives in the
    attribution/CommStats byte gauges.  ``compute_dtype`` is accepted for
    signature stability with those byte models; it cannot change the ratio.

    ``replica_budget`` (B > 0, already resolved from ``auto`` by the
    caller): score the wire rows WITH the replica shrink — a
    ``--replica-budget`` run ships the shrunken ``nrep_*`` exchange on
    every non-refresh step, so comparing the transports on the FULL pads
    would score a wire the run never pays.  Builds the ragged + replica
    layouts on each plan as a side effect (both are lazy and idempotent;
    ``resolve_forward_setup`` would build them right after anyway).

    ``decision`` (optional dict, filled in place): the selection's inputs
    and the rule that fired — the trainers stash it and ``attach_recorder``
    logs it into the run manifest (``comm_schedule`` block), so an ``auto``
    pick is reconstructible from the run directory alone.
    """
    import os
    del compute_dtype       # lane weights cancel in the ratio (see above)
    log = decision if decision is not None else {}
    asked = schedule
    if schedule is None:
        schedule = os.environ.get("SGCN_COMM_SCHEDULE", "a2a")
        asked = f"${{SGCN_COMM_SCHEDULE}}={schedule}"
    if schedule not in ("a2a", "ragged", "auto"):
        raise ValueError(
            f"comm_schedule must be 'a2a', 'ragged' or 'auto', got "
            f"{schedule!r}")
    log.update(asked=asked, model=model, halo_staleness=int(halo_staleness),
               replica_budget=int(replica_budget))

    def resolved(value: str, rule: str) -> str:
        log.update(resolved=value, rule=rule)
        return value

    if schedule != "auto":
        return resolved(schedule, "explicit")
    if model not in ("gcn", "gat"):
        return resolved("a2a", "model has no ragged transport")
    true = wire = wire_ragged = 0
    for p in plans:
        sc = np.asarray(p.send_counts)
        ragged_ready = (p.rr_sizes is not None
                        or (sc.ndim == 2 and sc.shape[0] == sc.shape[1]))
        if not (p.symmetric and ragged_ready and sc.shape[1] > 1):
            return resolved("a2a", "plan does not support the ragged ring "
                                   "(asymmetric, sliced, or k == 1)")
        if replica_budget:
            # replica-aware scoring: the steady-state step ships the
            # SHRUNKEN exchange, so the transports are compared at the
            # shrunken pads (the full figures are logged alongside)
            p.ensure_ragged()
            p.ensure_replicas(replica_budget)
        true += int(sc.sum())
        wire += p.wire_rows_per_exchange("a2a")
        wire_ragged += p.wire_rows_per_exchange("ragged")
    log.update(true_rows=true, wire_rows_a2a=wire,
               wire_rows_ragged=wire_ragged)
    if replica_budget:
        true = sum(int(np.asarray(p.nrep_send_counts).sum()) for p in plans)
        wire = sum(p.wire_rows_per_exchange("a2a", replica=True)
                   for p in plans)
        wire_ragged = sum(p.wire_rows_per_exchange("ragged", replica=True)
                          for p in plans)
        log.update(replica_rows=sum(int(p.replica_rows) for p in plans),
                   true_rows_replica=true,
                   wire_rows_a2a_replica=wire,
                   wire_rows_ragged_replica=wire_ragged)
    log.update(padding_efficiency=(true / wire if wire else 1.0),
               threshold=RAGGED_AUTO_EFFICIENCY)
    if halo_staleness:
        # hidden exchange: bytes-only rule (see docstring)
        if wire_ragged < wire:
            return resolved("ragged", "hidden-exchange wire-byte rule: "
                                      "ragged ships fewer wire rows")
        return resolved("a2a", "hidden-exchange wire-byte rule: ragged "
                               "ships no fewer wire rows")
    if not wire or true / wire >= RAGGED_AUTO_EFFICIENCY:
        return resolved("a2a", "padding efficiency at/above threshold")
    # no Pallas exception: the VMEM aggregator rides BOTH transports since
    # pspmm_pallas_ragged (schedule-agnostic kernel family; per-bucket
    # kernel choice happens after transport selection)
    return resolved("ragged", "padding efficiency below threshold")


def _relabel(n: int, partvec: np.ndarray, k: int, pad_rows_to: int,
             order_key: np.ndarray | None = None):
    """Shared vertex relabeling: (owner, local_idx, part_sizes, b, row_valid).

    Chip ``p`` owns local slots 0..B-1.  Within a part, vertices are ranked
    by global id (``order_key=None``) or descending by ``order_key`` with
    global id as the tie-break — the degree ordering that makes the bucketed
    ELL layout tight.  Single source of truth for both plan builders below.
    """
    owner = np.asarray(partvec, dtype=np.int64)
    if owner.shape[0] != n:
        raise ValueError(f"partvec length {owner.shape[0]} != n {n}")
    if n and (owner.min() < 0 or owner.max() >= k):
        raise ValueError("partvec entries out of range")
    part_sizes = np.bincount(owner, minlength=k)
    b = int(part_sizes.max()) if n else 1
    b = max(1, -(-b // pad_rows_to) * pad_rows_to)
    if order_key is None:
        order = np.lexsort((np.arange(n), owner))
    else:
        order = np.lexsort((np.arange(n), -np.asarray(order_key), owner))
    local_idx = np.empty(n, dtype=np.int64)
    starts = np.zeros(k + 1, dtype=np.int64)
    np.cumsum(part_sizes, out=starts[1:])
    local_idx[order] = np.arange(n) - starts[owner[order]]
    row_valid = np.zeros((k, b), dtype=np.float32)
    for p in range(k):
        row_valid[p, : part_sizes[p]] = 1.0
    return owner, local_idx, part_sizes, b, row_valid


def _split_edges(edge_dst, edge_src, edge_w, nnz, b,
                 el: int | None = None, eh: int | None = None,
                 halo_fold_key=None):
    """Split padded (k, E) edge lists into local-src and halo-src lists.

    Local edges (``src < b``) keep their src; halo edges re-base src to the
    halo block (``src - b``).  Filtering preserves the sorted-by-dst
    invariant.  ``el`` / ``eh`` force a larger padded width (shared
    compilation envelopes); padding edges carry dst ``b-1`` and weight 0.

    ``halo_fold_key`` (optional, (k, R) int): per-chip fold position of each
    halo rank — the ragged ring's arrival round ``(chip − owner) mod k``.
    When given, each chip's halo edges are re-sorted by (dst, fold, rank) so
    the dense halo-src segment-sum applies per-row updates in the SAME
    sequence as the ragged schedule's round-order fold — the f32 bit-parity
    contract between the two exchange schedules (``CommPlan.ensure_ragged``).
    Within a (dst, round) run the rank order equals the receive-buffer
    order, so each round's subsequence stays (dst, pos)-sorted too.
    """
    k = edge_dst.shape[0]
    parts = []
    for p in range(k):
        cnt = int(nnz[p])
        d, s0, w = edge_dst[p, :cnt], edge_src[p, :cnt], edge_w[p, :cnt]
        lm = s0 < b
        hd, hs, hw = d[~lm], s0[~lm] - b, w[~lm]
        if halo_fold_key is not None and len(hd):
            fk = halo_fold_key[p]
            o = np.lexsort((hs, fk[hs], hd))
            hd, hs, hw = hd[o], hs[o], hw[o]
        parts.append((d[lm], s0[lm], w[lm], hd, hs, hw))
    lnnz = np.array([len(t[0]) for t in parts], dtype=np.int64)
    hnnz = np.array([len(t[3]) for t in parts], dtype=np.int64)
    el_nat = max(1, int(lnnz.max()) if k else 1)
    eh_nat = max(1, int(hnnz.max()) if k else 1)
    el = el_nat if el is None else el
    eh = eh_nat if eh is None else eh
    if el < el_nat or eh < eh_nat:
        raise ValueError("split envelope smaller than natural edge counts")
    ld = np.full((k, el), b - 1, dtype=np.int32)
    ls = np.zeros((k, el), dtype=np.int32)
    lw = np.zeros((k, el), dtype=np.float32)
    hd = np.full((k, eh), b - 1, dtype=np.int32)
    hs = np.zeros((k, eh), dtype=np.int32)
    hw = np.zeros((k, eh), dtype=np.float32)
    for p, (d1, s1, w1, d2, s2, w2) in enumerate(parts):
        ld[p, : len(d1)] = d1
        ls[p, : len(s1)] = s1
        lw[p, : len(w1)] = w1
        hd[p, : len(d2)] = d2
        hs[p, : len(s2)] = s2
        hw[p, : len(w2)] = w2
    return dict(el=el, eh=eh, ledge_dst=ld, ledge_src=ls, ledge_w=lw,
                hedge_dst=hd, hedge_src=hs, hedge_w=hw, lnnz=lnnz, hnnz=hnnz)


def ell_degree_profile(ledge_dst, lnnz, b) -> np.ndarray:
    """Pointwise max over chips of the per-row local in-degree, (b,)."""
    k = ledge_dst.shape[0]
    prof = np.zeros(b, dtype=np.int64)
    for p in range(k):
        np.maximum(prof,
                   np.bincount(ledge_dst[p, : int(lnnz[p])], minlength=b),
                   out=prof)
    return prof


def _choose_buckets(profile: np.ndarray, max_buckets: int = 6,
                    width_cap: int = 64) -> tuple:
    """Optimal ≤``max_buckets`` contiguous row buckets for a DESCENDING
    degree profile, minimizing total padded slots Σ nb·wb (wb = max degree
    in the bucket = degree at its first row).  DP over degree-change points,
    subsampled to 64 candidates on graphs with many distinct degrees.

    ``width_cap`` bounds every bucket width: the SpMM unrolls one fused
    gather per width slot, so program size scales with Σ wb — a power-law
    hub (ogbn-arxiv hubs reach ~13k in-degree) must NOT set the width.
    Rows beyond the cap spill their overflow edges to the COO tail
    (scatter-add; hubs are few, so the tail stays small)."""
    b = len(profile)
    d = np.minimum(np.maximum(np.asarray(profile, dtype=np.int64), 0),
                   width_cap)
    cuts = [0] + [i for i in range(1, b) if d[i] != d[i - 1]] + [b]
    if len(cuts) > 65:
        keep = np.unique(np.linspace(0, len(cuts) - 1, 65).astype(int))
        cuts = [cuts[i] for i in keep]
    m = len(cuts)
    # bucket width = MAX degree inside the segment (profiles are descending
    # for the local-degree relabel key, but only near-descending for e.g.
    # the combined local+halo degree — take the true segment max, not d[start])
    segmax = [[0] * m for _ in range(m)]
    for i in range(m - 1):
        run = 0
        for j in range(i + 1, m):
            run = max(run, int(d[cuts[j - 1]: cuts[j]].max()))
            segmax[i][j] = run
    inf = float("inf")
    best = [[inf] * (max_buckets + 1) for _ in range(m)]
    back = [[0] * (max_buckets + 1) for _ in range(m)]
    best[0][0] = 0.0
    for j in range(1, m):
        for q in range(1, max_buckets + 1):
            for i in range(j):
                if best[i][q - 1] == inf:
                    continue
                w = max(segmax[i][j], 1)
                c = best[i][q - 1] + (cuts[j] - cuts[i]) * w
                if c < best[j][q]:
                    best[j][q] = c
                    back[j][q] = i
    q = min(range(1, max_buckets + 1), key=lambda t: best[m - 1][t])
    segs = []
    j = m - 1
    while j > 0:
        i = back[j][q]
        segs.append((cuts[j] - cuts[i], max(segmax[i][j], 1)))
        j, q = i, q - 1
    return tuple(reversed(segs))


def _single_bucket_width(alldeg: np.ndarray, tail_frac: float) -> int:
    """Classic ELL width choice: smallest multiple of 4 whose overflow tail
    holds at most ``tail_frac`` of the edges (capped at the max degree)."""
    maxdeg = int(alldeg.max()) if alldeg.size else 0
    total = max(1, int(alldeg.sum()))
    ell_k = 4
    while ell_k < maxdeg:
        if int(np.maximum(alldeg - ell_k, 0).sum()) <= tail_frac * total:
            break
        ell_k += 4
    return min(ell_k, max(maxdeg, 1))


def _build_ell(ledge_dst, ledge_src, ledge_w, lnnz, b,
               row_order: str = "degree",
               buckets: tuple | None = None, tl: int | None = None,
               tail_frac: float = 0.02, max_buckets: int = 6):
    """Bucketed-ELL layout of the local-src edge lists (see CommPlan).

    ``row_order='degree'`` (rows pre-sorted descending by local degree):
    bucket structure from ``_choose_buckets`` — or ``buckets`` forced, for
    mini-batch plans sharing one compiled envelope — with width-capped
    buckets; only hub rows past the cap spill edges to the COO tail.
    ``row_order='id'``: one bucket of the classic tail-bounded width plus
    the COO overflow tail (emit-compatible row numbering).
    """
    k = ledge_dst.shape[0]
    degs = [np.bincount(ledge_dst[p, : int(lnnz[p])], minlength=b)
            for p in range(k)]
    if buckets is None:
        if row_order == "degree":
            prof = np.zeros(b, dtype=np.int64)
            for dg in degs:
                np.maximum(prof, dg, out=prof)
            buckets = _choose_buckets(prof, max_buckets=max_buckets)
        else:
            alldeg = (np.concatenate(degs) if k else np.zeros(1, np.int64))
            buckets = ((b, _single_bucket_width(alldeg, tail_frac)),)
    if sum(nb for nb, _ in buckets) != b:
        raise ValueError(f"buckets {buckets} do not cover {b} rows")
    et = sum(nb * wb for nb, wb in buckets)
    # WIDTH-MAJOR flat layout: bucket at base `off` stores slot t of row r
    # (local rank r-r0 in the bucket) at off + t·nb + (r-r0), so the SpMM's
    # per-slot gathers read contiguous (nb,) index runs — one fused
    # gather·w + add per slot, no (nb, wb, f) intermediate to relayout
    # (the row-major form cost ~17 ms/epoch of data formatting in the
    # round-3 trace at ogbn-arxiv scale).
    row_base = np.empty(b, dtype=np.int64)   # off + (r - r0), stride nb
    row_stride = np.empty(b, dtype=np.int64)
    row_cap = np.empty(b, dtype=np.int64)
    off = r0 = 0
    for nb, wb in buckets:
        row_base[r0: r0 + nb] = off + np.arange(nb, dtype=np.int64)
        row_stride[r0: r0 + nb] = nb
        row_cap[r0: r0 + nb] = wb
        off += nb * wb
        r0 += nb
    ell_idx = np.zeros((k, et), dtype=np.int32)
    ell_wv = np.zeros((k, et), dtype=np.float32)
    tails = []
    for p in range(k):
        cnt = int(lnnz[p])
        d = ledge_dst[p, :cnt].astype(np.int64)
        s0 = ledge_src[p, :cnt]
        w = ledge_w[p, :cnt]
        # position of each edge within its (sorted) dst run
        starts = np.zeros(b + 1, dtype=np.int64)
        np.cumsum(degs[p], out=starts[1:])
        pos = np.arange(cnt) - starts[d]
        # beyond-width edges (hub rows past the width cap, or a forced
        # envelope narrower than a row) spill to the COO tail
        main = pos < row_cap[d]
        slots = row_base[d[main]] + pos[main] * row_stride[d[main]]
        ell_idx[p][slots] = s0[main]
        ell_wv[p][slots] = w[main]
        tails.append((d[~main].astype(np.int32), s0[~main], w[~main]))
    ltail_nnz = np.array([len(t[0]) for t in tails], dtype=np.int64)
    tl_nat = max(1, int(ltail_nnz.max()) if k else 1)
    tl = tl_nat if tl is None else tl
    if tl < tl_nat:
        raise ValueError("tail envelope smaller than natural tail size")
    ltail_dst = np.full((k, tl), b - 1, dtype=np.int32)
    ltail_src = np.zeros((k, tl), dtype=np.int32)
    ltail_w = np.zeros((k, tl), dtype=np.float32)
    for p, (d, s0, w) in enumerate(tails):
        ltail_dst[p, : len(d)] = d
        ltail_src[p, : len(s0)] = s0
        ltail_w[p, : len(w)] = w
    return dict(ell_k=max(wb for _, wb in buckets), tl=tl,
                ell_buckets=buckets, ell_idx=ell_idx, ell_w=ell_wv,
                ltail_dst=ltail_dst, ltail_src=ltail_src, ltail_w=ltail_w,
                ltail_nnz=ltail_nnz)


def shared_ell_buckets(plans: list, b: int, combined: bool = False) -> tuple:
    """Bucket structure covering every plan's degree profile — the shared
    compiled-envelope companion to ``pad_comm_plan`` for mini-batch plans
    (all padded to ``b`` rows).  ``combined=True`` covers the combined
    local+halo edge lists (the GAT layout) instead of the local-src ones."""
    prof = np.zeros(b, dtype=np.int64)
    for pl in plans:
        q = (ell_degree_profile(pl.edge_dst, pl.nnz, pl.b) if combined
             else ell_degree_profile(pl.ledge_dst, pl.lnnz, pl.b))
        np.maximum(prof[: pl.b], q, out=prof[: pl.b])
    if all(pl.row_order == "degree" for pl in plans):
        return _choose_buckets(prof)
    # id-ordered rows: one classic tail-bounded width shared by all.
    # Derive each plan's natural combined width from its degree counts
    # directly — materializing the full cell layout just to read the width
    # would double the O(nnz) build the caller is about to redo anyway.
    if combined:
        widths = []
        for pl in plans:
            alldeg = np.concatenate(
                [np.bincount(pl.edge_dst[p, : int(pl.nnz[p])], minlength=pl.b)
                 for p in range(pl.k)])
            widths.append(_single_bucket_width(alldeg, tail_frac=0.02))
        return ((b, max(widths)),)
    return ((b, max(pl.ell_k for pl in plans)),)


def _cell_fields(ell: dict) -> dict:
    """Rename a ``_build_ell`` result into the combined-edge field names."""
    return dict(ctl=ell["tl"], cell_buckets=ell["ell_buckets"],
                cell_idx=ell["ell_idx"], cell_w=ell["ell_w"],
                ctail_dst=ell["ltail_dst"], ctail_src=ell["ltail_src"],
                ctail_w=ell["ltail_w"], ctail_nnz=ell["ltail_nnz"])


def _check_symmetric(a: sp.spmatrix) -> bool:
    a = sp.csr_matrix(a)
    a.eliminate_zeros()
    a.sort_indices()
    at = sp.csr_matrix(a.T)
    at.eliminate_zeros()
    at.sort_indices()
    # misclassifying an asymmetric matrix as symmetric would silently flip
    # gradients to Â·g, so the sparsity pattern must match EXACTLY; the
    # tolerance applies to stored values only (normalization round-off)
    if not (np.array_equal(a.indptr, at.indptr)
            and np.array_equal(a.indices, at.indices)):
        return False
    if a.nnz == 0:
        return True
    scale = max(float(np.abs(a.data).max()), 1e-30)
    return float(np.abs(a.data - at.data).max()) <= 1e-6 * scale


def relabel_plan(a: sp.spmatrix, partvec: np.ndarray, k: int,
                 pad_rows_to: int = 1) -> CommPlan:
    """Vertex relabeling + padding fields only — no halo/send construction.

    For algorithms with no boundary exchange (the broadcast-1D baseline ships
    everything every layer), building the full halo plan is dead work; this
    fills owner/local_idx/part_sizes/b/e/nnz/row_valid and leaves the comm
    fields trivial.
    """
    a = sp.coo_matrix(a)
    n = a.shape[0]
    owner, local_idx, part_sizes, b, row_valid = _relabel(
        n, partvec, k, pad_rows_to)
    nnz = np.bincount(owner[a.row], minlength=k)
    e = max(1, int(nnz.max()) if len(nnz) else 1)
    z = np.zeros
    return CommPlan(
        n=n, k=k, b=b, s=1, r=1, e=e,
        owner=owner, local_idx=local_idx,
        part_sizes=part_sizes.astype(np.int64),
        send_idx=z((k, k, 1), np.int32), send_counts=z((k, k), np.int32),
        halo_src=z((k, 1), np.int32), halo_counts=z(k, np.int32),
        edge_dst=z((k, e), np.int32), edge_src=z((k, e), np.int32),
        edge_w=z((k, e), np.float32), nnz=nnz.astype(np.int64),
        row_valid=row_valid,
        el=1, eh=1,
        ledge_dst=z((k, 1), np.int32), ledge_src=z((k, 1), np.int32),
        ledge_w=z((k, 1), np.float32),
        hedge_dst=z((k, 1), np.int32), hedge_src=z((k, 1), np.int32),
        hedge_w=z((k, 1), np.float32),
        lnnz=z(k, np.int64), hnnz=z(k, np.int64),
        ell_k=1, tl=1, ell_buckets=((b, 1),),
        ell_idx=z((k, b), np.int32), ell_w=z((k, b), np.float32),
        ltail_dst=z((k, 1), np.int32), ltail_src=z((k, 1), np.int32),
        ltail_w=z((k, 1), np.float32), ltail_nnz=z(k, np.int64),
        ctl=1, cell_buckets=((b, 1),),
        cell_idx=z((k, b), np.int32), cell_w=z((k, b), np.float32),
        ctail_dst=z((k, 1), np.int32), ctail_src=z((k, 1), np.int32),
        ctail_w=z((k, 1), np.float32), ctail_nnz=z(k, np.int64),
        symmetric=_check_symmetric(a), row_order="id",
    )


def pad_comm_plan(plan: CommPlan, b: int, s: int, r: int, e: int,
                  el: int | None = None, eh: int | None = None,
                  tl: int | None = None, ctl: int | None = None,
                  ell_buckets: tuple | None = None,
                  cell_buckets: tuple | None = None) -> CommPlan:
    """Re-pad a plan to a larger (B, S, R, E) envelope.

    Lets many plans (one per mini-batch) share ONE compiled train step: the
    reference pre-samples all batches and builds per-batch comm maps up front
    (``GPU/PGCN-Mini-batch.py:220-230``); under XLA the analogous move is
    padding every batch plan to the max envelope so shapes are static
    (SURVEY.md §7.3).  Padding preserves the plan invariants: pad edges carry
    weight 0 and dst ``b-1`` (keeps ``edge_dst`` non-decreasing), pad send /
    halo slots index row 0 and are never read by valid gathers.  For the
    shared ELL layout pass ``ell_buckets`` covering every plan's degree
    profile (see ``ell_degree_profile`` / ``_choose_buckets``).
    """
    el = plan.el if el is None else el
    eh = plan.eh if eh is None else eh
    tl = plan.tl if tl is None else tl
    if ctl is None:
        ctl = plan.ctl
    if (b, s, r, e, el, eh, tl) == (
            plan.b, plan.s, plan.r, plan.e, plan.el, plan.eh, plan.tl) \
            and ctl == plan.ctl \
            and ell_buckets in (None, plan.ell_buckets) \
            and cell_buckets in (None, plan.cell_buckets):
        return plan
    if (b < plan.b or s < plan.s or r < plan.r or e < plan.e
            or el < plan.el or eh < plan.eh or tl < plan.tl
            or (ctl is not None and plan.ctl is not None and ctl < plan.ctl)):
        raise ValueError("pad_comm_plan cannot shrink an envelope")
    k = plan.k

    send_idx = np.zeros((k, k, s), dtype=np.int32)
    send_idx[:, :, : plan.s] = plan.send_idx
    halo_src = np.zeros((k, r), dtype=np.int32)
    # remap old flat recv slots q*S_old + t -> q*S_new + t
    q_old, t_old = plan.halo_src // plan.s, plan.halo_src % plan.s
    halo_src[:, : plan.r] = (q_old * s + t_old).astype(np.int32)
    edge_dst = np.full((k, e), b - 1, dtype=np.int32)
    edge_dst[:, : plan.e] = plan.edge_dst
    # old pad edges pointed at plan.b-1; retarget them to b-1 to keep the
    # non-decreasing invariant (weight 0 either way)
    for p in range(k):
        edge_dst[p, plan.nnz[p]: plan.e] = b - 1
    edge_src = np.zeros((k, e), dtype=np.int32)
    # halo table shifts from plan.b to b: remap src indices >= plan.b
    old_src = plan.edge_src
    edge_src[:, : plan.e] = np.where(
        old_src >= plan.b, old_src - plan.b + b, old_src)
    edge_w = np.zeros((k, e), dtype=np.float32)
    edge_w[:, : plan.e] = plan.edge_w
    row_valid = np.zeros((k, b), dtype=np.float32)
    row_valid[:, : plan.b] = plan.row_valid

    chips = (np.asarray(plan.chip_ids) if plan.chip_ids is not None
             else np.arange(k))
    peers = plan.send_counts.shape[1]
    split = _split_edges(edge_dst, edge_src, edge_w, plan.nnz, b, el=el, eh=eh,
                         halo_fold_key=(chips[:, None] - halo_src // s) % peers)
    ell = _build_ell(split["ledge_dst"], split["ledge_src"], split["ledge_w"],
                     split["lnnz"], b, row_order=plan.row_order,
                     buckets=ell_buckets, tl=tl)
    padded = CommPlan(
        n=plan.n, k=k, b=b, s=s, r=r, e=e,
        owner=plan.owner, local_idx=plan.local_idx, part_sizes=plan.part_sizes,
        send_idx=send_idx, send_counts=plan.send_counts.copy(),
        halo_src=halo_src, halo_counts=plan.halo_counts.copy(),
        edge_dst=edge_dst, edge_src=edge_src, edge_w=edge_w,
        nnz=plan.nnz.copy(), row_valid=row_valid,
        symmetric=plan.symmetric, row_order=plan.row_order,
        **split, **ell,
    )
    if cell_buckets is not None or plan.cell_buckets is not None:
        padded.ensure_cell(buckets=cell_buckets, ctl=ctl)
    return padded


def build_comm_plan(
    a: sp.spmatrix,
    partvec: np.ndarray,
    k: int,
    pad_rows_to: int = 1,
    pad_send_to: int = 1,
    row_order: str = "degree",
) -> CommPlan:
    """Compute the static plan from adjacency + part vector.

    ``pad_rows_to`` / ``pad_send_to`` round B and S up to a multiple (e.g. 8
    for TPU sublane alignment). The recv side of the reference's map predicate
    (nonzero with local row, remote col → receive that col's row;
    ``GPU/PGCN.py:37-51``) defines the halo; the send side is its transpose.

    ``row_order='degree'`` (default) relabels each part's rows descending by
    local in-degree so the bucketed ELL layout is tight; any consistent
    order is correct (all row data routes through owner/local_idx), so this
    is purely a layout choice.  ``row_order='id'`` ranks by global id —
    required by the ``.r``-file emitter whose text formats assume it.
    """
    a = sp.coo_matrix(a)
    n = a.shape[0]
    if row_order not in ("degree", "id"):
        raise ValueError(f"unknown row_order {row_order!r}")
    key = None
    if row_order == "degree":
        ow = np.asarray(partvec, dtype=np.int64)
        local_edge = ow[a.row] == ow[a.col]
        key = np.bincount(a.row[local_edge], minlength=n)
    owner, local_idx, part_sizes, b, row_valid = _relabel(
        n, partvec, k, pad_rows_to, order_key=key)

    src_g, dst_g, w_g = a.col, a.row, a.data.astype(np.float32)
    eo = owner[dst_g]                                   # chip owning each edge (by row)

    # per-chip halo vertex lists, sorted by (owner, id)
    halo_lists: list[np.ndarray] = []
    for p in range(k):
        em = eo == p
        cols = src_g[em]
        remote = cols[owner[cols] != p]
        uniq = np.unique(remote)
        uniq = uniq[np.lexsort((uniq, owner[uniq]))]
        halo_lists.append(uniq)
    halo_counts = np.array([len(h) for h in halo_lists], dtype=np.int32)
    r = max(1, int(halo_counts.max()) if k else 1)

    # send lists per ordered pair (p → q): vertices owned by p in q's halo
    send_lists: dict[tuple[int, int], np.ndarray] = {}
    s = 1
    for q in range(k):
        hq = halo_lists[q]
        ho = owner[hq]
        for p in range(k):
            if p == q:
                continue
            vs = hq[ho == p]                           # already sorted by id
            if len(vs):
                send_lists[(p, q)] = vs
                s = max(s, len(vs))
    s = max(1, -(-s // pad_send_to) * pad_send_to)

    send_idx = np.zeros((k, k, s), dtype=np.int32)
    send_counts = np.zeros((k, k), dtype=np.int32)
    for (p, q), vs in send_lists.items():
        send_idx[p, q, : len(vs)] = local_idx[vs]
        send_counts[p, q] = len(vs)

    # halo gather: chip p's halo row t' (owner q, position t in p's per-owner
    # sublist == position in q→p send list) reads recv-flat slot q*S + t
    halo_src = np.zeros((k, r), dtype=np.int32)
    for p in range(k):
        hp = halo_lists[p]
        if not len(hp):
            continue
        ho = owner[hp]
        pos = np.zeros(len(hp), dtype=np.int64)
        for q in np.unique(ho):
            m = ho == q
            pos[m] = q * s + np.arange(m.sum())
        halo_src[p, : len(hp)] = pos

    # per-chip padded edge lists
    nnz = np.bincount(eo, minlength=k)
    e = max(1, int(nnz.max()) if len(nnz) else 1)
    # pad dst with the last row (b-1) so each chip's edge_dst stays globally
    # non-decreasing — segment_sum is told indices_are_sorted=True
    edge_dst = np.full((k, e), b - 1, dtype=np.int32)
    edge_src = np.zeros((k, e), dtype=np.int32)
    edge_w = np.zeros((k, e), dtype=np.float32)
    for p in range(k):
        em = eo == p
        rows = local_idx[dst_g[em]].astype(np.int32)
        cols = src_g[em]
        vals = w_g[em]
        co = owner[cols]
        csrc = np.empty(len(cols), dtype=np.int32)
        lm = co == p
        csrc[lm] = local_idx[cols[lm]].astype(np.int32)
        if (~lm).any():
            # halo position via searchsorted on the (owner, id)-sorted halo list
            hp = halo_lists[p]
            keys = owner[hp] * (n + 1) + hp
            qkeys = co[~lm] * (n + 1) + cols[~lm]
            csrc[~lm] = b + np.searchsorted(keys, qkeys).astype(np.int32)
        srt = np.argsort(rows, kind="stable")          # sorted dst → fast segsum
        cnt = em.sum()
        edge_dst[p, :cnt] = rows[srt]
        edge_src[p, :cnt] = csrc[srt]
        edge_w[p, :cnt] = vals[srt]

    split = _split_edges(edge_dst, edge_src, edge_w, nnz, b,
                         halo_fold_key=(np.arange(k)[:, None]
                                        - halo_src // s) % k)
    ell = _build_ell(split["ledge_dst"], split["ledge_src"], split["ledge_w"],
                     split["lnnz"], b, row_order=row_order)
    return CommPlan(
        n=n, k=k, b=b, s=s, r=r, e=e,
        owner=owner, local_idx=local_idx, part_sizes=part_sizes.astype(np.int64),
        send_idx=send_idx, send_counts=send_counts,
        halo_src=halo_src, halo_counts=halo_counts,
        edge_dst=edge_dst, edge_src=edge_src, edge_w=edge_w,
        nnz=nnz.astype(np.int64), row_valid=row_valid,
        symmetric=_check_symmetric(a), row_order=row_order,
        **split, **ell,
    )
