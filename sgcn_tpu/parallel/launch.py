"""Multi-host launch: process bootstrap + global mesh over ICI/DCN.

Reference equivalents: the SLURM rendezvous plumbing — ``MASTER_ADDR`` /
``MASTER_PORT`` derived from the job id and nodelist, ``WORLD_SIZE`` =
nodes × tasks (``GPU/pytorch.3node.slurm:46-56``), consumed by
``dist.init_process_group`` via ``SLURM_NPROCS``/``SLURM_PROCID``
(``GPU/PGCN.py:241-260``).

TPU-native shape: one Python process per host, ``jax.distributed.initialize``
for the rendezvous (it auto-detects on Cloud TPU pods; SLURM env vars are the
fallback), and a single global 1D vertex mesh over ALL chips of all hosts.
Collectives between co-located chips ride ICI; cross-host hops ride DCN —
the same topology split as the reference's NCCL intra/inter-node rings, but
chosen by XLA's collective scheduler rather than hand-written P2P.

Every sgcn_tpu trainer takes an explicit ``mesh``; launching multi-host is
therefore just::

    ctx = init_distributed()                  # once per process, before use
    mesh = global_mesh_1d()                   # k = total chips in the job
    trainer = FullBatchTrainer(plan, fin, widths, mesh=mesh)
    data = make_train_data_multihost(plan, mesh, features, labels)

``make_train_data_multihost`` builds blocks only for this process's chips
and assembles global arrays via ``jax.make_array_from_process_local_data``
— the supported multi-process placement path (a plain ``device_put`` of
host-local arrays to a global sharding is NOT, and the plan-array /
parameter placement in ``parallel.mesh`` takes the same route when
``jax.process_count() > 1``).  Exercised end-to-end by the 2-process × 4
virtual-device integration test (``tests/test_multihost.py``).  See
``launch/tpu.slurm`` for the batch-script equivalent of the reference's
``pytorch.3node.slurm``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass

import jax

from .mesh import AXIS, make_mesh_1d

# rendezvous robustness (docs/resilience.md): how long ONE initialize
# attempt may wait for all peers before it is declared stalled, and the
# backoff before the single retry.  A transiently late peer (a host still
# booting, a container being rescheduled) is routine on preemptible pods —
# one retry absorbs it; a peer that misses BOTH attempts is genuinely gone
# and the clear error beats an unbounded hang.
RENDEZVOUS_TIMEOUT_S = 300.0
RENDEZVOUS_BACKOFF_S = 5.0


def _initialize_with_retry(heartbeat, detail: str, **kwargs) -> None:
    """``jax.distributed.initialize`` under an explicit stalled-peer
    timeout with ONE retry + backoff.  Heartbeats mark every transition
    (start/stalled/retry/done/failed), so an operator watching the run
    directory sees WHICH attempt is in flight — the stalled-vs-slow signal
    the dryrun classifier reads."""
    import inspect

    timeout = float(os.environ.get("SGCN_RENDEZVOUS_TIMEOUT",
                                   str(RENDEZVOUS_TIMEOUT_S)))
    backoff = float(os.environ.get("SGCN_RENDEZVOUS_BACKOFF",
                                   str(RENDEZVOUS_BACKOFF_S)))
    try:
        params = inspect.signature(jax.distributed.initialize).parameters
        if "initialization_timeout" in params:
            kwargs["initialization_timeout"] = int(timeout)
    except (TypeError, ValueError):
        pass                    # older jax: no per-attempt timeout knob
    for attempt in (1, 2):
        heartbeat("rendezvous:start", phase="init_distributed",
                  detail=f"attempt {attempt}/2, {detail}, "
                         f"timeout {timeout:.0f}s")
        try:
            jax.distributed.initialize(**kwargs)
            heartbeat("rendezvous:done", phase="init_distributed",
                      detail=f"attempt {attempt}/2")
            return
        except Exception as e:           # noqa: BLE001 — classified below
            # classify before diagnosing: only a timeout-shaped failure is
            # evidence of a STALLED peer — blaming a dead peer for a bad
            # coordinator address / bound port / auth error sends the
            # operator hunting in exactly the wrong place
            text = str(e).lower()
            stall_like = any(t in text for t in
                             ("timed out", "timeout", "deadline",
                              "unavailable"))
            if attempt == 2:
                heartbeat("rendezvous:failed", phase="init_distributed",
                          detail=str(e)[-200:])
                cause = (
                    f"a peer stalled past the {timeout:.0f}s timeout on "
                    "both attempts, or the coordinator is unreachable — "
                    "check that every host in the job is up and can reach "
                    f"{kwargs.get('coordinator_address') or 'the pod'} "
                    "($SGCN_RENDEZVOUS_TIMEOUT / _BACKOFF tune the "
                    "attempt budget)" if stall_like else
                    "NOT a timeout — likely local configuration (bad "
                    "coordinator address, port already bound, auth)")
                raise RuntimeError(
                    f"rendezvous failed twice ({detail}): {cause}; "
                    f"underlying error: {e}") from e
            heartbeat("rendezvous:stalled" if stall_like
                      else "rendezvous:error",
                      phase="init_distributed",
                      detail=f"attempt 1 failed ({str(e)[-120:]}); "
                             f"retrying in {backoff:.0f}s")
            # a timed-out initialize leaves the distributed client SET
            # (jax assigns global_state.client before connect()), and a
            # second initialize then refuses with "should only be called
            # once" — shut the half-initialized state down or the retry
            # can never actually re-attempt the rendezvous
            try:
                jax.distributed.shutdown()
            except Exception:           # noqa: BLE001 — nothing to shut down
                pass
            time.sleep(backoff)


@dataclass
class DistributedContext:
    process_id: int
    num_processes: int
    coordinator: str | None
    local_devices: int
    global_devices: int

    @property
    def is_coordinator(self) -> bool:
        """Rank-0 check — all end-of-run printing is rank-0-only in the
        reference (``GPU/PGCN.py:230-238``)."""
        return self.process_id == 0


def slurm_rendezvous_env() -> tuple[str, int, int] | None:
    """Derive (coordinator, num_processes, process_id) from SLURM variables,
    mirroring the reference's launcher arithmetic
    (``GPU/pytorch.3node.slurm:46-56``: port = 10000 + last 4 digits of the
    job id; master = first node of the nodelist — here the caller passes the
    resolved hostname via ``SGCN_COORDINATOR`` or ``MASTER_ADDR``)."""
    nprocs = os.environ.get("SLURM_NPROCS")
    procid = os.environ.get("SLURM_PROCID")
    if nprocs is None or procid is None:
        return None
    addr = (os.environ.get("SGCN_COORDINATOR")
            or os.environ.get("MASTER_ADDR"))
    if addr is None:
        return None
    port = os.environ.get("MASTER_PORT")
    if port is None:
        # array/het job ids like "1234_5" contain non-digits; keep the
        # digits so the port stays derivable instead of crashing startup
        jobid = "".join(c for c in os.environ.get("SLURM_JOBID", "0")
                        if c.isdigit())
        port = str(10000 + int(jobid[-4:] or "0"))
    return f"{addr}:{port}", int(nprocs), int(procid)


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> DistributedContext:
    """Bootstrap multi-process JAX.  Single-process (the common dev case and
    the one-chip bench) is a no-op that still returns a valid context.

    Resolution order: explicit args → Cloud TPU autodetection (no env needed)
    → SLURM env (reference-style cluster).
    """
    from ..obs.recorder import heartbeat   # no-op unless SGCN_METRICS_OUT

    if num_processes is None:
        env = slurm_rendezvous_env()
        if env is not None:
            coordinator, num_processes, process_id = env
    if num_processes is not None and num_processes > 1:
        # heartbeats bracket the rendezvous: a pod whose coordinator never
        # comes up looks IDENTICAL to a slow compile from the driver's seat
        # — the last heartbeat's phase tells them apart
        # (docs/observability.md); a stalled peer times out per attempt
        # and gets ONE retry + backoff before the clear failure
        _initialize_with_retry(
            heartbeat, f"{num_processes} processes @ {coordinator}",
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif num_processes is None:
        # Cloud TPU pod: fully autodetected — only when there genuinely are
        # multiple workers (single-worker boxes also set TPU_WORKER_HOSTNAMES)
        hosts = [h for h in os.environ.get(
            "TPU_WORKER_HOSTNAMES", "").split(",") if h]
        if len(hosts) > 1:
            _initialize_with_retry(
                heartbeat, f"TPU pod autodetect, {len(hosts)} hosts")
    return DistributedContext(
        process_id=jax.process_index(),
        num_processes=jax.process_count(),
        coordinator=coordinator,
        local_devices=jax.local_device_count(),
        global_devices=jax.device_count(),
    )


def global_mesh_1d(k: int | None = None):
    """1D vertex mesh over every chip in the job (all hosts).

    Device order follows ``jax.devices()`` — co-located chips are adjacent,
    so neighboring parts land on ICI-connected chips and only part-boundary
    traffic that crosses hosts rides DCN.
    """
    devs = jax.devices()
    return make_mesh_1d(k if k is not None else len(devs), devices=devs)


__all__ = ["DistributedContext", "init_distributed", "global_mesh_1d",
           "slurm_rendezvous_env", "AXIS"]
