"""Device mesh helpers for the 1D vertex-parallel layout.

The reference's process topology is flat: k MPI ranks / k torch.distributed
workers, one graph part each (``Parallel-GCN/main.c:101-103``,
``GPU/PGCN.py:241-253``).  The TPU-native equivalent is a 1D
``jax.sharding.Mesh`` over the chips with a single ``'v'`` (vertex) axis;
per-chip arrays are stacked along a leading k axis and sharded with
``PartitionSpec('v')``, replicated arrays use ``PartitionSpec()``.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "v"


def make_mesh_1d(k: int, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if len(devices) < k:
        raise ValueError(f"need {k} devices, have {len(devices)}")
    return Mesh(list(devices[:k]), (AXIS,))


def shard_stacked(mesh: Mesh, tree):
    """Place a pytree of (k, ...)-stacked arrays with the leading axis sharded."""
    sh = NamedSharding(mesh, P(AXIS))
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)


def replicate(mesh: Mesh, tree):
    sh = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
