"""Device mesh helpers for the 1D vertex-parallel layout.

The reference's process topology is flat: k MPI ranks / k torch.distributed
workers, one graph part each (``Parallel-GCN/main.c:101-103``,
``GPU/PGCN.py:241-253``).  The TPU-native equivalent is a 1D
``jax.sharding.Mesh`` over the chips with a single ``'v'`` (vertex) axis;
per-chip arrays are stacked along a leading k axis and sharded with
``PartitionSpec('v')``, replicated arrays use ``PartitionSpec()``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "v"


def make_mesh_1d(k: int, devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    if len(devices) < k:
        raise ValueError(f"need {k} devices, have {len(devices)}")
    return Mesh(list(devices[:k]), (AXIS,))


def local_chip_slice(mesh: Mesh) -> slice:
    """Positions along the stacked k axis owned by THIS process.

    ``jax.devices()`` orders chips process-contiguously, so a process's
    chips form one contiguous run of the 1D mesh; verified here because
    ``make_array_from_process_local_data`` needs the local chunk to be
    exactly that run.
    """
    pid = jax.process_index()
    mine = [i for i, d in enumerate(mesh.devices.flat)
            if d.process_index == pid]
    if not mine:
        return slice(0, 0)
    if mine != list(range(mine[0], mine[-1] + 1)):
        raise ValueError(f"process {pid}'s mesh positions are not "
                         f"contiguous: {mine}")
    return slice(mine[0], mine[-1] + 1)


def shard_stacked(mesh: Mesh, tree):
    """Place a pytree of (k, ...)-stacked arrays with the leading axis sharded.

    Single-process: plain ``device_put``.  Multi-process (every process
    holding the full stacked array, e.g. the plan arrays every host builds
    identically): the SUPPORTED path is
    ``jax.make_array_from_process_local_data`` fed each process's slice of
    the leading axis — ``device_put`` of a host-local array to a global
    sharding is not (the reference's analogous step is each rank reading its
    own ``H.r``/``A.r`` shard, ``Parallel-GCN/main.c:456-504``).
    """
    sh = NamedSharding(mesh, P(AXIS))
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
    sl = local_chip_slice(mesh)

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(sh, x[sl], x.shape)

    return jax.tree.map(put, tree)


def replicate(mesh: Mesh, tree):
    """Replicate a pytree on every chip (params / optimizer state)."""
    sh = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), tree)

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(sh, x, x.shape)

    return jax.tree.map(put, tree)
