"""Shared activation registry for all models, trainers, and oracles.

One table so every model accepts the same names: ``relu`` (torch-flavor GCN,
``GPU/PGCN.py:147``), ``sigmoid`` (MPI flavor, ``Parallel-GCN/main.c:79-81``),
``elu`` (standard GAT variant), ``none``.
"""

from __future__ import annotations

import jax

ACTS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "elu": jax.nn.elu,
    "none": lambda x: x,
}


def get_activation(name: str):
    try:
        return ACTS[name]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; one of {sorted(ACTS)}")
