"""Partitioned GAT model: sharded edge-softmax attention over the halo exchange.

Reference being matched: ``GPU/PGAT.py`` — the paper's demonstration that the
partitioned halo exchange composes with graph attention.  Per layer the
reference computes ``Z = H·W``, scores ``e_ij = z1_i + z2_j`` with
``z1 = Z·a1, z2 = Z·a2``, masks by ``A > 0``, row-softmaxes, and aggregates
``H' = attention · Z`` (``GPU/PGAT.py:137-150``); Xavier init (``:132-135``);
gradients all-reduced like the GCN (``:152-157``).

Two deliberate capability upgrades over the reference (SURVEY.md §5.7):

  * the reference keeps a **dense global-shape** adjacency and softmaxes over
    the full row with zeros filled for non-edges (``:52-63,144-146``) — fine
    for a demo, unscalable and mass-leaking.  Here attention is a masked
    **edge-softmax over the local padded edge lists** (true neighbor softmax),
    so memory is O(local nnz), never O(n²);
  * the boundary exchange ships each boundary vertex's ``[Z_j, z2_j]`` (f+1
    floats) instead of raw H, so attention scores for halo neighbors are
    computed without a second exchange — one all_to_all per layer, same as GCN.

Per-chip code, meant to run inside ``shard_map`` over the 1D vertex mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.pspmm import halo_exchange
from ..parallel.mesh import AXIS
from .activations import get_activation

# plan arrays the GAT forward consumes (fullbatch ships exactly these)
GAT_PLAN_FIELDS = ("send_idx", "halo_src", "edge_dst", "edge_src", "edge_w")

_NEG = -1e30


def init_gat_params(rng: jax.Array, dims: list[tuple[int, int]]):
    """Xavier-normal params per layer: ``w`` (fin,fout), ``a1``/``a2`` (fout,).

    The reference's single (2·fout, 1) attention vector (``GPU/PGAT.py:129``)
    is split into its two halves ``a1``/``a2`` — algebraically identical
    (``e_ij = [z_i ‖ z_j]·a = z_i·a1 + z_j·a2``), and the halves are what the
    sharded score computation needs separately.
    """
    xavier = jax.nn.initializers.glorot_normal()
    xavier_vec = jax.nn.initializers.normal(stddev=1.0)
    params = []
    for k, (fin, fout) in zip(jax.random.split(rng, len(dims)), dims):
        kw, k1, k2 = jax.random.split(k, 3)
        params.append({
            "w": xavier(kw, (fin, fout), jnp.float32),
            "a1": xavier_vec(k1, (fout,), jnp.float32) / jnp.sqrt(fout),
            "a2": xavier_vec(k2, (fout,), jnp.float32) / jnp.sqrt(fout),
        })
    return params


def edge_softmax(scores, edge_mask, edge_dst, num_rows: int):
    """Numerically-stable softmax over incoming edges of each dst row.

    ``edge_dst`` is sorted (plan invariant); padding edges (mask 0) get -inf
    scores so they carry zero mass; rows with no real edges produce zeros.
    """
    scores = jnp.where(edge_mask, scores, _NEG)
    row_max = jax.ops.segment_max(
        scores, edge_dst, num_segments=num_rows, indices_are_sorted=True)
    row_max = jnp.maximum(row_max, _NEG)            # empty segments: -inf → _NEG
    ex = jnp.where(edge_mask, jnp.exp(scores - row_max[edge_dst]), 0.0)
    denom = jax.ops.segment_sum(
        ex, edge_dst, num_segments=num_rows, indices_are_sorted=True)
    return ex / (denom[edge_dst] + 1e-9)


def gat_layer_local(
    w, a1, a2,
    h,                            # (B, fin) local rows
    send_idx, halo_src,           # halo plan
    edge_dst, edge_src, edge_w,   # padded local edge lists (E,)
    axis_name: str = AXIS,
):
    """One sharded GAT layer: project → exchange [Z‖z2] → edge-softmax → aggregate."""
    z = h @ w                                        # (B, fout)
    z1 = z @ a1                                      # (B,)
    z2 = z @ a2                                      # (B,)
    table = jnp.concatenate([z, z2[:, None]], axis=-1)
    halo = halo_exchange(table, send_idx, halo_src, axis_name)
    full = jnp.concatenate([table, halo], axis=0)    # (B+R, fout+1)
    zt, z2t = full[:, :-1], full[:, -1]
    mask = edge_w > 0
    scores = z1[edge_dst] + z2t[edge_src]            # (E,)
    alpha = edge_softmax(scores, mask, edge_dst, z.shape[0])
    gathered = zt[edge_src] * alpha[:, None]
    return jax.ops.segment_sum(
        gathered, edge_dst, num_segments=z.shape[0], indices_are_sorted=True)


def gat_forward_local(
    params,
    h,
    pa,                           # plan arrays dict (GAT_PLAN_FIELDS)
    activation: str = "none",
    final_activation: str = "none",
    symmetric: bool = False,      # accepted for interface parity; attention
                                  # weights are never symmetric, so unused
    axis_name: str = AXIS,
):
    """Per-chip forward: stacked GAT layers.

    The reference stacks bare PGAT modules with no inter-layer nonlinearity
    (softmax-weighted aggregation is the nonlinearity, ``GPU/PGAT.py:202-213``);
    ``activation='elu'`` gives the standard GAT variant.

    GAT keeps the combined ``[local; halo]`` edge list (not the split
    overlap form): the edge-softmax normalizes each row over local AND halo
    edges together, so the aggregation genuinely depends on the exchange.
    """
    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    for i, p in enumerate(params):
        h = gat_layer_local(
            p["w"], p["a1"], p["a2"], h,
            pa["send_idx"], pa["halo_src"],
            pa["edge_dst"], pa["edge_src"], pa["edge_w"],
            axis_name=axis_name)
        h = fact(h) if i == nl - 1 else act(h)
    return h
