"""Partitioned GAT model: sharded edge-softmax attention over the halo exchange.

Reference being matched: ``GPU/PGAT.py`` — the paper's demonstration that the
partitioned halo exchange composes with graph attention.  Per layer the
reference computes ``Z = H·W``, scores ``e_ij = z1_i + z2_j`` with
``z1 = Z·a1, z2 = Z·a2``, masks by ``A > 0``, row-softmaxes, and aggregates
``H' = attention · Z`` (``GPU/PGAT.py:137-150``); Xavier init (``:132-135``);
gradients all-reduced like the GCN (``:152-157``).

Two deliberate capability upgrades over the reference (SURVEY.md §5.7):

  * the reference keeps a **dense global-shape** adjacency and softmaxes over
    the full row with zeros filled for non-edges (``:52-63,144-146``) — fine
    for a demo, unscalable and mass-leaking.  Here attention is a masked
    **edge-softmax over the local padded edge lists** (true neighbor softmax),
    so memory is O(local nnz), never O(n²);
  * the boundary exchange ships each boundary vertex's ``[Z_j, z2_j]`` (f+1
    floats) instead of raw H, so attention scores for halo neighbors are
    computed without a second exchange — one all_to_all per layer, same as GCN.

Per-chip code, meant to run inside ``shard_map`` over the 1D vertex mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.pspmm import halo_exchange
from ..parallel.mesh import AXIS
from .activations import get_activation

# plan arrays the GAT forward consumes (fullbatch ships exactly these):
# the bucketed combined-edge layout plus its hub tail
GAT_PLAN_FIELDS = ("send_idx", "halo_src", "cell_idx", "cell_w",
                   "ctail_dst", "ctail_src", "ctail_w")

_NEG = -1e30


def init_gat_params(rng: jax.Array, dims: list[tuple[int, int]]):
    """Xavier-normal params per layer: ``w`` (fin,fout), ``a1``/``a2`` (fout,).

    The reference's single (2·fout, 1) attention vector (``GPU/PGAT.py:129``)
    is split into its two halves ``a1``/``a2`` — algebraically identical
    (``e_ij = [z_i ‖ z_j]·a = z_i·a1 + z_j·a2``), and the halves are what the
    sharded score computation needs separately.
    """
    xavier = jax.nn.initializers.glorot_normal()
    xavier_vec = jax.nn.initializers.normal(stddev=1.0)
    params = []
    for k, (fin, fout) in zip(jax.random.split(rng, len(dims)), dims):
        kw, k1, k2 = jax.random.split(k, 3)
        params.append({
            "w": xavier(kw, (fin, fout), jnp.float32),
            "a1": xavier_vec(k1, (fout,), jnp.float32) / jnp.sqrt(fout),
            "a2": xavier_vec(k2, (fout,), jnp.float32) / jnp.sqrt(fout),
        })
    return params


def edge_softmax(scores, edge_mask, edge_dst, num_rows: int):
    """Numerically-stable softmax over incoming edges of each dst row.

    Segment-machinery form over a sorted COO edge list — for callers
    holding plain edge lists; unit-tested against a dense softmax.  The
    trainer path uses the streaming bucketed form in ``gat_layer_local``
    (itself parity-tested against the dense GAT oracle).
    """
    scores = jnp.where(edge_mask, scores, _NEG)
    row_max = jax.ops.segment_max(
        scores, edge_dst, num_segments=num_rows, indices_are_sorted=True)
    row_max = jnp.maximum(row_max, _NEG)            # empty segments: -inf → _NEG
    ex = jnp.where(edge_mask, jnp.exp(scores - row_max[edge_dst]), 0.0)
    denom = jax.ops.segment_sum(
        ex, edge_dst, num_segments=num_rows, indices_are_sorted=True)
    return ex / (denom[edge_dst] + 1e-9)


def gat_layer_local(
    w, a1, a2,
    h,                            # (B, fin) local rows
    send_idx, halo_src,           # halo plan
    cell_idx, cell_w,             # bucketed combined-edge layout (flat)
    ctail_dst, ctail_src, ctail_w,  # hub overflow tail (COO)
    buckets,                      # static ((nb, wb), ...) of cell layout
    axis_name: str = AXIS,
):
    """One sharded GAT layer: project → exchange [Z‖z2] → streaming
    edge-softmax over the bucketed slots → aggregate.

    The attention softmax runs ONLINE (flash-attention style): per width
    slot t, ONE gather of ``[z_src ‖ z2_src]`` rows feeds both the score and
    the aggregation, with running max ``m``, denominator ``d`` and weighted
    accumulator renormalized as larger scores arrive.  This replaces the
    segment-max/sum/scatter pipeline over a COO edge list (measured 1.15 s
    vs 0.037 s GCN at ogbn-arxiv scale) with the same per-slot fused
    gathers the GCN path uses.  Hub rows past the bucket width cap merge
    their tail edges through a second max/renormalize pass — exact, not
    approximate.  The v5e gather is row-rate-bound, so fetching the
    (fout+1)-wide row costs the same as fout; one gather per edge total.
    """
    b = h.shape[0]
    z = h @ w                                        # (B, fout)
    fout = z.shape[-1]
    z1 = z @ a1                                      # (B,)
    z2 = z @ a2                                      # (B,)
    table = jnp.concatenate([z, z2[:, None]], axis=-1)
    halo = halo_exchange(table, send_idx, halo_src, axis_name)
    full = jnp.concatenate([table, halo], axis=0)    # (B+R, fout+1)

    accs, denoms, maxes = [], [], []
    off = r0 = 0
    for nb, wb in buckets:
        z1b = jax.lax.slice_in_dim(z1, r0, r0 + nb)
        m = jnp.full((nb,), _NEG, jnp.float32)
        d = jnp.zeros((nb,), jnp.float32)
        acc = jnp.zeros((nb, fout), jnp.float32)
        for t in range(wb):
            seg = slice(off + t * nb, off + (t + 1) * nb)
            g = jnp.take(full, cell_idx[seg], axis=0)   # (nb, fout+1)
            valid = cell_w[seg] > 0
            s = jnp.where(valid, z1b + g[:, -1], _NEG)
            m2 = jnp.maximum(m, s)
            scale = jnp.exp(m - m2)                  # 0 while m = -inf
            e = jnp.where(valid, jnp.exp(s - m2), 0.0)
            acc = acc * scale[:, None] + e[:, None] * g[:, :-1]
            d = d * scale + e
            m = m2
        accs.append(acc)
        denoms.append(d)
        maxes.append(m)
        off += nb * wb
        r0 += nb
    acc = accs[0] if len(accs) == 1 else jnp.concatenate(accs, axis=0)
    d = denoms[0] if len(denoms) == 1 else jnp.concatenate(denoms)
    m = maxes[0] if len(maxes) == 1 else jnp.concatenate(maxes)

    # fold the hub tail into the same softmax: global row max first, then
    # rescale the streamed partials and add the tail's exp mass
    tvalid = ctail_w > 0
    ts = jnp.where(tvalid, z1[ctail_dst] + full[ctail_src, -1], _NEG)
    tmax = jax.ops.segment_max(ts, ctail_dst, num_segments=b,
                               indices_are_sorted=True)
    mg = jnp.maximum(m, jnp.maximum(tmax, _NEG))
    # empty rows (m = mg = _NEG) get rescale = exp(0) = 1, harmless
    # because their acc and d are both exactly 0
    rescale = jnp.exp(m - mg)
    acc = acc * rescale[:, None]
    d = d * rescale
    te = jnp.where(tvalid, jnp.exp(ts - mg[ctail_dst]), 0.0)
    d = d + jax.ops.segment_sum(te, ctail_dst, num_segments=b,
                                indices_are_sorted=True)
    acc = acc.at[ctail_dst].add(te[:, None] * full[ctail_src, :-1])
    return acc / (d + 1e-9)[:, None]


def gat_forward_local(
    params,
    h,
    pa,                           # plan arrays dict (GAT_PLAN_FIELDS)
    activation: str = "none",
    final_activation: str = "none",
    symmetric: bool = False,      # accepted for interface parity; attention
                                  # weights are never symmetric, so unused
    cell_buckets: tuple | None = None,   # static plan.cell_buckets
    axis_name: str = AXIS,
):
    """Per-chip forward: stacked GAT layers.

    The reference stacks bare PGAT modules with no inter-layer nonlinearity
    (softmax-weighted aggregation is the nonlinearity, ``GPU/PGAT.py:202-213``);
    ``activation='elu'`` gives the standard GAT variant.

    GAT streams the combined ``[local; halo]`` bucketed edge layout (not the
    split overlap form): the edge-softmax normalizes each row over local AND
    halo edges together, so the aggregation genuinely depends on the
    exchange.
    """
    if cell_buckets is None:
        raise ValueError("GAT forward needs the plan's static cell_buckets")
    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    for i, p in enumerate(params):
        h = gat_layer_local(
            p["w"], p["a1"], p["a2"], h,
            pa["send_idx"], pa["halo_src"],
            pa["cell_idx"], pa["cell_w"],
            pa["ctail_dst"], pa["ctail_src"], pa["ctail_w"],
            cell_buckets, axis_name=axis_name)
        h = fact(h) if i == nl - 1 else act(h)
    return h
