"""Partitioned GAT model: sharded edge-softmax attention over the halo exchange.

Reference being matched: ``GPU/PGAT.py`` — the paper's demonstration that the
partitioned halo exchange composes with graph attention.  Per layer the
reference computes ``Z = H·W``, scores ``e_ij = z1_i + z2_j`` with
``z1 = Z·a1, z2 = Z·a2``, masks by ``A > 0`` (here ``A != 0``, so
signed-weight graphs keep their edges — ADVICE r4), row-softmaxes, and aggregates
``H' = attention · Z`` (``GPU/PGAT.py:137-150``); Xavier init (``:132-135``);
gradients all-reduced like the GCN (``:152-157``).

Two deliberate capability upgrades over the reference (SURVEY.md §5.7):

  * the reference keeps a **dense global-shape** adjacency and softmaxes over
    the full row with zeros filled for non-edges (``:52-63,144-146``) — fine
    for a demo, unscalable and mass-leaking.  Here attention is a masked
    **edge-softmax over the local padded edge lists** (true neighbor softmax),
    so memory is O(local nnz), never O(n²);
  * the boundary exchange ships each boundary vertex's ``[Z_j, z2_j]`` (f+1
    floats) instead of raw H, so attention scores for halo neighbors are
    computed without a second exchange — one all_to_all per layer, same as GCN.

Per-chip code, meant to run inside ``shard_map`` over the 1D vertex mesh.
"""

from __future__ import annotations

import os as _os
from functools import partial

import jax
import jax.numpy as jnp

from ..ops.pspmm import (a2a_or_identity, halo_exchange, halo_exchange_ragged,
                         halo_exchange_ragged_multi)
from ..parallel.mesh import AXIS
from .activations import get_activation

# plan arrays the GAT forward consumes (fullbatch ships exactly these):
# the bucketed combined-edge layout plus its hub tail
GAT_PLAN_FIELDS = ("send_idx", "halo_src", "cell_idx", "cell_w",
                   "ctail_dst", "ctail_src", "ctail_w", "row_valid")
# Under comm_schedule='ragged' the dense (k, S) send buckets are swapped for
# the per-round ppermute-ring layout (CommPlan.ensure_ragged) — the
# rsend_idx/rhalo_dst split is per-VERTEX and model-independent, so GAT
# reuses the exact arrays the GCN ring rides; only the table riding them
# (the (fout+1)-lane attention table) differs.
GAT_PLAN_FIELDS_RAGGED = ("rsend_idx", "rhalo_dst", "cell_idx", "cell_w",
                          "ctail_dst", "ctail_src", "ctail_w", "row_valid")
# Under the Pallas VMEM aggregator (``use_pallas_spmm`` fires for GAT too)
# the bucketed slot passes swap for mask-weighted runs of the dst-tile
# kernel over the COMBINED-edge tile classes
# (``CommPlan.ensure_pallas_cell_tiles``); the ragged flavor reads the
# ring's receive concat directly (``ptile_crsrc`` ring-re-based sources —
# no halo table, so ``rhalo_dst`` is NOT shipped).
GAT_PLAN_FIELDS_PALLAS = ("send_idx", "halo_src", "ptile_csrc", "ptile_cld",
                          "ptile_cw", "row_valid")
GAT_PLAN_FIELDS_PALLAS_RAGGED = ("rsend_idx", "ptile_crsrc", "ptile_cld",
                                 "ptile_cw", "row_valid")

# static comm spec threaded through the layer stack: ('a2a',) selects the
# dense all_to_all, ('ragged', rr_sizes, r) the per-round ppermute ring —
# hashable, so it rides custom_vjp's nondiff_argnums
COMM_A2A = ("a2a",)

_NEG = -1e30


def score_project(z, a2):
    """Per-row attention-score projection ``z2_i = z_i · a2`` as a ROW-LOCAL
    multiply-reduce instead of a matvec ``z @ a2``.

    Same math; the form matters for bit-reproducibility: XLA:CPU's gemv
    kernel makes each output element's accumulation order depend on the
    ROW's position and the matrix height (measured: permuting rows of a
    (339, 16) @ (16,) matvec changes bits, and sub-matrices disagree with
    the full product on scattered rows), while the elementwise-multiply +
    per-row reduce is position- and height-independent (each row reduces
    its own K-length chain).  The sub-graph serving path
    (``serve/subgraph.py``) recomputes boundary rows' scores from COMPACT
    receptive-set tables and pins f32 bit-identity (``==``) against
    ``evaluate()`` — only the row-local form can deliver that.  Every
    consumer (forward, backward remat, the serve stabilizer precompute)
    rides THIS helper so the projection cannot fork."""
    return jnp.sum(z * a2, axis=-1)


def gat_exchange_lane_widths(widths, compute_dtype: str | None = None):
    """Per-layer wire width of the GAT attention-table exchange, in
    f32-LANE equivalents — THE shared lane model for every byte-accounting
    consumer (``obs.attribution.step_cost``, ``CommStats`` — the
    schedule-selection ratio needs no lanes: they cancel, see
    ``resolve_comm_schedule``); change the forward's table forms and this
    together.

    Per layer (both exchange directions ship the same table shape):

      * f32 fused table ``[p ‖ u]``: ``fout + 1`` lanes;
      * f32 split pair (``fout`` features + 1 scalar, whether as the a2a's
        two dense dispatches or one two-lane ragged ring): the SAME
        ``fout + 1`` lanes across its buffers;
      * bf16 packed (even ``fout``): the bit-paired ``fout/2 + 1`` f32
        lanes;
      * bf16 unpacked (odd ``fout``): a ``(fout+1)``-lane bf16 table =
        ``(fout+1)/2`` f32-lane equivalents.

    Expressing narrow dtypes as f32-lane equivalents keeps one itemsize (4)
    for every downstream byte figure.
    """
    out = []
    for fout in widths:
        fout = int(fout)
        if compute_dtype == "bfloat16":
            out.append(fout // 2 + 1 if fout % 2 == 0 else (fout + 1) // 2)
        else:
            out.append(fout + 1)
    return out


def init_gat_params(rng: jax.Array, dims: list[tuple[int, int]]):
    """Xavier-normal params per layer: ``w`` (fin,fout), ``a1``/``a2`` (fout,).

    The reference's single (2·fout, 1) attention vector (``GPU/PGAT.py:129``)
    is split into its two halves ``a1``/``a2`` — algebraically identical
    (``e_ij = [z_i ‖ z_j]·a = z_i·a1 + z_j·a2``), and the halves are what the
    sharded score computation needs separately.
    """
    xavier = jax.nn.initializers.glorot_normal()
    xavier_vec = jax.nn.initializers.normal(stddev=1.0)
    params = []
    for k, (fin, fout) in zip(jax.random.split(rng, len(dims)), dims):
        kw, k1, k2 = jax.random.split(k, 3)
        params.append({
            "w": xavier(kw, (fin, fout), jnp.float32),
            "a1": xavier_vec(k1, (fout,), jnp.float32) / jnp.sqrt(fout),
            "a2": xavier_vec(k2, (fout,), jnp.float32) / jnp.sqrt(fout),
        })
    return params


def edge_softmax(scores, edge_mask, edge_dst, num_rows: int):
    """Numerically-stable softmax over incoming edges of each dst row.

    Segment-machinery form over a sorted COO edge list — for callers
    holding plain edge lists; unit-tested against a dense softmax.  The
    trainer path uses the streaming bucketed form in ``gat_layer_local``
    (itself parity-tested against the dense GAT oracle).
    """
    scores = jnp.where(edge_mask, scores, _NEG)
    row_max = jax.ops.segment_max(
        scores, edge_dst, num_segments=num_rows, indices_are_sorted=True)
    row_max = jnp.maximum(row_max, _NEG)            # empty segments: -inf → _NEG
    ex = jnp.where(edge_mask, jnp.exp(scores - row_max[edge_dst]), 0.0)
    denom = jax.ops.segment_sum(
        ex, edge_dst, num_segments=num_rows, indices_are_sorted=True)
    return ex / (denom[edge_dst] + 1e-9)


def gat_layer_local(
    w, a1, a2,
    h,                            # (B, fin) local rows
    send_idx, halo_src,           # halo plan
    cell_idx, cell_w,             # bucketed combined-edge layout (flat)
    ctail_dst, ctail_src, ctail_w,  # hub overflow tail (COO)
    row_valid=None,               # (B,) 1/0 — real vs pad rows
    buckets=((1, 1),),            # static ((nb, wb), ...) of cell layout
    axis_name: str = AXIS,
    comm=COMM_A2A,                # static transport spec (_exchange_table)
):
    """One sharded GAT layer for GENERAL (possibly asymmetric) edge
    patterns: the factored forward of ``gat_layer_sym`` with autodiff
    providing the backward.

    The factorization (see ``gat_layer_sym``) is pattern-independent:
    ``s_ij = z1_i + z2_j`` is shift-invariant under the row softmax, so
    ``out_i = (Σ_{j∈N(i)} u_j z_j) / (Σ_{j∈N(i)} u_j)`` with
    ``u_j = exp(z2_j − C)`` holds for any in-edge set — only the BACKWARD
    trick (transpose = the same gather passes) needs pattern symmetry.
    Routing this path through the same ``bucketed_slot_reduce`` core means
    the general path shares the GCN memory policy (budgeted unroll / scan
    over width slots) instead of hand-unrolling a Python loop per slot
    (the round-3 streaming form: ~7k ops/step at products scale).
    Autodiff's mechanical transpose (scatter-adds) carries the backward —
    slower than the symmetric custom VJP, and only taken when the plan's
    edge pattern genuinely is asymmetric.
    """
    if row_valid is None:
        row_valid = jnp.ones((h.shape[0],), jnp.float32)
    out, _, _, _, _ = _gat_factored_fwd_core(
        w, a2, h, send_idx, halo_src, cell_idx, cell_w,
        ctail_dst, ctail_src, ctail_w, row_valid, buckets, axis_name, comm)
    return out


@partial(jax.custom_vjp, nondiff_argnums=(12, 13, 14))
def gat_layer_sym(w, a1, a2, h, send_idx, halo_src, cell_idx, cell_w,
                  ctail_dst, ctail_src, ctail_w, row_valid, buckets,
                  axis_name=AXIS, comm=COMM_A2A):
    """``gat_layer_local`` in FACTORIZED form with a gather-only backward,
    for SYMMETRIC edge patterns (undirected graphs — the standing case).

    Two algebraic facts reshape the whole layer:

      * ``s_ij = z1_i + z2_j`` is SHIFT-INVARIANT under the row softmax: any
        per-row constant cancels, so ``z1``/``a1`` do not affect the output
        at all (``∂L/∂a1 = 0`` exactly; the reference's PGAT shares this —
        no LeakyReLU between the additive scores and the softmax,
        ``GPU/PGAT.py:137-150``) and α factorizes per SOURCE:
        ``α_ij = u_j / Σ_{j'∈N(i)} u_j'`` with ``u_j = exp(z2_j − C)``.
        The layer is exactly ``out_i = (Σ_j u_j z_j) / (Σ_j u_j)`` — two
        mask-weighted aggregations over the bucketed slots, both gathering
        128-lane rows (the v5e gather drops 3.2× the moment a row exceeds
        one 128-lane tile, so numerator rows ``u·z`` and a lane-broadcast
        denominator table are kept exactly 128 wide; the denominator pass
        row-sums its gathered tile, which also keeps XLA from narrowing the
        gather).  ``C`` is the global max of ``z2`` (one pmax): exact
        stabilization for score spreads < ~80 nats — beyond that f32
        attention is degenerate under ANY stabilization;

      * for a symmetric pattern, row ``j``'s in-edge slots enumerate exactly
        the rows ``i`` that aggregate ``j``, so the backward transposes
        ``N = P·(u z), D = P·u`` into the SAME gather passes over the
        exchanged ``[ḡ/D ‖ −(ḡ·out)/D]`` table — no scatter, no sort, and
        the halo's backward contribution arrives through a forward-style
        exchange (measured: autodiff's scatter transpose was ~223 ms of the
        320 ms online-softmax GAT epoch at ogbn-arxiv scale; this form
        benches 0.062 s).
    """
    out, _, _, _, _ = _gat_factored_fwd_core(
        w, a2, h, send_idx, halo_src, cell_idx, cell_w,
        ctail_dst, ctail_src, ctail_w, row_valid, buckets, axis_name, comm)
    return out


# Tail gathers above this size stream through a chunked scan instead of one
# shot: a power-law graph at products scale spills ~29M hub edges past the
# bucket width cap, and the one-shot tail gather materialized a 29.8 GB
# (tail, fout+1 -> 256-lane-padded) temp — an instant compile-time OOM on a
# 16 GB chip (measured round 4).  Chunking bounds the temp like the slot
# scan bounds bucket temps.  SGCN_GAT_TAIL_CHUNK overrides (bytes); read at
# call time so setting it after import (monkeypatch, A/B) works — ADVICE r4.
def _tail_chunk_bytes() -> int:
    return int(_os.environ.get("SGCN_GAT_TAIL_CHUNK", 256 * 1024**2))


# GAT programs run several slot reduces back to back (num+den, fwd+bwd), so
# each gets HALF the default scan-unroll liveness budget — one pass at the
# full budget measured as the margin of a 264 MB products-scale OOM.
_GAT_SCAN_LIVE = 3 * 1024**3 // 2

# Row count above which the denominator pass gathers the 1-D u directly
# instead of a (rows, 128) broadcast table (see _pair_slot_pass).
_ONED_U_ROWS = 1_000_000


def _edge_pass(cell_idx, cell_w, ctail_dst, ctail_src, ctail_w, buckets,
               b, contrib, init, slot_bytes):
    """Shared scaffold for every masked in-edge aggregation: bucketed slot
    reduce + hub-tail fold, generic over the per-slot ``contrib``'s output
    pytree (which also decodes the tail — the tail IS one more masked
    slot)."""
    from ..ops.pspmm import bucketed_slot_reduce

    outs = bucketed_slot_reduce(cell_idx, cell_w, buckets, contrib=contrib,
                                init=init, slot_bytes=slot_bytes,
                                scan_live_limit=_GAT_SCAN_LIVE)
    if len(outs) == 1:
        out = outs[0]
    else:
        out = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *outs)

    t = ctail_src.shape[0]
    tail_chunk = _tail_chunk_bytes()
    if slot_bytes(t) <= tail_chunk:
        tc = contrib(ctail_src, ctail_w)
        return jax.tree.map(
            lambda acc, x: acc + jax.ops.segment_sum(
                x, ctail_dst, num_segments=b, indices_are_sorted=True),
            out, tc)

    # chunked tail: pad with weight-0 edges on the last (already-max) dst so
    # each chunk stays dst-sorted, then scan chunk-wise segment-sums.  The
    # carry IS the bucket output — fresh zero accumulators would hold
    # another (b, fout) array live (1.17 GB at products scale) for no reason.
    nchunks = -(-slot_bytes(t) // tail_chunk)
    chunk = -(-t // nchunks)
    pad = nchunks * chunk - t
    cd = jnp.pad(ctail_dst, (0, pad), constant_values=b - 1)
    cs = jnp.pad(ctail_src, (0, pad))
    cw = jnp.pad(ctail_w, (0, pad))

    def body(carry, xs):
        d_i, s_i, w_i = xs
        tc = contrib(s_i, w_i)
        return jax.tree.map(
            lambda acc, x: acc + jax.ops.segment_sum(
                x, d_i, num_segments=b, indices_are_sorted=True),
            carry, tc), None

    out, _ = jax.lax.scan(
        body, out,
        (cd.reshape(nchunks, chunk), cs.reshape(nchunks, chunk),
         cw.reshape(nchunks, chunk)))
    return out


# The FUSED one-gather-per-edge form applies ONLY while the (fout+1)-lane
# row fits one 128-lane tile.  Past a tile the micro numbers flatter it (a
# lone 2-tile gather out-rates two 1-tile gathers at GB tables, 142 vs
# 2×209 Mrows/s) but the REAL program pays XLA's tile padding: every
# (x, 129) f32 array physically doubles (measured 2.34 GB for the products
# table, "2.0x expansion"), and at products scale that padding alone tipped
# the step from fitting to a 17.07 GB compile-time OOM.  SGCN_GAT_FUSED=0
# forces the split form everywhere (A/B lever).


def _fused_form(fout: int) -> bool:
    """One-gather-per-edge only while the (fout+1)-lane row fits one tile
    (SGCN_GAT_FUSED: 0 forces split everywhere, 2 forces fused even past a
    tile — A/B levers; read at call time per ADVICE r4)."""
    mode = _os.environ.get("SGCN_GAT_FUSED", "1")   # 0=never, 2=always
    if mode == "0":
        return False
    if mode == "2":
        return True
    return fout + 1 <= 128


def _exchange_table(table, send_idx, halo_src, axis_name, comm=COMM_A2A):
    """Ship one boundary row table over the SELECTED transport and return
    its (R, d) halo block — the single dispatch point of the GAT exchange
    (``docs/comm_schedule.md``).  Under ``('a2a',)`` ``send_idx``/
    ``halo_src`` are the plan's dense ``(k, S)`` layout; under
    ``('ragged', rr_sizes, r)`` they are ``rsend_idx``/``rhalo_dst`` and
    the table rides the per-round-sized ppermute ring.  Halo rows are
    bit-identical either way (the ragged scatter writes each real slot
    exactly once), so every slot pass downstream is schedule-blind."""
    if comm[0] == "ragged":
        return halo_exchange_ragged(table, send_idx, halo_src,
                                    comm[1], comm[2], axis_name)
    return halo_exchange(table, send_idx, halo_src, axis_name)


def _exchange_rows_scalar(p, u, send_idx, halo_src, axis_name,
                          comm=COMM_A2A):
    """Exchange feature rows AND a per-row scalar without ever building a
    ``(B, fout+1)``-lane table: on the dense schedule the scalar rides its
    own (k, S) buffer (second all_to_all of negligible bytes), dodging the
    2× tile-padding tax a 129-lane f32 array pays.  On the ragged schedule
    both lanes ride ONE ring (``halo_exchange_ragged_multi``): the
    ``(S_d, fout+1)`` concatenation exists only at round size — never the
    (B, ·) table the split form is dodging — so the two dense dispatches
    per exchange collapse into one ppermute per live round.  Returns the
    concatenated ``[local; halo]`` pair
    ``(full_p (B+R, fout), full_u (B+R,))``."""
    if comm[0] == "ragged":
        halo_p, halo_u = halo_exchange_ragged_multi(
            (p, u), send_idx, halo_src, comm[1], comm[2], axis_name)
    else:
        halo_p = halo_exchange(p, send_idx, halo_src, axis_name)
        buf_u = jnp.take(u, send_idx, axis=0)                    # (k, S)
        recv_u = a2a_or_identity(buf_u, axis_name)
        halo_u = jnp.take(recv_u.reshape(-1), halo_src, axis=0)  # (R,)
    return (jnp.concatenate([p, halo_p], axis=0),
            jnp.concatenate([u, halo_u]))


def _mask_slot_pass(table, fout, cell_idx, cell_w, ctail_dst, ctail_src,
                    ctail_w, buckets, b):
    """FUSED masked Σ over in-edge slots of the ``(fout+1)``-wide ``[p ‖ u]``
    table: one gather per edge; both slices of the gathered row are consumed
    so XLA keeps a single full-row gather.  Callers use this only under
    ``_fused_form`` (row within one tile).
    Returns ``(N, D)``: (b, fout) feature sums and (b,) scalar sums."""
    def contrib(idx, wv):
        mask = (wv != 0).astype(jnp.float32)
        g = jnp.take(table, idx, axis=0).astype(jnp.float32)
        return g[:, :fout] * mask[:, None], g[:, fout] * mask

    return _edge_pass(cell_idx, cell_w, ctail_dst, ctail_src, ctail_w,
                      buckets, b, contrib,
                      init=lambda nb: (jnp.zeros((nb, fout), jnp.float32),
                                       jnp.zeros((nb,), jnp.float32)),
                      slot_bytes=lambda nb: nb * (fout + 1) * 4)


def _pair_slot_pass(full_p, full_u, fout, cell_idx, cell_w, ctail_dst,
                    ctail_src, ctail_w, buckets, b):
    """SPLIT masked Σ: feature-table gather + 128-lane broadcast-u gather
    (the row-sum consumes every lane, keeping that gather a fast full-tile
    fetch).  Taken when the fused row would cross a tile (fout ≥ 128):
    the 2-tile row out-rates two 1-tile gathers in isolation, but every
    129-lane f32 array physically DOUBLES under tile padding (measured
    2.0× at products scale) and that padding tipped the step into a
    compile-time OOM — so past one tile the split form wins end-to-end.

    The two aggregations run as SEPARATE edge passes, not one combined
    contrib: per-pass slot temps halve (one gather each), which doubles the
    scan-unroll headroom and lets the broadcast-u table die before the next
    pass's temps peak."""
    def contrib_n(idx, wv):
        mask = (wv != 0).astype(jnp.float32)
        return jnp.take(full_p, idx, axis=0).astype(jnp.float32) \
            * mask[:, None]

    n_out = _edge_pass(cell_idx, cell_w, ctail_dst, ctail_src, ctail_w,
                       buckets, b, contrib_n,
                       init=lambda nb: jnp.zeros((nb, fout), jnp.float32),
                       slot_bytes=lambda nb: nb * fout * 4)

    rows = full_p.shape[0]
    if rows >= _ONED_U_ROWS:
        # huge tables: gather the scalar u directly (1-D, no tile padding).
        # A narrow gather runs ~1.45× slower per row than a 128-lane one
        # (143 vs 209 Mrows/s measured at 2.45M rows), but the (rows, 128)
        # broadcast-u table it replaces is 1.6 GB per pass at products
        # scale — the difference between fitting and the round-4 OOMs.
        def contrib_d(idx, wv):
            mask = (wv != 0).astype(jnp.float32)
            return jnp.take(full_u, idx, axis=0).astype(jnp.float32) * mask

        d_out = _edge_pass(cell_idx, cell_w, ctail_dst, ctail_src, ctail_w,
                           buckets, b, contrib_d,
                           init=lambda nb: jnp.zeros((nb,), jnp.float32),
                           slot_bytes=lambda nb: nb * 8)
        return n_out, d_out

    # small tables: 128-lane broadcast-u gather (full-tile fetch at the fast
    # 1-tile row rate; the row-sum consumes every lane)
    ub = jnp.broadcast_to(full_u[:, None], (rows, 128))

    def contrib_d(idx, wv):
        mask = (wv != 0).astype(jnp.float32)
        return jnp.take(ub, idx, axis=0).astype(jnp.float32).sum(axis=-1) \
            * (mask / 128)

    d_out = _edge_pass(cell_idx, cell_w, ctail_dst, ctail_src, ctail_w,
                       buckets, b, contrib_d,
                       init=lambda nb: jnp.zeros((nb,), jnp.float32),
                       slot_bytes=lambda nb: nb * 128 * 4)
    return n_out, d_out


def _pack_rows(x16):
    """(B, f) bf16 → (B, f/2) f32 by bit-pairing adjacent lanes."""
    b, f = x16.shape
    return jax.lax.bitcast_convert_type(
        x16.reshape(b, f // 2, 2), jnp.float32)


def _unpack_rows(xp, f):
    """(B, f/2) f32 → (B, f) bf16 (inverse of ``_pack_rows``)."""
    return jax.lax.bitcast_convert_type(xp, jnp.bfloat16).reshape(
        xp.shape[0], f)


def _packed_aggregate(rows16, scalar, fout, send_idx, halo_src, cell_idx,
                      cell_w, ctail_dst, ctail_src, ctail_w, buckets, b,
                      axis_name, comm=COMM_A2A):
    """Masked Σ over in-edges of ``(rows16[src], scalar[src])`` — ONE gather
    per edge: the bf16 feature row bit-packs into ``fout/2`` f32 lanes and
    the scalar rides the next lane, so the whole (fout/2 + 1)-wide gathered
    row stays inside one 128-lane tile for fout ≤ 254 (the v5e gather drops
    3.2× past one tile).  Exchange ships the same packed table — half the
    ICI bytes of the f32 path — over whichever transport ``comm`` selects.
    Used by the bf16 compute path; masked slots contribute exactly 0 either
    way."""
    half = fout // 2
    table = jnp.concatenate([_pack_rows(rows16), scalar[:, None]], axis=-1)
    halo = _exchange_table(table, send_idx, halo_src, axis_name, comm)
    full = jnp.concatenate([table, halo], axis=0)     # (B+R, half+1)

    def contrib(idx, wv):
        mask = (wv != 0).astype(jnp.float32)
        g = jnp.take(full, idx, axis=0)               # (nb, half+1)
        rows = _unpack_rows(g[:, :half], fout).astype(jnp.float32)
        return rows * mask[:, None], g[:, half] * mask

    return _edge_pass(cell_idx, cell_w, ctail_dst, ctail_src, ctail_w,
                      buckets, b, contrib,
                      init=lambda nb: (jnp.zeros((nb, fout), jnp.float32),
                                       jnp.zeros((nb,), jnp.float32)),
                      slot_bytes=lambda nb: nb * (half + 1 + fout) * 4)


def _is_pallas_comm(comm) -> bool:
    return comm[0] in ("a2a+pallas", "ragged+pallas")


def _gat_pallas_aggregate(p, s, fout, form, send_idx, halo_src,
                          csrc, cw, cld, axis_name, comm):
    """The GAT attention slot pass on the VMEM kernel: masked Σ of the
    ``[p ‖ s]`` table over combined-edge tile classes.  The WIRE is
    form-for-form the slot-pass path's (``gat_table_form`` — the audit's
    census does not change): ``fused`` ships one ``(·, fout+1)`` table and
    runs ONE kernel pass whose trailing lane is the scalar sum; ``split``
    ships the feature table and the scalar separately (two dense
    dispatches / one two-lane ring) and runs two kernel passes.  The
    ragged flavor feeds the ring's round-major receive concat to the
    kernel directly (``pallas_ring_concat`` — no halo-table scatter), with
    tile sources ring-re-based at plan time, so its bits equal the a2a
    flavor's (same tile fold order).  Returns ``(N (b, fout), D (b,))``.
    """
    from ..ops.pallas_spmm import gat_pallas_pass, pallas_ring_concat

    tbp, cclasses, pemu = comm[-1]
    b = p.shape[0]
    ragged = comm[0] == "ragged+pallas"
    if form == "fused":
        table = jnp.concatenate([p, s[:, None]], axis=-1)
        halo = (pallas_ring_concat(table, send_idx, comm[1], axis_name)
                if ragged
                else halo_exchange(table, send_idx, halo_src, axis_name))
        full = jnp.concatenate([table, halo], axis=0)
        out = gat_pallas_pass(csrc, cld, cw, full.astype(jnp.float32),
                              cclasses, tbp, pemu, axis_name, b)
        return out[:, :fout], out[:, fout]
    if form != "split":
        raise ValueError(
            f"the Pallas slot pass takes the fused/split table forms, not "
            f"{form!r} (use_pallas_spmm gates the packed bf16 form out)")
    if ragged:
        # one two-lane ring per exchange, exactly _exchange_rows_scalar's
        # ragged wire; the concat exists only at round size
        pair = jnp.concatenate([p, s[:, None]], axis=-1)
        ring = pallas_ring_concat(pair, send_idx, comm[1], axis_name)
        full_p = jnp.concatenate([p, ring[:, :fout]], axis=0)
        full_u = jnp.concatenate([s, ring[:, fout]])
    else:
        # the dense split wire has ONE home — the slot-pass path's helper
        full_p, full_u = _exchange_rows_scalar(p, s, send_idx, halo_src,
                                               axis_name)
    num = gat_pallas_pass(csrc, cld, cw, full_p.astype(jnp.float32),
                          cclasses, tbp, pemu, axis_name, b)
    den = gat_pallas_pass(csrc, cld, cw,
                          full_u[:, None].astype(jnp.float32),
                          cclasses, tbp, pemu, axis_name, b)[:, 0]
    return num, den


def _use_packed(dtype, fout: int) -> bool:
    return dtype == jnp.bfloat16 and fout % 2 == 0


def gat_table_form(fout: int, compute_dtype=None) -> str:
    """The table form one GAT exchange ships at width ``fout`` —
    ``'fused'`` (one ``(·, fout+1)`` table), ``'split'`` (feature rows +
    scalar as separate dense dispatches / one two-lane ring) or
    ``'packed'`` (the bit-paired ``(·, fout/2+1)`` f32 table of the bf16
    compute path).  THE shared encoding of the layer's dispatch selection
    (``_gat_factored_fwd_core`` / ``_gat_layer_sym_bwd`` branch on it, both
    directions ship the same form) — the static-analysis collective census
    (``sgcn_tpu/analysis``) derives the expected per-exchange dispatch
    count and wire shape from it, so the forward cannot change form
    without the HLO audit noticing.  ``compute_dtype`` accepts the
    trainer-level string, a jnp/np dtype, or ``None`` (f32)."""
    bf16 = (compute_dtype is not None
            and jnp.dtype(compute_dtype) == jnp.bfloat16)
    if _use_packed(jnp.bfloat16 if bf16 else jnp.float32, fout):
        return "packed"
    return "fused" if _fused_form(fout) else "split"


def _gat_factored_fwd_core(w, a2, h, send_idx, halo_src, cell_idx, cell_w,
                           ctail_dst, ctail_src, ctail_w, row_valid, buckets,
                           axis_name, comm=COMM_A2A):
    b = h.shape[0]
    z = h @ w
    fout = z.shape[-1]
    z2 = score_project(z, a2)
    # global stabilizer over REAL rows only: pad rows carry z2 = 0, which
    # would floor the max at 0 and turn the underflow guard into an absolute
    # threshold instead of the documented relative-spread limit
    z2m = jnp.where(row_valid > 0, z2.astype(jnp.float32), -jnp.inf)
    # C shifts every score equally, so `out` is EXACTLY invariant to it
    # (∂out/∂C = 0 analytically) — stop_gradient both encodes that and lets
    # the general path autodiff through this core (pmax has no diff rule)
    cg = jax.lax.pmax(jax.lax.stop_gradient(jnp.max(z2m)), axis_name)
    u = jnp.exp(z2.astype(jnp.float32) - cg)         # (B,) in (0, 1]
    form = gat_table_form(fout, z.dtype)
    if _is_pallas_comm(comm):
        # VMEM-kernel slot pass: under the Pallas comm spec the cell_idx/
        # cell_w/ctail_dst slots carry the combined TILE arrays
        # (ptile_c[r]src / ptile_cw / ptile_cld — see gat_forward_local)
        p = u.astype(z.dtype)[:, None] * z
        num, den = _gat_pallas_aggregate(
            p, u.astype(z.dtype), fout, form, send_idx, halo_src,
            cell_idx, cell_w, ctail_dst, axis_name, comm)
    elif form == "packed":
        # bf16 compute: ONE gather per edge carries [u·z ‖ u] bit-packed
        p16 = u.astype(jnp.bfloat16)[:, None] * z
        num, den = _packed_aggregate(
            p16, u, fout, send_idx, halo_src, cell_idx, cell_w,
            ctail_dst, ctail_src, ctail_w, buckets, b, axis_name, comm)
    else:
        # table stays in the compute dtype (bf16 under mixed precision,
        # halving exchange bytes); u itself is f32 for stabilizer exactness
        p = u.astype(z.dtype)[:, None] * z           # (B, fout)
        if form == "fused":
            table = jnp.concatenate([p, u.astype(z.dtype)[:, None]], axis=-1)
            halo = _exchange_table(table, send_idx, halo_src, axis_name,
                                   comm)
            full = jnp.concatenate([table, halo], axis=0)   # (B+R, fout+1)
            num, den = _mask_slot_pass(full, fout, cell_idx, cell_w,
                                       ctail_dst, ctail_src, ctail_w,
                                       buckets, b)
        else:
            full_p, full_u = _exchange_rows_scalar(
                p, u.astype(z.dtype), send_idx, halo_src, axis_name, comm)
            num, den = _pair_slot_pass(full_p, full_u, fout, cell_idx,
                                       cell_w, ctail_dst, ctail_src,
                                       ctail_w, buckets, b)
    # max(den, tiny): u > 0 for every real edge, so this stays exact until
    # genuine f32 underflow (~68-nat spread); an ABSOLUTE eps would zero
    # rows whose neighborhoods sit merely ~20 nats below the global max.
    # 1e-30, not 1e-38: subnormals are flushed to zero on TPU/XLA, so a
    # sub-`tiny` guard silently becomes max(den, 0) -> 0/0 = NaN
    out = num / jnp.maximum(den, 1e-30)[:, None]
    return out, z, u, den, cg


def _gat_layer_sym_fwd(w, a1, a2, h, send_idx, halo_src, cell_idx, cell_w,
                       ctail_dst, ctail_src, ctail_w, row_valid, buckets,
                       axis_name, comm):
    out, _, _, den, cg = _gat_factored_fwd_core(
        w, a2, h, send_idx, halo_src, cell_idx, cell_w,
        ctail_dst, ctail_src, ctail_w, row_valid, buckets, axis_name, comm)
    # z and u are NOT stored: at products scale each stored (B, fout) array
    # is 1.25 GB and the fwd+bwd step measured 17.07 GB of HLO temps on a
    # 16 GB chip with them resident; the backward recomputes z = h·w (one
    # MXU matmul, ~0.4 ms at products scale — noise next to the gather
    # streams) and u from the stored scalar stabilizer cg.
    res = (w, a1, a2, h, cg, den, out, send_idx, halo_src, cell_idx,
           cell_w, ctail_dst, ctail_src, ctail_w)
    return out, res


def _gat_layer_sym_bwd(buckets, axis_name, comm, res, gbar):
    (w, a1, a2, h, cg, den, out, send_idx, halo_src, cell_idx, cell_w,
     ctail_dst, ctail_src, ctail_w) = res
    b = h.shape[0]
    z = h @ w                                        # remat (see fwd)
    fout = z.shape[-1]
    u = jnp.exp(score_project(z, a2).astype(jnp.float32) - cg)
    # out = N/(D+ε): cotangents of the two aggregations, per dst row
    dng = jnp.maximum(den, 1e-30)                    # same guard as forward
    dn = gbar / dng[:, None]                         # (B, fout)
    dd = -(gbar * out).sum(axis=-1) / dng            # (B,)
    # transpose of a symmetric pattern = the same aggregation: for src row
    # j, Σ_i mask_ij·dn_i over j's in-edge slots (aggregators of j) — the
    # backward's [ḡ/D ‖ −(ḡ·out)/D] table rides the SAME transport (comm)
    # as the forward's, so the ragged ring carries both directions
    form = gat_table_form(fout, z.dtype)
    if _is_pallas_comm(comm):
        # backward table rides the SAME transport and kernel as the
        # forward's (symmetric pattern: transpose = the same passes)
        dp, du_agg = _gat_pallas_aggregate(
            dn, dd, fout, form, send_idx, halo_src,
            cell_idx, cell_w, ctail_dst, axis_name, comm)
    elif form == "packed":
        dp, du_agg = _packed_aggregate(
            dn.astype(jnp.bfloat16), dd, fout, send_idx, halo_src,
            cell_idx, cell_w, ctail_dst, ctail_src, ctail_w, buckets, b,
            axis_name, comm)
    elif form == "fused":
        table = jnp.concatenate([dn, dd[:, None]], axis=-1)
        halo = _exchange_table(table, send_idx, halo_src, axis_name, comm)
        full = jnp.concatenate([table, halo], axis=0)
        dp, du_agg = _mask_slot_pass(full, fout, cell_idx, cell_w,
                                     ctail_dst, ctail_src, ctail_w,
                                     buckets, b)
    else:
        full_dn, full_dd = _exchange_rows_scalar(
            dn, dd, send_idx, halo_src, axis_name, comm)
        dp, du_agg = _pair_slot_pass(full_dn, full_dd, fout, cell_idx,
                                     cell_w, ctail_dst, ctail_src, ctail_w,
                                     buckets, b)
    # p = u·z, u = exp(z2 − C): chain rules (C is a pmax — constant a.e.)
    dz = u[:, None] * dp
    du = (dp * z).sum(axis=-1) + du_agg
    dz2 = u * du
    dz_total = dz + dz2[:, None] * a2[None, :]
    dh = dz_total @ w.T
    dW = h.T @ dz_total
    da2 = z.T @ dz2
    da1 = jnp.zeros_like(a1)       # softmax shift-invariance: exactly zero
    return (dW, da1, da2, dh,
            None, None, None, None, None, None, None, None)


gat_layer_sym.defvjp(_gat_layer_sym_fwd, _gat_layer_sym_bwd)


def estimate_gat_hbm_bytes(b: int, r: int, fin: int, widths: list[int],
                           nnz: int = 0, tail: int = 0,
                           dtype: str | None = None) -> int:
    """Per-chip peak-HBM model of one GAT fwd+bwd step, CALIBRATED on the
    round-3/4 measured capacity edges.

    ``r`` (true per-chip halo rows) is currently unused: every calibration
    point is single-chip (r=0), so a halo coefficient would be a guess.
    Callers pass the real value (``plan.halo_counts.max()``) so a fitted
    term can be added the moment multi-chip capacity data exists.

    f32 model ``7.08·B·(fin+Σfout) + 64·nnz + 90·tail`` reproduces the
    measured capacity points (products shape, 15.75 GB v5e):
      * BA 3-layer f32 (tail 29M): est 17.25 GB == the measured compile
        OOM ("Used 17.25G");
      * ER 3-layer f32 (tail 3.7M): est 15.13 GB — RUNS (15.9 s/epoch);
      * bf16-packed BA 3-layer: est 16.76 == measured compile OOM;
      * bf16-packed at B=1M: est 6.7 GB — ran (5.69 s, round 3).
    The per-tail-edge coefficient is large (90 B) because the chunked tail
    scans keep full-width gather temps and carries live; nnz carries the
    slot arrays + working set of the bucketed passes.

    KNOWN BLIND SPOT: the BA 2-layer f32 step estimates 15.2 GB (below the
    ER-3L running point), compiled — and then crashed the WORKER at
    runtime.  That crash is not separable by any capacity ranking
    (2-layer < ER-3L which runs), so it is likely a kernel fault, not
    capacity; a capacity guard cannot catch it.
    """
    ftot = fin + sum(widths)
    if dtype == "bfloat16":
        # packed path: fitted to the 16.76 GB BA-3L compile OOM and the
        # running 1M-vertex point (6.7 GB est) — the packed tables halve
        # but mixed precision double-books activations via casts, so the
        # per-row coefficient is NOT half of f32's
        return int(7.4 * b * ftot + 56 * nnz + 70 * tail)
    return int(7.08 * b * ftot + 64 * nnz + 90 * tail)


def check_gat_memory(b: int, r: int, fin: int, widths: list[int],
                     nnz: int = 0, tail: int = 0, dtype: str | None = None,
                     hbm_bytes: int | None = None) -> None:
    """Pre-flight guard for the GAT capacity edge (VERDICT r3): raise a
    clear error instead of letting the compile OOM or — worse — the TPU
    worker die at runtime (both observed; the 2-layer BA-products f32 step
    passed compile and then crashed the worker).

    The threshold is sharp by necessity — the largest RUNNING config
    estimates 15.13 GB of the chip's 15.75 GB and the smallest compile-OOM
    16.76 — so the guard raises above 0.97·HBM and tells the user the
    levers.  ``SGCN_HBM_BYTES`` overrides the detected/assumed HBM size
    (set it huge to bypass the guard for capacity experiments);
    ``SGCN_GAT_UNSAFE=1`` skips both guards outright."""
    if _os.environ.get("SGCN_GAT_UNSAFE") == "1":
        return
    if hbm_bytes is None:
        env = _os.environ.get("SGCN_HBM_BYTES")
        if env:
            hbm_bytes = int(env)
        else:
            try:
                hbm_bytes = jax.local_devices()[0].memory_stats()[
                    "bytes_limit"]
            except Exception:               # noqa: BLE001 — stats optional
                hbm_bytes = 16 * 1024**3    # v5e default
    # Secondary fence for the runtime-crash blind spot: the 2-layer BA
    # products step (tail 29M) passed both compile and this capacity model
    # and then KILLED the worker, while an 11.9M-tail run (B=1M) was fine —
    # so huge hub tails are fenced outright until the fault is understood.
    if tail > 20_000_000:
        raise RuntimeError(
            f"GAT hub tail of {tail / 1e6:.1f}M edges exceeds the measured "
            f"single-chip safety fence (20M): a products-scale run with a "
            f"29M-edge tail crashed the TPU worker AT RUNTIME despite "
            f"fitting the capacity model, while 11.9M ran fine.  Shard "
            f"over more chips (the per-chip tail shrinks ~k-fold) or set "
            f"SGCN_GAT_UNSAFE=1 to bypass both guards knowingly.")
    est = estimate_gat_hbm_bytes(b, r, fin, widths, nnz, tail, dtype)
    if est > 0.97 * hbm_bytes:
        raise RuntimeError(
            f"GAT at this shape is past the measured single-chip capacity "
            f"edge: estimated ~{est / 1024**3:.1f} GB of per-chip peak HBM "
            f"vs {hbm_bytes / 1024**3:.1f} GB available (guard at 97%; "
            f"calibrated on the measured compile-OOM points — see "
            f"estimate_gat_hbm_bytes).  Levers: shard over more chips "
            f"(per-chip B, nnz and tail all shrink ~k-fold), reduce "
            f"layers/width, or SGCN_HBM_BYTES to override.")


def gat_forward_local(
    params,
    h,
    pa,                           # plan arrays dict (GAT_PLAN_FIELDS)
    activation: str = "none",
    final_activation: str = "none",
    symmetric: bool = False,      # True selects the factored custom-backward
                                  # layer, which REQUIRES a symmetric edge
                                  # PATTERN (attention VALUES need not be)
    cell_buckets: tuple | None = None,   # static plan.cell_buckets
    comm_schedule: str = "a2a",   # static: 'a2a' (dense all_to_all) or
                                  # 'ragged' (per-round ppermute ring,
                                  # docs/comm_schedule.md)
    rr_sizes: tuple | None = None,  # static plan.rr_sizes (ragged)
    halo_r: int | None = None,      # static plan.r — halo table height
                                    # (ragged; not derivable from rhalo_dst)
    pallas_tb: int | None = None,   # static: VMEM-kernel tile height —
                                    # selects the Pallas slot pass
    pallas_emulate: bool = False,   # static: jnp emulation (off-TPU CI)
    pallas_cclasses: tuple | None = None,  # static: combined tile classes
                                    # ((T, Emax, kern), ...)
    axis_name: str = AXIS,
    halo_carry=None,              # stale-halo carries (trainer contract slot)
    collect_stabilizers: bool = False,  # static: also return the per-layer
                                  # softmax stabilizers cg (serving's
                                  # sub-graph precompute — see below)
):
    """Per-chip forward: stacked GAT layers.

    The reference stacks bare PGAT modules with no inter-layer nonlinearity
    (softmax-weighted aggregation is the nonlinearity, ``GPU/PGAT.py:202-213``);
    ``activation='elu'`` gives the standard GAT variant.

    GAT streams the combined ``[local; halo]`` bucketed edge layout (not the
    split overlap form): the edge-softmax normalizes each row over local AND
    halo edges together, so the aggregation genuinely depends on the
    exchange.

    ``halo_carry`` is the trainer's stale-halo carry slot (the pipelined
    exchange of ``ops.pspmm.pspmm_stale``).  GAT's exchange ships per-layer
    attention tables ``[Z_j, z2_j]`` whose staleness interacts with the
    edge-softmax normalization — carrying them is future work, so only the
    exact mode (``halo_carry=None``) is accepted here; the trainer gates
    ``halo_staleness`` to the GCN model accordingly.
    """
    if halo_carry is not None:
        raise NotImplementedError(
            "stale-halo pipelining is implemented for the GCN hot path only; "
            "run GAT with halo_staleness=0")
    if cell_buckets is None:
        raise ValueError("GAT forward needs the plan's static cell_buckets")
    if comm_schedule not in ("a2a", "ragged"):
        raise ValueError(f"unknown comm_schedule {comm_schedule!r} "
                         "(the trainer resolves 'auto' before the forward)")
    cell_arrays = (pa.get("cell_idx"), pa.get("cell_w"),
                   pa.get("ctail_dst"), pa.get("ctail_src"),
                   pa.get("ctail_w"))
    if pallas_tb is not None:
        # VMEM-kernel slot pass (schedule-agnostic, docs/comm_schedule.md):
        # the cell_idx/cell_w/ctail_dst slots of the layer signature carry
        # the combined TILE arrays; the tail slots ride unused dummies (the
        # tiles already cover every combined edge, hub tail included)
        if not symmetric:
            raise ValueError(
                "the Pallas GAT slot pass rides the symmetric custom "
                "backward; asymmetric plans run the slot-pass path")
        pspec = (int(pallas_tb), pallas_cclasses, bool(pallas_emulate))
        dummy_i = jnp.zeros((1,), jnp.int32)
        dummy_f = jnp.zeros((1,), jnp.float32)
        if comm_schedule == "ragged":
            if rr_sizes is None:
                raise ValueError(
                    "ragged Pallas GAT forward needs the plan's static "
                    "rr_sizes (CommPlan.ensure_ragged)")
            comm = ("ragged+pallas", tuple(rr_sizes), pspec)
            send_idx, halo_src = pa["rsend_idx"], dummy_i
            csrc = pa["ptile_crsrc"]
        else:
            comm = ("a2a+pallas", pspec)
            send_idx, halo_src = pa["send_idx"], pa["halo_src"]
            csrc = pa["ptile_csrc"]
        cell_arrays = (csrc, pa["ptile_cw"], pa["ptile_cld"],
                       dummy_i, dummy_f)
    elif comm_schedule == "ragged":
        # per-round ppermute ring: the attention tables ride the plan's
        # model-independent per-vertex layout (rsend_idx/rhalo_dst); same
        # math, f32 bit-identical (tests/test_gat_ragged.py)
        if not symmetric:
            raise ValueError(
                "comm_schedule='ragged' uses the symmetric custom backward "
                "(the gradient table rides the same ring); asymmetric "
                "plans run the a2a schedule")
        if rr_sizes is None or halo_r is None:
            raise ValueError(
                "ragged GAT forward needs the plan's static rr_sizes + "
                "halo table height r (CommPlan.ensure_ragged)")
        comm = ("ragged", tuple(rr_sizes), int(halo_r))
        send_idx, halo_src = pa["rsend_idx"], pa["rhalo_dst"]
    else:
        comm = COMM_A2A
        send_idx, halo_src = pa["send_idx"], pa["halo_src"]
    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    # symmetric edge pattern (undirected graphs): gather-only custom
    # backward; general pattern: autodiff through the streaming forward
    layer = gat_layer_sym if symmetric else gat_layer_local
    if symmetric:
        # custom_vjp cotangents must carry the same varying-axes type as
        # the primals; params arrive replicated (unvarying) but the bwd
        # produces per-chip PARTIAL grads (varying — the trainer completes
        # them with its psum), so cast the primals to varying first
        params = [
            jax.tree.map(lambda x: jax.lax.pcast(x, axis_name, to="varying"),
                         p) for p in params]
    cgs = []
    for i, p in enumerate(params):
        if collect_stabilizers:
            # the layer's own stabilizer, recomputed from the SAME
            # expressions _gat_factored_fwd_core evaluates (z = h·w,
            # z2 = score_project, real-row mask, global pmax) — XLA CSEs
            # the duplicate matmul away, and determinism makes the value
            # bit-equal to the one the layer uses internally.  Serving's
            # sub-graph forward (``serve/subgraph.py``) consumes these as
            # INPUTS: cg is a full-graph max, the one quantity a
            # receptive-set program cannot derive locally, but it is
            # constant per (params, features) — precomputed once per
            # weight swap, it keeps the compact u = exp(z2 − cg) values
            # bit-identical to the full program's.
            z2 = score_project(h @ p["w"], p["a2"])
            z2m = jnp.where(pa["row_valid"] > 0, z2.astype(jnp.float32),
                            -jnp.inf)
            cgs.append(jax.lax.pmax(jnp.max(z2m), axis_name))
        h = layer(
            p["w"], p["a1"], p["a2"], h,
            send_idx, halo_src,
            cell_arrays[0], cell_arrays[1],
            cell_arrays[2], cell_arrays[3], cell_arrays[4],
            pa["row_valid"], cell_buckets, axis_name, comm)
        h = fact(h) if i == nl - 1 else act(h)
        if i < nl - 1:
            # the softmax-weighted aggregation accumulates in f32 and
            # returns f32 rows; under mixed precision the NEXT layer must
            # see the compute dtype again or every layer past the first
            # silently runs the full-width f32 table forms — an f32 wire
            # under a bf16 request that no loss-parity test notices (found
            # by the sgcn_tpu/analysis wire audit; the byte gauges'
            # gat_exchange_lane_widths always assumed all layers narrow)
            h = h.astype(p["w"].dtype)
    if collect_stabilizers:
        return h, jnp.stack(cgs)
    return h
