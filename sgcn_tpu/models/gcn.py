"""Partitioned GCN model: per-chip layer stack over the pspmm op.

Reference model being matched (capability, not quirk-for-quirk):

  * ``PGCN(nn.Module)``: per layer, partitioned SpMM aggregation → bias-free
    Linear → ReLU (``GPU/PGCN.py:136-148``), log-softmax + NLL loss
    (``:204-205``), Glorot/averaged init (``:156-160``).
  * MPI flavor uses sigmoid activations and BCE (``Parallel-GCN/main.c:79-90,
    301-335``) — selectable here via ``activation='sigmoid'``.

Per-chip code: every function below runs inside ``shard_map``; weights are
replicated on every chip (the reference replicates W on every rank and
all-reduces dW — ``Parallel-GCN/main.c:422-430``, ``GPU/PGCN.py:150-154``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pspmm import (pspmm_ell_sym, pspmm_overlap, pspmm_ragged_sym,
                         pspmm_replica, pspmm_replica_partial,
                         pspmm_replica_ragged, pspmm_replica_stale,
                         pspmm_replica_stale_ragged, pspmm_stale,
                         pspmm_stale_ragged)
from ..parallel.mesh import AXIS
from .activations import get_activation

# plan arrays the GCN forward consumes (fullbatch ships exactly these).
# Symmetric Â takes the ELL + symmetric-backward fast path; general Â the
# split-COO overlap path whose backward is JAX's mechanical transpose.
# Under comm_schedule='ragged' the symmetric path swaps the dense a2a
# arrays for the per-round ppermute-ring layout (CommPlan.ensure_ragged).
GCN_PLAN_FIELDS_SYM = ("send_idx", "halo_src", "ell_idx", "ell_w",
                       "ltail_dst", "ltail_src", "ltail_w",
                       "hedge_dst", "hedge_src", "hedge_w")
GCN_PLAN_FIELDS_GEN = ("send_idx", "halo_src", "ledge_dst", "ledge_src",
                       "ledge_w", "hedge_dst", "hedge_src", "hedge_w")
GCN_PLAN_FIELDS_RAGGED = ("rsend_idx", "ell_idx", "ell_w",
                          "ltail_dst", "ltail_src", "ltail_w",
                          "redge_dst", "redge_src", "redge_w")


def gcn_plan_fields(plan):
    return GCN_PLAN_FIELDS_SYM if plan.symmetric else GCN_PLAN_FIELDS_GEN

# minimum input width (f32 elements) for the project-before-aggregate layer
# order to win: below this, random row gathers are HBM-access-bound, so
# shrinking the row does not shrink the SpMM time (measured on v5e)
PROJECT_FIRST_MIN_FIN = 256


def exchange_widths(fin: int, widths) -> list[int]:
    """Per-layer exchanged/aggregated row width (lanes) under the
    project-first rule of ``gcn_forward_local`` — THE shared encoding of
    that rule for every cost model (bench roofline, shard epoch model);
    change the forward's condition and this together."""
    out, f = [], fin
    for w in widths:
        out.append(w if (w < f and f >= PROJECT_FIRST_MIN_FIN) else f)
        f = w
    return out


def init_gcn_params(rng: jax.Array, dims: list[tuple[int, int]]):
    """Glorot-uniform weight list, one (fin, fout) matrix per layer.

    Reference init: Glorot uniform (``Parallel-GCN/main.c:584-594``); the
    torch flavor synchronizes via an allreduce average (``GPU/PGCN.py:156-160``)
    — here a shared seed makes every chip's copy identical by construction.
    """
    keys = jax.random.split(rng, len(dims))
    return [
        jax.nn.initializers.glorot_uniform()(k, (fin, fout), jnp.float32)
        for k, (fin, fout) in zip(keys, dims)
    ]


def gcn_forward_local(
    params,
    h,                      # (B, f_in) local feature rows
    pa,                     # plan arrays dict (gcn_plan_fields(plan))
    activation: str = "relu",
    final_activation: str = "none",
    symmetric: bool = False,
    ell_buckets: tuple | None = None,   # static plan.ell_buckets (sym path)
    pallas_tb: int | None = None,       # static: VMEM-kernel tile height —
                                        # selects the Pallas aggregator
    pallas_emulate: bool = False,       # static: jnp emulation (off-TPU shard_map CI)
    pallas_lclasses: tuple | None = None,  # static: degree-binned local
                                        # tile classes ((T,Emax,kern), ...)
    pallas_hclasses: tuple | None = None,  # static: halo tile classes
    halo_dtype: str | None = None,      # static: wire-only exchange dtype
                                        # ('bfloat16' halves ICI bytes;
                                        # tables/activations stay f32 —
                                        # ops/pspmm.py::halo_exchange)
    comm_schedule: str = "a2a",         # static: 'a2a' (dense all_to_all)
                                        # or 'ragged' (per-round ppermute
                                        # ring, docs/comm_schedule.md)
    rr_sizes: tuple | None = None,      # static plan.rr_sizes (ragged)
    rr_edge_sizes: tuple | None = None,  # static plan.rr_edge_sizes (ragged)
    axis_name: str = AXIS,
):
    """Per-chip forward: L × (pspmm ⊗ dense matmul → activation) → (B, nout).

    Aggregation uses ``pspmm_overlap`` — the split-edge-list formulation in
    which the local SpMM has no data dependence on the halo ``all_to_all``,
    so XLA overlaps communication with compute the way the MPI trainer's
    Irecv/compute/Waitany loop does (``Parallel-GCN/main.c:238-299``).

    Op order per layer exploits associativity: ``(Â·H)·W = Â·(H·W)``.  When
    the input is wide and the output narrower, the dense projection runs
    FIRST, so the halo exchange ships ``fout``-wide rows and the gather-bound
    SpMM touches ``fout``-wide features — both comm volume and the hot gather
    shrink by ``fout/fin`` (measured 2.7× per layer for cora-like 1433-wide
    inputs on v5e).  Below ~256 floats/row the gather is access-bound, not
    byte-bound (rows are shorter than an HBM burst), so narrowing does not
    pay and aggregate-first (the reference's fixed order,
    ``GPU/PGCN.py:144-148``) is kept.  Identical math either way.
    """
    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)

    if comm_schedule not in ("a2a", "ragged"):
        raise ValueError(f"unknown comm_schedule {comm_schedule!r} "
                         "(the trainer resolves 'auto' before the forward)")
    if symmetric and pallas_tb is not None and comm_schedule == "ragged":
        # schedule-agnostic Pallas aggregation: the ragged ring's receive
        # buffers feed the VMEM kernel directly (tile sources re-based to
        # ring positions at plan time — no HBM halo table; f32
        # bit-identical to the a2a-pallas flavor)
        from ..ops.pallas_spmm import pspmm_pallas_ragged

        if rr_sizes is None:
            raise ValueError(
                "ragged Pallas GCN forward needs the plan's static "
                "rr_sizes (CommPlan.ensure_ragged)")

        def agg(x):
            return pspmm_pallas_ragged(
                x, pa["rsend_idx"],
                pa["ptile_lsrc"], pa["ptile_lld"], pa["ptile_lw"],
                pa["ptile_hrsrc"], pa["ptile_hld"], pa["ptile_hw"],
                pallas_tb, pallas_lclasses, pallas_hclasses, rr_sizes,
                pallas_emulate, axis_name, halo_dtype)
    elif comm_schedule == "ragged":
        # ragged ppermute ring (docs/comm_schedule.md): per-round-sized
        # buffers replace the globally-padded a2a; same math, f32
        # bit-identical by construction (plan-time round-order edge sort)
        if not symmetric:
            raise ValueError(
                "comm_schedule='ragged' uses the symmetric custom backward "
                "(the gradient rides the same ring); asymmetric plans run "
                "the a2a schedule")
        if ell_buckets is None or rr_sizes is None or rr_edge_sizes is None:
            raise ValueError(
                "ragged GCN forward needs the plan's static ell_buckets + "
                "rr_sizes + rr_edge_sizes (CommPlan.ensure_ragged)")

        def agg(x):
            return pspmm_ragged_sym(
                x, pa["rsend_idx"], pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["redge_dst"], pa["redge_src"], pa["redge_w"],
                ell_buckets, rr_sizes, rr_edge_sizes, axis_name, halo_dtype)
    elif symmetric and pallas_tb is not None:
        # plan-driven kernel choice: per-chip tables fit the VMEM-resident
        # Pallas kernel (ops/pallas_spmm.py::use_pallas_spmm) — the regime
        # k-way sharding produces as k grows
        from ..ops.pallas_spmm import pspmm_pallas_sym

        def agg(x):
            return pspmm_pallas_sym(
                x, pa["send_idx"], pa["halo_src"],
                pa["ptile_lsrc"], pa["ptile_lld"], pa["ptile_lw"],
                pa["ptile_hsrc"], pa["ptile_hld"], pa["ptile_hw"],
                pallas_tb, pallas_lclasses, pallas_hclasses,
                pallas_emulate, axis_name, halo_dtype)
    elif symmetric:
        if ell_buckets is None:
            raise ValueError(
                "symmetric GCN forward needs the plan's static ell_buckets")

        def agg(x):
            return pspmm_ell_sym(
                x, pa["send_idx"], pa["halo_src"], pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                ell_buckets, axis_name, halo_dtype)
    else:
        def agg(x):
            return pspmm_overlap(
                x, pa["send_idx"], pa["halo_src"],
                pa["ledge_dst"], pa["ledge_src"], pa["ledge_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                axis_name=axis_name, halo_dtype=halo_dtype)

    for i, w in enumerate(params):
        if w.shape[1] < h.shape[1] and h.shape[1] >= PROJECT_FIRST_MIN_FIN:
            z = agg(h @ w)
        else:
            z = agg(h) @ w
        h = fact(z) if i == nl - 1 else act(z)
    return h


def gcn_forward_local_stale(
    params,
    h,                      # (B, f_in) local feature rows
    pa,                     # plan arrays dict (GCN_PLAN_FIELDS_SYM, or
    #                         STALE_PLAN_FIELDS_RAGGED under 'ragged')
    halos,                  # per-layer halo carries (step t−1): (R, f_ℓ)
    #                         dense, (ΣS_d, f_ℓ) round-major under 'ragged'
    ghalos,                 # per-layer gradient-halo carries (same shapes)
    bases,                  # per-layer delta baselines (or dummies):
    #                         (k, S, f_ℓ) dense, (ΣS_d, f_ℓ) under 'ragged'
    activation: str = "relu",
    final_activation: str = "none",
    ell_buckets: tuple | None = None,
    delta: bool = False,            # static: halo-delta caching on the wire
    wire_dtype: str | None = None,  # static: feature-wire dtype
    gwire_dtype: str | None = None,  # static: gradient-wire dtype
    fresh: bool = False,            # static: full-sync step (exact math)
    gauges: bool = False,           # static: emit per-layer drift gauges
    comm_schedule: str = "a2a",     # static: 'a2a' (pspmm_stale) or
    #                                 'ragged' (pspmm_stale_ragged — the
    #                                 composed mode, docs/comm_schedule.md)
    rr_sizes: tuple | None = None,  # static plan.rr_sizes (ragged)
    rr_edge_sizes: tuple | None = None,  # static plan.rr_edge_sizes (ragged)
    replica: bool = False,          # static: hot-halo replication composed
    #                                 in (--replica-budget + staleness —
    #                                 stale steps ship the SHRUNKEN nrep_*
    #                                 exchange; the carry subsumes the
    #                                 replica tables)
    nrep_rr_sizes: tuple | None = None,  # static plan.nrep_rr_sizes
    #                                      (ragged composed)
    axis_name: str = AXIS,
):
    """Per-chip forward under the pipelined stale-halo exchange.

    Same layer math and project-first scheduling as ``gcn_forward_local``,
    but every aggregation goes through a stale op: layer ℓ consumes
    ``halos[ℓ]`` (exchanged during step t−1) and issues step t's exchange
    with no in-step consumer.  ``comm_schedule`` selects the transport the
    carry rides: the dense a2a (``pspmm_stale``, ``(R, f)`` carries) or the
    per-round ppermute ring (``pspmm_stale_ragged``, round-major
    ``(Σ_d S_d, f)`` carries — the composed mode, in which the k−1 ring
    rounds leave the critical path too).  Returns
    ``(out, new_halos, new_bases)``; the gradient-halo carries come back as
    the ``ghalos`` cotangents of ``jax.value_and_grad`` (see
    ``pspmm_stale``).  Symmetric-Â plans only — the trainer gates on
    ``plan.symmetric``.

    ``gauges=True`` (the telemetry program the trainer compiles when a
    ``RunRecorder`` is attached) additionally returns a per-layer list of
    halo-delta quantization residuals: ``Σ (full − base_next)²`` over the
    send buffer (dense ``(k, S, f)``, ragged ``(Σ_d S_d, f)``), which is
    EXACTLY this step's wire rounding error ``(full − base) −
    quantize(full − base)`` since ``base_next = base + quantized_wire`` —
    zero when ``delta`` is off (the f32 wire is exact) and zero on sync
    steps (the re-base wire is full f32).  The extra send-buffer gather per
    layer exists only in the gauged program; the default hot path is
    untouched.
    """
    if ell_buckets is None:
        raise ValueError(
            "stale GCN forward needs the plan's static ell_buckets")
    if comm_schedule not in ("a2a", "ragged"):
        raise ValueError(f"unknown comm_schedule {comm_schedule!r} "
                         "(the trainer resolves 'auto' before the forward)")
    if comm_schedule == "ragged" and (rr_sizes is None
                                      or rr_edge_sizes is None):
        raise ValueError(
            "composed stale-ragged forward needs the plan's static "
            "rr_sizes + rr_edge_sizes (CommPlan.ensure_ragged)")
    if replica and delta:
        raise ValueError(
            "replica × stale × delta is deferred: the delta baseline and "
            "the replica carry would disagree on what a stale step ships "
            "(docs/replication.md)")
    if replica and comm_schedule == "ragged" and nrep_rr_sizes is None:
        raise ValueError(
            "composed replica-stale-ragged forward needs the plan's "
            "static nrep_rr_sizes (CommPlan.ensure_replicas after "
            "ensure_ragged)")
    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    new_halos, new_bases, qerrs = [], [], []
    for i, w in enumerate(params):
        # identical scheduling rule to gcn_forward_local: the carry widths
        # (plan.stale_carry_shapes → exchange_widths) encode the same rule
        project_first = (w.shape[1] < h.shape[1]
                         and h.shape[1] >= PROJECT_FIRST_MIN_FIN)
        x = (h @ w) if project_first else h
        if replica and comm_schedule == "ragged":
            z, hn, bn = pspmm_replica_stale_ragged(
                x, halos[i], ghalos[i], bases[i], pa["rsend_idx"],
                pa["nrep_rsend_idx"], pa["nrep_ring_dst"],
                pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["redge_dst"], pa["redge_src"], pa["redge_w"],
                ell_buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes,
                axis_name, wire_dtype, gwire_dtype, fresh)
        elif replica:
            z, hn, bn = pspmm_replica_stale(
                x, halos[i], ghalos[i], bases[i],
                pa["send_idx"], pa["halo_src"],
                pa["nrep_send_idx"], pa["nrep_halo_src"], pa["rep_slots"],
                pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                ell_buckets, axis_name, wire_dtype, gwire_dtype, fresh)
        elif comm_schedule == "ragged":
            z, hn, bn = pspmm_stale_ragged(
                x, halos[i], ghalos[i], bases[i], pa["rsend_idx"],
                pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["redge_dst"], pa["redge_src"], pa["redge_w"],
                ell_buckets, rr_sizes, rr_edge_sizes, axis_name, delta,
                wire_dtype, gwire_dtype, fresh)
        else:
            z, hn, bn = pspmm_stale(
                x, halos[i], ghalos[i], bases[i],
                pa["send_idx"], pa["halo_src"], pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                ell_buckets, axis_name, delta, wire_dtype, gwire_dtype,
                fresh)
        if gauges:
            if delta:
                sidx = (pa["rsend_idx"] if comm_schedule == "ragged"
                        else pa["send_idx"])
                full = jnp.take(x, sidx, axis=0)
                qerrs.append(jnp.sum(jnp.square(full - bn)))
            else:
                qerrs.append(jnp.zeros((), x.dtype))
        if not project_first:
            z = z @ w
        new_halos.append(hn)
        new_bases.append(bn)
        h = fact(z) if i == nl - 1 else act(z)
    if gauges:
        return h, new_halos, new_bases, qerrs
    return h, new_halos, new_bases


def gcn_forward_local_replica(
    params,
    h,                      # (B, f_in) local feature rows
    pa,                     # plan arrays dict (REPLICA_PLAN_FIELDS /
    #                         REPLICA_PLAN_FIELDS_RAGGED /
    #                         REPLICA_PARTIAL_PLAN_FIELDS)
    reps,                   # per-layer replica carries: (RP, f_ℓ)
    greps,                  # per-layer gradient-replica carries (same shapes)
    activation: str = "relu",
    final_activation: str = "none",
    ell_buckets: tuple | None = None,
    halo_dtype: str | None = None,  # static: wire-only exchange dtype
    fresh: bool = False,            # static: refresh (sync) step — the full
    #                                 exact exchange, replicas re-read fresh
    comm_schedule: str = "a2a",     # static: 'a2a' (pspmm_replica) or
    #                                 'ragged' (pspmm_replica_ragged)
    rr_sizes: tuple | None = None,       # static plan.rr_sizes (ragged)
    rr_edge_sizes: tuple | None = None,  # static plan.rr_edge_sizes (ragged)
    nrep_rr_sizes: tuple | None = None,  # static plan.nrep_rr_sizes (ragged)
    halo_r: int | None = None,           # static plan.r (ragged halo table)
    rep_base=None,          # per-layer sender-side refresh baselines
    #                         (RS, f_ℓ) — --refresh-band trainers only
    track_base: bool = False,       # static: thread the baselines through
    #                                 (returns (logits, reps, bases, nships))
    partial_step: bool = False,     # static: THIS program is the partial
    #                                 refresh step (pspmm_replica_partial)
    band: float = 0.0,              # static: relative per-row drift band
    axis_name: str = AXIS,
):
    """Per-chip forward under hot-halo replication (``--replica-budget``).

    Same layer math and project-first scheduling as ``gcn_forward_local``,
    but every aggregation goes through a replica-aware op: the plan's top-B
    boundary rows never ride the per-layer wire — their halo slots fill
    from ``reps[ℓ]``/``greps[ℓ]``, refreshed only on ``fresh`` (sync)
    steps, where the program is EXACTLY the exact path plus the replica
    gathers (the f32 bit-identity contract of ``--sync-every 1``).
    Returns ``(out, new_reps)``; the gradient-replica carries come back as
    the ``greps`` cotangents of ``jax.value_and_grad`` (see
    ``pspmm_replica``).  Symmetric-Â plans only — the trainer gates on
    ``plan.symmetric``.
    """
    if ell_buckets is None:
        raise ValueError(
            "replica GCN forward needs the plan's static ell_buckets")
    if comm_schedule not in ("a2a", "ragged"):
        raise ValueError(f"unknown comm_schedule {comm_schedule!r} "
                         "(the trainer resolves 'auto' before the forward)")
    if comm_schedule == "ragged" and (rr_sizes is None
                                      or rr_edge_sizes is None
                                      or nrep_rr_sizes is None
                                      or halo_r is None):
        raise ValueError(
            "ragged replica forward needs the plan's static rr_sizes + "
            "rr_edge_sizes + nrep_rr_sizes + halo table height "
            "(CommPlan.ensure_ragged + ensure_replicas)")
    if partial_step and (not track_base or comm_schedule != "a2a"):
        raise ValueError(
            "the partial refresh step needs the threaded baselines "
            "(track_base=True) and rides the dense a2a transport only "
            "(docs/replication.md)")
    act = get_activation(activation)
    fact = get_activation(final_activation)
    nl = len(params)
    new_reps, new_bases, nships = [], [], []
    for i, w in enumerate(params):
        # identical scheduling rule to gcn_forward_local: the carry widths
        # (plan.replica_carry_shapes → exchange_widths) encode the same rule
        project_first = (w.shape[1] < h.shape[1]
                         and h.shape[1] >= PROJECT_FIRST_MIN_FIN)
        x = (h @ w) if project_first else h
        if partial_step:
            z, rn, bn, ns = pspmm_replica_partial(
                x, reps[i], greps[i], rep_base[i],
                pa["nrep_send_idx"], pa["nrep_halo_src"], pa["rep_slots"],
                pa["rep_rows"], pa["rep_row_counts"],
                pa["ronly_send_idx"], pa["ronly_send_counts"],
                pa["ronly_base_pos"], pa["rep_recv_src"],
                pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                ell_buckets, axis_name, halo_dtype, band)
        elif comm_schedule == "ragged":
            z, rn = pspmm_replica_ragged(
                x, reps[i], greps[i], pa["rsend_idx"],
                pa["nrep_rsend_idx"], pa["nrep_rhalo_dst"], pa["rep_slots"],
                pa["rep_ring_pos"],
                pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                pa["redge_dst"], pa["redge_src"], pa["redge_w"],
                ell_buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes,
                halo_r, axis_name, halo_dtype, fresh)
        else:
            z, rn = pspmm_replica(
                x, reps[i], greps[i], pa["send_idx"], pa["halo_src"],
                pa["nrep_send_idx"], pa["nrep_halo_src"], pa["rep_slots"],
                pa["ell_idx"], pa["ell_w"],
                pa["ltail_dst"], pa["ltail_src"], pa["ltail_w"],
                pa["hedge_dst"], pa["hedge_src"], pa["hedge_w"],
                ell_buckets, axis_name, halo_dtype, fresh)
        if track_base and not partial_step:
            if fresh:
                # full refresh re-anchors the sender-side baseline to what
                # the CONSUMERS actually received — the wire-quantized
                # value under --halo-dtype (halo_exchange casts the
                # refresh's send buffer to the wire dtype and upcasts on
                # arrival), so sender baseline and every consumer replica
                # start the next partial-refresh epoch in exact lockstep
                # (an exact-f32 anchor would carry the quantization error
                # as permanent sender/receiver disagreement).
                # lax.stop_gradient: the baselines are carry state, not a
                # loss path (no cotangent into x)
                valid = (jnp.arange(pa["rep_rows"].shape[0])
                         < pa["rep_row_counts"])[:, None].astype(x.dtype)
                bn = jnp.take(x, pa["rep_rows"], axis=0)
                if halo_dtype is not None:
                    bn = bn.astype(halo_dtype).astype(x.dtype)
                bn = lax.stop_gradient(bn * valid)
            else:
                bn = rep_base[i]        # replica steps pass them through
            ns = jnp.zeros((), jnp.int32)
        if not project_first:
            z = z @ w
        new_reps.append(rn)
        if track_base:
            new_bases.append(bn)
            nships.append(ns)
        h = fact(z) if i == nl - 1 else act(z)
    if track_base:
        return h, new_reps, new_bases, nships
    return h, new_reps


def masked_softmax_xent_local(logits, labels, valid, axis_name: str = AXIS):
    """Global mean softmax cross-entropy over valid (non-padding) rows.

    Per-chip sums are ``psum``-reduced so every chip holds the same scalar —
    the analogue of the loss ``MPI_Reduce`` (``Parallel-GCN/main.c:318-323``)
    and ``dist.all_reduce`` of the loss (``GPU/PGCN.py:223-224``), but exact:
    a single global mean rather than a mean-of-per-rank-means.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    local = -jnp.sum(picked * valid)
    total = lax.psum(local, axis_name)
    count = lax.psum(jnp.sum(valid), axis_name)
    # a (mini-)batch can contain zero valid train rows globally; 0/0 would
    # poison the replicated weights with NaN for every later step
    return total / jnp.maximum(count, 1.0)


def masked_sigmoid_bce_local(logits, labels, valid, axis_name: str = AXIS):
    """Global mean elementwise sigmoid+BCE against one-hot targets — the MPI
    trainer's loss flavor (``Parallel-GCN/main.c:70-90``).

    The C stack's backward chain ``H=(H−Y)/[H(1−H)]; G=H⊙σ'(Z)`` collapses
    to exactly ``σ(z)−y`` (the BCE-with-logits gradient), so training under
    this loss reproduces grbgcn's update rule; the stable softplus form
    avoids materializing σ(z) in the loss itself.
    """
    y = jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)
    bce = (jnp.maximum(logits, 0) - logits * y
           + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    local = jnp.sum(bce * valid[:, None])
    total = lax.psum(local, axis_name)
    count = lax.psum(jnp.sum(valid), axis_name)
    return total / jnp.maximum(count, 1.0)


def masked_err_local(logits, labels, valid, axis_name: str = AXIS):
    """The MPI stack's printed ``err``: Σ −y·log σ(z) over valid rows, summed
    (not averaged) across ranks — ``T = −Y⊙log H; err = reduce(T)``
    (``Parallel-GCN/main.c:318-323``)."""
    logp = jax.nn.log_sigmoid(logits)
    picked = jnp.take_along_axis(
        logp, labels[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return lax.psum(-jnp.sum(picked * valid), axis_name)


def masked_accuracy_local(logits, labels, valid, axis_name: str = AXIS):
    """Global accuracy over valid rows (every chip gets the same scalar)."""
    pred = jnp.argmax(logits, axis=-1)
    hits = jnp.sum((pred == labels) * valid)
    count = lax.psum(jnp.sum(valid), axis_name)
    return lax.psum(hits, axis_name) / jnp.maximum(count, 1.0)
