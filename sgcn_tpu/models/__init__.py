from .gcn import init_gcn_params, gcn_forward_local, masked_softmax_xent_local
from .gat import init_gat_params, gat_forward_local, gat_layer_local, edge_softmax

__all__ = [
    "init_gcn_params", "gcn_forward_local", "masked_softmax_xent_local",
    "init_gat_params", "gat_forward_local", "gat_layer_local", "edge_softmax",
]
