from .gcn import init_gcn_params, gcn_forward_local, masked_softmax_xent_local

__all__ = ["init_gcn_params", "gcn_forward_local", "masked_softmax_xent_local"]
