from .pspmm import (halo_exchange, spmm_local, pspmm, pspmm_exchange,
                    pspmm_overlap)

__all__ = ["halo_exchange", "spmm_local", "pspmm", "pspmm_exchange",
           "pspmm_overlap"]
