from .pspmm import (halo_exchange, spmm_local, spmm_ell, pspmm,
                    pspmm_exchange, pspmm_overlap, pspmm_ell_sym,
                    pspmm_stale)

__all__ = ["halo_exchange", "spmm_local", "spmm_ell", "pspmm",
           "pspmm_exchange", "pspmm_overlap", "pspmm_ell_sym",
           "pspmm_stale"]
