"""Pallas TPU SpMM kernel (dst-tiled) — the hand-written alternative to the
XLA gather/segment-sum path in ``sgcn_tpu.ops.pspmm``.

Status and honest measurements (v5e; round-3 DIFFERENTIAL protocol — the
round-1/2 absolute numbers below carried a ~110 ms-per-dispatch tunnel
constant, see BASELINE.md): the graph SpMM is the framework's hot op and is
ROW-RATE-bound in XLA's gather (~350–460 Mrows/s regardless of index
pattern or row dtype; ~655 Mrows/s in-context for the shipped bucketed
slot-pass form, ~51 % of the 655 GB/s achieved stream ceiling).  Mosaic
exposes no batched-row DMA and its ``tpu.dynamic_gather`` is single-vreg,
so a Pallas kernel cannot beat the row rate from HBM; the round-3 speedups
came from gathering FEWER rows (bucketed width-major ELL, padding 1.71× →
1.08×, `sgcn_tpu.parallel.plan`).

This kernel holds the whole feature table VMEM-resident and accumulates per
edge from SMEM-prefetched indices — measured ~1.3× over the XLA path where
the table fits VMEM (≈ a few MB, n≈2k at f=128 on v5e); beyond VMEM the
Mosaic compile fails, so `spmm_pallas` is opt-in, not the default.  It is
kept as a first-class, tested op (interpret-mode CI + TPU parity): the
starting point for per-chip blocks small enough to pin in VMEM — which is
exactly what k-way partitioning produces as k grows (n/k ≈ 2k rows at
k≈64 for ogbn-arxiv, or any k with bf16 tables at n/k ≲ 16k).

Layout: edges are grouped into tiles of ``TB`` consecutive dst rows (plan
edge lists are dst-sorted already), each tile padded to ``Emax`` edges;
``build_dst_tiles`` converts any (edge_dst, edge_src, edge_w) triple.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def build_dst_tiles(edge_dst, edge_src, edge_w, num_rows: int, tb: int = 256):
    """Group dst-sorted edges into ceil(num_rows/tb) row tiles.

    Returns ``(tsrc, tld, tw, padded_rows)`` — the first three in the exact
    positional order ``spmm_pallas`` consumes, each (T, Emax); pad edges
    carry weight 0 and local dst tb-1.
    """
    edge_dst = np.asarray(edge_dst)
    edge_src = np.asarray(edge_src)
    edge_w = np.asarray(edge_w)
    t = -(-num_rows // tb)
    tile_of_edge = edge_dst // tb
    counts = np.bincount(tile_of_edge, minlength=t)
    emax = max(8, int(counts.max()))
    emax = -(-emax // 8) * 8
    tsrc = np.zeros((t, emax), np.int32)
    tw = np.zeros((t, emax), np.float32)
    tld = np.full((t, emax), tb - 1, np.int32)
    # edges are dst-sorted, so per-tile runs are contiguous
    starts = np.zeros(t + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    for i in range(t):
        s, e = starts[i], starts[i + 1]
        c = e - s
        tsrc[i, :c] = edge_src[s:e]
        tw[i, :c] = edge_w[s:e]
        tld[i, :c] = edge_dst[s:e] - i * tb
    return tsrc, tld, tw, t * tb


@partial(jax.jit, static_argnames=("tb", "interpret", "emulate", "vma"))
def spmm_pallas(tsrc, tld, tw, table, tb: int = 256, interpret: bool = False,
                emulate: bool = False, vma: tuple | None = None):
    """Â·table via the tiled Pallas kernel.

    Args:
      tsrc/tld/tw: (T, Emax) tile arrays from ``build_dst_tiles``.
      table: (N, f) feature rows (local ‖ halo), f a multiple of 128 ideally.
      interpret: run ``pl.pallas_call`` in interpreter mode (CPU CI) — the
        kernel BODY executes, off-TPU.
      emulate: skip pallas entirely and run an exact jnp emulation of the
        tile semantics — used ONLY by the shard_map path off-TPU, where
        pallas interpret mode trips a JAX vma-analysis bug in its internal
        scan.  Standalone CI keeps ``interpret=True`` so the kernel body and
        the vma-annotated out_shape stay covered off-TPU.
      vma: mesh axis names the output varies over — REQUIRED when called
        inside ``shard_map`` (pallas_call outputs must declare their
        varying axes under check_vma).

    Returns (T·tb, f); slice to the true row count.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, emax = tsrc.shape
    f = table.shape[-1]
    if emulate:
        gathered = jnp.take(table, tsrc.reshape(-1), axis=0) \
            * tw.reshape(-1)[:, None]
        flat_dst = (jnp.arange(t, dtype=jnp.int32)[:, None] * tb
                    + tld).reshape(-1)
        return jax.ops.segment_sum(gathered.astype(jnp.float32), flat_dst,
                                   num_segments=t * tb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,     # tsrc, tld, tw land in SMEM (scalar reads)
        grid=(t,),
        in_specs=[
            # whole feature table resident in VMEM — the kernel's premise
            # (and its size limit; see module docstring)
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tb, f), lambda i, *pf: (i, 0)),
        scratch_shapes=[pltpu.VMEM((tb, f), jnp.float32)],
    )

    def kernel(tsrc_pf, tld_pf, tw_pf, table_ref, out_ref, acc_ref):
        i = pl.program_id(0)
        acc_ref[:] = jnp.zeros_like(acc_ref)

        def body(e, _):
            src = tsrc_pf[i, e]
            ld = tld_pf[i, e]
            w = tw_pf[i, e]
            acc_ref[pl.ds(ld, 1), :] += w * table_ref[pl.ds(src, 1), :]
            return 0

        jax.lax.fori_loop(0, tsrc_pf.shape[1], body, 0)
        out_ref[:] = acc_ref[:]

    out_shape = (jax.ShapeDtypeStruct((t * tb, f), jnp.float32)
                 if vma is None else
                 jax.ShapeDtypeStruct((t * tb, f), jnp.float32,
                                      vma=frozenset(vma)))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(tsrc, tld, tw, table)


# ------------------------------------------------- plan-driven selection
# Per-table VMEM budget for auto-selecting this kernel.  The measured win
# over the XLA gather path is ~1.3× while the table is VMEM-resident at a
# few MB (round-1 measurement, module docstring); past VMEM the Mosaic
# compile fails outright.  SGCN_PALLAS_SPMM=1 forces the choice wherever it
# FITS (tests), =0 disables, unset/auto selects on TPU only (the win was
# measured there; CPU interpret mode is a correctness tool, not a fast
# path).  SGCN_PALLAS_VMEM overrides the byte budget.
import os as _os


def _pallas_table_budget() -> int:
    # read at call time so SGCN_PALLAS_VMEM set after import (monkeypatch,
    # programmatic use) takes effect — ADVICE r4
    return int(_os.environ.get("SGCN_PALLAS_VMEM", 4 * 1024 * 1024))


def pallas_spmm_fits(plan, fin: int, widths) -> bool:
    """True when every layer's per-chip [local] and [halo] feature tables
    fit the kernel's VMEM budget — the k-way-sharded regime the kernel was
    kept for (plan.b ≈ n/k shrinks as k grows)."""
    budget = _pallas_table_budget()
    fmax = max([fin, *widths])
    return (plan.b * fmax * 4 <= budget and plan.r * fmax * 4 <= budget)


def use_pallas_spmm(plan, fin: int, widths) -> bool:
    import jax as _jax

    env = _os.environ.get("SGCN_PALLAS_SPMM", "auto")
    if env == "0":
        return False
    if not (plan.symmetric and pallas_spmm_fits(plan, fin, widths)):
        return False
    return env == "1" or _jax.default_backend() == "tpu"


PALLAS_PLAN_FIELDS = ("send_idx", "halo_src", "ptile_lsrc", "ptile_lld",
                      "ptile_lw", "ptile_hsrc", "ptile_hld", "ptile_hw")


def _pspmm_pallas_once(h, send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw,
                       tb, emulate, axis_name, halo_dtype=None):
    from .pspmm import halo_exchange

    halo = halo_exchange(h, send_idx, halo_src, axis_name, halo_dtype)
    b = h.shape[0]
    local = spmm_pallas(lsrc, lld, lw, h.astype(jnp.float32), tb=tb,
                        emulate=emulate, vma=(axis_name,))[:b]
    remote = spmm_pallas(hsrc, hld, hw, halo.astype(jnp.float32), tb=tb,
                         emulate=emulate, vma=(axis_name,))[:b]
    return (local + remote).astype(h.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12))
def pspmm_pallas_sym(h, send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw,
                     tb=256, emulate=False, axis_name="v", halo_dtype=None):
    """``pspmm_ell_sym`` with the VMEM-resident Pallas kernel as the local
    aggregator — same overlap structure (local pass independent of the
    exchange), same symmetric gather-only backward.  Selected by the
    trainer via ``use_pallas_spmm`` when per-chip tables fit VMEM.
    ``emulate=True`` (the off-TPU shard_map path) swaps in the jnp
    emulation — see ``spmm_pallas``."""
    return _pspmm_pallas_once(h, send_idx, halo_src, lsrc, lld, lw,
                              hsrc, hld, hw, tb, emulate, axis_name,
                              halo_dtype)


def _pspmm_pallas_sym_fwd(h, send_idx, halo_src, lsrc, lld, lw, hsrc, hld,
                          hw, tb, emulate, axis_name, halo_dtype):
    out = _pspmm_pallas_once(h, send_idx, halo_src, lsrc, lld, lw,
                             hsrc, hld, hw, tb, emulate, axis_name,
                             halo_dtype)
    return out, (send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw)


def _pspmm_pallas_sym_bwd(tb, emulate, axis_name, halo_dtype, res, g):
    send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw = res
    gh = _pspmm_pallas_once(g, send_idx, halo_src, lsrc, lld, lw,
                            hsrc, hld, hw, tb, emulate, axis_name,
                            halo_dtype)
    return (gh,) + (None,) * 8


pspmm_pallas_sym.defvjp(_pspmm_pallas_sym_fwd, _pspmm_pallas_sym_bwd)
