"""Pallas TPU SpMM kernel (dst-tiled) — the hand-written alternative to the
XLA gather/segment-sum path in ``sgcn_tpu.ops.pspmm``.

Status and honest measurements (v5e; round-3 DIFFERENTIAL protocol — the
round-1/2 absolute numbers below carried a ~110 ms-per-dispatch tunnel
constant, see BASELINE.md): the graph SpMM is the framework's hot op and is
ROW-RATE-bound in XLA's gather (~350–460 Mrows/s regardless of index
pattern or row dtype; ~655 Mrows/s in-context for the shipped bucketed
slot-pass form, ~51 % of the 655 GB/s achieved stream ceiling).  Mosaic
exposes no batched-row DMA and its ``tpu.dynamic_gather`` is single-vreg,
so a Pallas kernel cannot beat the row rate from HBM; the round-3 speedups
came from gathering FEWER rows (bucketed width-major ELL, padding 1.71× →
1.08×, `sgcn_tpu.parallel.plan`).

This kernel holds the whole feature table VMEM-resident and accumulates per
edge from SMEM-prefetched indices — measured ~1.3× over the XLA path where
the table fits VMEM (≈ a few MB, n≈2k at f=128 on v5e); beyond VMEM the
Mosaic compile fails, so `spmm_pallas` is opt-in, not the default.  It is
kept as a first-class, tested op (interpret-mode CI + TPU parity): the
starting point for per-chip blocks small enough to pin in VMEM — which is
exactly what k-way partitioning produces as k grows (n/k ≈ 2k rows at
k≈64 for ogbn-arxiv, or any k with bf16 tables at n/k ≲ 16k).

Layout: edges are grouped into tiles of ``TB`` consecutive dst rows (plan
edge lists are dst-sorted already) and tiles into DEGREE-BINNED CLASSES
aligned with the plan's degree-bucket histogram (``ell_buckets`` /
``cell_buckets``): each class pads its tiles to its OWN ``Emax_c`` instead
of the hub tile's global max (Accel-GCN-style, arXiv:2308.11825 — a
one-hub BA graph no longer inflates every tile), and the kernel × schedule
choice is made PER CLASS (``choose_pallas_dispatch``): a hub class whose
serial per-tile edge chain exceeds ``pallas_emax_cap()`` stays on the XLA
gather/segment-sum form while the dense low-degree mass rides the VMEM
kernel.  The schedule-agnostic family:

  * ``pspmm_pallas_sym`` — dense-a2a exchange + class-dispatched kernels;
  * ``pspmm_pallas_ragged`` — the per-round ppermute ring's receive
    buffers feed the kernel DIRECTLY (the round-major concat is the
    kernel's halo-side table, tile sources re-based to ring positions at
    plan time, ``CommPlan.ensure_pallas_ragged_tiles``): no HBM halo
    table is ever materialized — the audit (``sgcn_tpu/analysis``) pins
    the absence of the ``(R, f)`` scatter per mode;
  * ``gat_pallas_pass`` — the GAT fused/split attention-table slot pass as
    a mask-weighted run of the same kernel over combined-edge tiles.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def tile_classes_from_buckets(buckets, num_rows: int, tb: int) -> tuple:
    """Per-class TILE counts, classes aligned to the degree-bucket
    histogram's row boundaries (rounded up to tile multiples) — the plan's
    existing degree profile drives the binning, so hub rows and the dense
    low-degree mass land in different classes and each class pads to its
    own ``Emax_c``.  Always covers all ``ceil(num_rows/tb)`` tiles."""
    t = max(1, -(-num_rows // tb))
    cuts = {t}
    cum = 0
    for nb, _wb in (buckets or ()):
        cum += int(nb)
        cuts.add(min(t, -(-cum // tb)))
    bounds = sorted(c for c in cuts if 0 < c <= t)
    out, prev = [], 0
    for c in bounds:
        if c > prev:
            out.append(c - prev)
            prev = c
    if prev < t:
        out.append(t - prev)
    return tuple(out)


def build_dst_tile_classes(edge_dst, edge_src, edge_w, num_rows: int,
                           tb: int, class_tiles) -> list:
    """Group dst-sorted edges into tiles of ``tb`` rows, binned into the
    given tile classes; per class, tiles pad to that class's own edge max.

    Returns a list over classes of ``(tsrc, tld, tw)`` — each
    ``(T_c, Emax_c)``, pad edges carrying weight 0 and local dst tb−1.
    The fill is ONE sliced numpy assignment per class (no per-tile Python
    loop — the O(T) interpreted loop of the original ``build_dst_tiles``
    was the preprocessing cost OGB-scale plans would pay).
    """
    edge_dst = np.asarray(edge_dst)
    edge_src = np.asarray(edge_src)
    edge_w = np.asarray(edge_w)
    t = int(sum(class_tiles))
    tile_of = edge_dst // tb
    counts = np.bincount(tile_of, minlength=t)
    starts = np.zeros(t + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    # position of each edge within its tile — edges are dst-sorted, so
    # per-tile runs are contiguous and this is pure arithmetic
    pos = np.arange(edge_dst.shape[0], dtype=np.int64) - starts[tile_of]
    out = []
    t0 = 0
    for tc in class_tiles:
        emax = max(8, int(counts[t0: t0 + tc].max()) if tc else 8)
        emax = -(-emax // 8) * 8
        tsrc = np.zeros((tc, emax), np.int32)
        tw = np.zeros((tc, emax), np.float32)
        tld = np.full((tc, emax), tb - 1, np.int32)
        sel = slice(int(starts[t0]), int(starts[t0 + tc]))
        ti = tile_of[sel] - t0
        pj = pos[sel]
        tsrc[ti, pj] = edge_src[sel]
        tw[ti, pj] = edge_w[sel]
        tld[ti, pj] = edge_dst[sel] - (ti + t0) * tb
        out.append((tsrc, tld, tw))
        t0 += tc
    return out


def build_dst_tiles(edge_dst, edge_src, edge_w, num_rows: int, tb: int = 256):
    """Group dst-sorted edges into ceil(num_rows/tb) row tiles (the single
    global-Emax layout — one class covering every tile).

    Returns ``(tsrc, tld, tw, padded_rows)`` — the first three in the exact
    positional order ``spmm_pallas`` consumes, each (T, Emax); pad edges
    carry weight 0 and local dst tb-1.  Output is bit-identical to the
    original per-tile Python loop (pinned by ``tests/test_pallas_spmm``).
    """
    t = max(1, -(-num_rows // tb))
    (tsrc, tld, tw), = build_dst_tile_classes(
        edge_dst, edge_src, edge_w, num_rows, tb, (t,))
    return tsrc, tld, tw, t * tb


@partial(jax.jit, static_argnames=("tb", "interpret", "emulate", "vma"))
def spmm_pallas(tsrc, tld, tw, table, tb: int = 256, interpret: bool = False,
                emulate: bool = False, vma: tuple | None = None):
    """Â·table via the tiled Pallas kernel.

    Args:
      tsrc/tld/tw: (T, Emax) tile arrays from ``build_dst_tiles``.
      table: (N, f) feature rows (local ‖ halo), f a multiple of 128
        ideally.  Held VMEM-resident in its OWN dtype (a bf16 table costs
        half the f32 budget — ``pallas_spmm_fits`` charges the true
        itemsize); accumulation is always f32.
      interpret: run ``pl.pallas_call`` in interpreter mode (CPU CI) — the
        kernel BODY executes, off-TPU.
      emulate: skip pallas entirely and run an exact jnp emulation of the
        tile semantics — used by the shard_map path off-TPU, where
        pallas interpret mode trips a JAX vma-analysis bug in its internal
        scan, and by tile classes whose kernel assignment is ``'ell'``
        (the XLA gather/segment-sum form IS this emulation).  Standalone
        CI keeps ``interpret=True`` so the kernel body and the
        vma-annotated out_shape stay covered off-TPU.
      vma: mesh axis names the output varies over — REQUIRED when called
        inside ``shard_map`` (pallas_call outputs must declare their
        varying axes under check_vma).

    Returns (T·tb, f) f32; slice to the true row count.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, emax = tsrc.shape
    f = table.shape[-1]
    if emulate:
        gathered = jnp.take(table, tsrc.reshape(-1), axis=0) \
            * tw.reshape(-1)[:, None]
        flat_dst = (jnp.arange(t, dtype=jnp.int32)[:, None] * tb
                    + tld).reshape(-1)
        return jax.ops.segment_sum(gathered.astype(jnp.float32), flat_dst,
                                   num_segments=t * tb)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,     # tsrc, tld, tw land in SMEM (scalar reads)
        grid=(t,),
        in_specs=[
            # whole feature table resident in VMEM — the kernel's premise
            # (and its size limit; see module docstring)
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tb, f), lambda i, *pf: (i, 0)),
        scratch_shapes=[pltpu.VMEM((tb, f), jnp.float32)],
    )

    def kernel(tsrc_pf, tld_pf, tw_pf, table_ref, out_ref, acc_ref):
        i = pl.program_id(0)
        acc_ref[:] = jnp.zeros_like(acc_ref)

        def body(e, _):
            src = tsrc_pf[i, e]
            ld = tld_pf[i, e]
            w = tw_pf[i, e]
            row = table_ref[pl.ds(src, 1), :].astype(jnp.float32)
            acc_ref[pl.ds(ld, 1), :] += w * row
            return 0

        jax.lax.fori_loop(0, tsrc_pf.shape[1], body, 0)
        out_ref[:] = acc_ref[:]

    out_shape = (jax.ShapeDtypeStruct((t * tb, f), jnp.float32)
                 if vma is None else
                 jax.ShapeDtypeStruct((t * tb, f), jnp.float32,
                                      vma=frozenset(vma)))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(tsrc, tld, tw, table)


def spmm_pallas_classes(flat_src, flat_ld, flat_w, table, classes,
                        tb: int, interpret: bool = False,
                        emulate: bool = False, vma: tuple | None = None):
    """Degree-binned kernel dispatch over flat tile-class arrays.

    ``classes = ((t_c, emax_c, kernel_c), ...)`` is the static per-class
    structure (``choose_pallas_dispatch``): class c owns the next
    ``t_c·emax_c`` flat slots, reshaped to its own ``(t_c, emax_c)`` pad,
    and runs the VMEM kernel (``'vmem'``) or the XLA gather/segment-sum
    form (``'ell'`` — hub classes whose serial per-tile chain would
    exceed the cap).  Per-row addition order is identical either way
    (edges stay in flat dst-sorted order; XLA's sorted scatter-add applies
    updates in order), so mixing kernels per class preserves the f32
    bit-parity contracts of the callers.  Returns ``(Σ t_c·tb, f)`` f32.
    """
    outs, off = [], 0
    for tc, ec, kern in classes:
        sl = slice(off, off + tc * ec)
        outs.append(spmm_pallas(
            flat_src[sl].reshape(tc, ec), flat_ld[sl].reshape(tc, ec),
            flat_w[sl].reshape(tc, ec), table, tb=tb, interpret=interpret,
            emulate=emulate or kern == "ell", vma=vma))
        off += tc * ec
    return outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)


# ------------------------------------------------- plan-driven selection
# Per-table VMEM budget for auto-selecting this kernel.  The measured win
# over the XLA gather path is ~1.3× while the table is VMEM-resident at a
# few MB (round-1 measurement, module docstring); past VMEM the Mosaic
# compile fails outright.  SGCN_PALLAS_SPMM=1 forces the choice wherever it
# FITS (tests), =0 disables, unset/auto selects on TPU only (the win was
# measured there; CPU interpret mode is a correctness tool, not a fast
# path).  SGCN_PALLAS_VMEM overrides the byte budget.
import os as _os


def _pallas_table_budget() -> int:
    # read at call time so SGCN_PALLAS_VMEM set after import (monkeypatch,
    # programmatic use) takes effect — ADVICE r4
    return int(_os.environ.get("SGCN_PALLAS_VMEM", 4 * 1024 * 1024))


def pallas_emax_cap() -> int:
    """Per-class serial-chain cap of the kernel dispatch: a tile class
    whose ``Emax_c`` exceeds this runs the XLA gather/segment-sum form
    instead (the kernel's fori_loop is SERIAL per tile, so one hub row's
    edge count is wall-clock; the gather form vectorizes over rows).
    ``SGCN_PALLAS_EMAX`` overrides (read at call time, ADVICE r4)."""
    return int(_os.environ.get("SGCN_PALLAS_EMAX", 8192))


def _table_itemsize(compute_dtype) -> int:
    if compute_dtype is None:
        return 4
    return int(jnp.dtype(compute_dtype).itemsize)


def _halo_table_rows(plan, schedule: str) -> int:
    """Rows of the halo-side kernel table: the dense halo pad for the a2a
    schedule, the ring's round-major receive concat (Σ_d S_d — it IS the
    table, no (R, f) halo buffer exists) for the ragged one."""
    if schedule == "ragged":
        try:
            sizes = (plan.rr_sizes if plan.rr_sizes is not None
                     else plan.ragged_round_sizes())
            return max(1, int(sum(sizes)))
        except ValueError:
            pass           # sliced plan: fall back to the dense halo pad
    return plan.r


def pallas_spmm_fits(plan, fin: int, widths, model: str = "gcn",
                     compute_dtype=None, schedule: str = "a2a") -> bool:
    """True when every layer's per-chip kernel tables fit the VMEM budget —
    the k-way-sharded regime the kernel was kept for (plan.b ≈ n/k shrinks
    as k grows).  Itemsize-aware: a bf16 ``compute_dtype`` table costs its
    true 2 bytes/elem, not the f32 4 the original check hard-coded (which
    charged bf16 tables double and refused plans that fit).  GCN charges
    the [local] and [halo] tables separately (two kernel passes); GAT the
    combined ``[local ‖ halo]`` (fout+1)-lane attention table (one pass).
    """
    budget = _pallas_table_budget()
    item = _table_itemsize(compute_dtype)
    if model == "gat":
        lanes = max(int(w) + 1 for w in widths)
        rows = plan.b + _halo_table_rows(plan, schedule)
        return rows * lanes * item <= budget
    fmax = max([fin, *widths])
    return (plan.b * fmax * item <= budget
            and _halo_table_rows(plan, schedule) * fmax * item <= budget)


def use_pallas_spmm(plan, fin: int, widths, model: str = "gcn",
                    compute_dtype=None, schedule: str = "a2a") -> bool:
    """THE kernel-selection rule (schedule- and model-agnostic): the VMEM
    aggregator fires for symmetric plans whose tables fit the budget, on
    either transport and for both models.  GAT under
    ``compute_dtype='bfloat16'`` is the one remaining carve-out: its
    packed wire form bit-pairs bf16 lanes into f32 words, which the
    kernel's f32 accumulate cannot consume without an in-kernel unpack —
    deferred, the slot-pass path serves it."""
    import jax as _jax

    env = _os.environ.get("SGCN_PALLAS_SPMM", "auto")
    if env == "0":
        return False
    if model == "gat" and compute_dtype is not None \
            and jnp.dtype(compute_dtype) == jnp.bfloat16:
        return False
    if not (plan.symmetric and pallas_spmm_fits(
            plan, fin, widths, model=model, compute_dtype=compute_dtype,
            schedule=schedule)):
        return False
    return env == "1" or _jax.default_backend() == "tpu"


def _assign_kernels(classes) -> tuple:
    """((t_c, emax_c), ...) → ((t_c, emax_c, 'vmem'|'ell'), ...): the
    per-class kernel choice (see ``pallas_emax_cap``)."""
    cap = pallas_emax_cap()
    return tuple((t, e, "vmem" if e <= cap else "ell") for t, e in classes)


def _classes_log(classes) -> list:
    return [{"tiles": t, "emax": e, "kernel": kern}
            for t, e, kern in classes]


def choose_pallas_dispatch(plan, model: str = "gcn",
                           schedule: str = "a2a", tb: int = 256,
                           decision: dict | None = None) -> dict:
    """Build the plan's tile-class layouts and assign a kernel per class —
    the degree-binned auto-dispatch of the ISSUE-15 tentpole.  Returns the
    static structures the forward threads through (``fwd_static``), and
    fills ``decision['pallas_dispatch']`` (landing in the run manifest's
    ``comm_schedule`` block) so the per-bucket choice is reconstructible
    from the run directory alone."""
    out: dict = {"pallas_tb": tb}
    if model == "gat":
        plan.ensure_pallas_cell_tiles(tb)
        if schedule == "ragged":
            plan.ensure_pallas_cell_ragged_tiles()
        out["pallas_cclasses"] = _assign_kernels(plan.pallas_cclasses)
        log = {"model": model, "schedule": schedule, "tb": tb,
               "emax_cap": pallas_emax_cap(),
               "combined": _classes_log(out["pallas_cclasses"])}
    else:
        plan.ensure_pallas_tiles(tb)
        if schedule == "ragged":
            plan.ensure_pallas_ragged_tiles()
        out["pallas_lclasses"] = _assign_kernels(plan.pallas_lclasses)
        out["pallas_hclasses"] = _assign_kernels(plan.pallas_hclasses)
        log = {"model": model, "schedule": schedule, "tb": tb,
               "emax_cap": pallas_emax_cap(),
               "local": _classes_log(out["pallas_lclasses"]),
               "halo": _classes_log(out["pallas_hclasses"])}
    if decision is not None:
        decision["pallas_dispatch"] = log
    return out


# plan arrays the Pallas GCN forwards ship.  The a2a flavor keeps the
# dense exchange layout + both tile-class families; the ragged flavor
# swaps (send_idx, halo_src, ptile_hsrc) for the ring layout: halo tiles
# re-based to RING positions (``ptile_hrsrc``) read the round-major
# receive concat directly — no (R, f) halo table exists in the program
# (the sgcn_tpu/analysis ``halo-materialization`` rule pins that).
PALLAS_PLAN_FIELDS = ("send_idx", "halo_src", "ptile_lsrc", "ptile_lld",
                      "ptile_lw", "ptile_hsrc", "ptile_hld", "ptile_hw")
PALLAS_PLAN_FIELDS_RAGGED = ("rsend_idx", "ptile_lsrc", "ptile_lld",
                             "ptile_lw", "ptile_hrsrc", "ptile_hld",
                             "ptile_hw")


def pallas_ring_concat(x, rsend_idx, rr_sizes, axis_name, halo_dtype=None):
    """The ragged ring's receive buffers, round-major-concatenated — the
    kernel's halo-side table.  Per live round (``ragged_live_rounds``, the
    shared elision rule) one ppermute ships the round's send gather;
    received buffers are NOT scattered into an (R, f) halo table — they
    concatenate in round order and the halo tile sources (re-based to ring
    positions at plan time) read them in place, so the fold happens inside
    the VMEM tile accumulator.  ``halo_dtype`` narrows the wire only."""
    from .pspmm import ppermute_or_identity, ragged_live_rounds

    segs = []
    live = ragged_live_rounds(rr_sizes)
    off = 0
    for d, sd in enumerate(rr_sizes, start=1):
        if d not in live:
            off += sd      # keep slice bookkeeping right under ANY rule
            continue
        buf = jnp.take(x, rsend_idx[off: off + sd], axis=0)
        if halo_dtype is not None:
            buf = buf.astype(halo_dtype)
        segs.append(ppermute_or_identity(buf, axis_name, d).astype(x.dtype))
        off += sd
    if not segs:                           # k=1 / all-empty ring
        return jnp.zeros((1, x.shape[-1]), x.dtype)
    return segs[0] if len(segs) == 1 else jnp.concatenate(segs)


def _pspmm_pallas_once(h, send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw,
                       tb, lclasses, hclasses, emulate, axis_name,
                       halo_dtype=None):
    from .pspmm import halo_exchange

    halo = halo_exchange(h, send_idx, halo_src, axis_name, halo_dtype)
    b = h.shape[0]
    # tile weights ride SMEM as f32 whatever the compute dtype; the tables
    # stay native (bf16 halves the VMEM bill — pallas_spmm_fits charges it)
    local = spmm_pallas_classes(lsrc, lld, lw.astype(jnp.float32), h,
                                lclasses, tb, emulate=emulate,
                                vma=(axis_name,))[:b]
    remote = spmm_pallas_classes(hsrc, hld, hw.astype(jnp.float32), halo,
                                 hclasses, tb, emulate=emulate,
                                 vma=(axis_name,))[:b]
    return (local + remote).astype(h.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13, 14))
def pspmm_pallas_sym(h, send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw,
                     tb=256, lclasses=((1, 8, "vmem"),),
                     hclasses=((1, 8, "vmem"),), emulate=False,
                     axis_name="v", halo_dtype=None):
    """``pspmm_ell_sym`` with the VMEM-resident Pallas kernel as the local
    aggregator — same overlap structure (local pass independent of the
    exchange), same symmetric gather-only backward.  Selected by the
    trainer via ``use_pallas_spmm`` when per-chip tables fit VMEM.
    ``lclasses``/``hclasses`` are the degree-binned per-class kernel
    dispatch (``choose_pallas_dispatch``); ``emulate=True`` (the off-TPU
    shard_map path) swaps in the jnp emulation — see ``spmm_pallas``."""
    return _pspmm_pallas_once(h, send_idx, halo_src, lsrc, lld, lw,
                              hsrc, hld, hw, tb, lclasses, hclasses,
                              emulate, axis_name, halo_dtype)


def _pspmm_pallas_sym_fwd(h, send_idx, halo_src, lsrc, lld, lw, hsrc, hld,
                          hw, tb, lclasses, hclasses, emulate, axis_name,
                          halo_dtype):
    out = _pspmm_pallas_once(h, send_idx, halo_src, lsrc, lld, lw,
                             hsrc, hld, hw, tb, lclasses, hclasses,
                             emulate, axis_name, halo_dtype)
    return out, (send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw)


def _pspmm_pallas_sym_bwd(tb, lclasses, hclasses, emulate, axis_name,
                          halo_dtype, res, g):
    send_idx, halo_src, lsrc, lld, lw, hsrc, hld, hw = res
    gh = _pspmm_pallas_once(g, send_idx, halo_src, lsrc, lld, lw,
                            hsrc, hld, hw, tb, lclasses, hclasses,
                            emulate, axis_name, halo_dtype)
    return (gh,) + (None,) * 8


pspmm_pallas_sym.defvjp(_pspmm_pallas_sym_fwd, _pspmm_pallas_sym_bwd)


def _pspmm_pallas_ragged_once(h, rsend_idx, lsrc, lld, lw, rsrc, rld, rw,
                              tb, lclasses, hclasses, rr_sizes, emulate,
                              axis_name, halo_dtype=None):
    ring = pallas_ring_concat(h, rsend_idx, rr_sizes, axis_name, halo_dtype)
    b = h.shape[0]
    local = spmm_pallas_classes(lsrc, lld, lw.astype(jnp.float32), h,
                                lclasses, tb, emulate=emulate,
                                vma=(axis_name,))[:b]
    # fold-as-you-arrive inside the kernel: the halo tiles (same tile/edge
    # order as the a2a flavor's, sources re-based to ring positions) read
    # the receive concat directly — per-row addition sequence identical to
    # the a2a-pallas halo pass, hence f32-bit-identical outputs
    remote = spmm_pallas_classes(rsrc, rld, rw.astype(jnp.float32), ring,
                                 hclasses, tb, emulate=emulate,
                                 vma=(axis_name,))[:b]
    return (local + remote).astype(h.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12, 13, 14))
def pspmm_pallas_ragged(h, rsend_idx, lsrc, lld, lw, rsrc, rld, rw,
                        tb=256, lclasses=((1, 8, "vmem"),),
                        hclasses=((1, 8, "vmem"),), rr_sizes=(),
                        emulate=False, axis_name="v", halo_dtype=None):
    """``pspmm_pallas_sym`` on the ragged ppermute ring: per-round-sized
    ppermutes (empty rounds elided per ``ragged_live_rounds``) whose
    receive buffers ARE the kernel's halo-side table — the ragged fold is
    fused into the VMEM tile accumulator instead of materializing the HBM
    halo table first (``pallas_ring_concat``).  f32-bit-identical to the
    a2a flavor (same tile fold order; tile sources re-based at plan time,
    ``CommPlan.ensure_pallas_ragged_tiles``); the symmetric custom
    backward reuses the forward form on ``g`` — the gradient rides the
    same ring at the same round sizes.  Symmetric-Â plans only."""
    return _pspmm_pallas_ragged_once(h, rsend_idx, lsrc, lld, lw,
                                     rsrc, rld, rw, tb, lclasses, hclasses,
                                     rr_sizes, emulate, axis_name,
                                     halo_dtype)


def _pspmm_pallas_ragged_fwd(h, rsend_idx, lsrc, lld, lw, rsrc, rld, rw,
                             tb, lclasses, hclasses, rr_sizes, emulate,
                             axis_name, halo_dtype):
    out = _pspmm_pallas_ragged_once(h, rsend_idx, lsrc, lld, lw,
                                    rsrc, rld, rw, tb, lclasses, hclasses,
                                    rr_sizes, emulate, axis_name,
                                    halo_dtype)
    return out, (rsend_idx, lsrc, lld, lw, rsrc, rld, rw)


def _pspmm_pallas_ragged_bwd(tb, lclasses, hclasses, rr_sizes, emulate,
                             axis_name, halo_dtype, res, g):
    rsend_idx, lsrc, lld, lw, rsrc, rld, rw = res
    gh = _pspmm_pallas_ragged_once(g, rsend_idx, lsrc, lld, lw,
                                   rsrc, rld, rw, tb, lclasses, hclasses,
                                   rr_sizes, emulate, axis_name, halo_dtype)
    return (gh,) + (None,) * 7


pspmm_pallas_ragged.defvjp(_pspmm_pallas_ragged_fwd,
                           _pspmm_pallas_ragged_bwd)


def gat_pallas_pass(csrc, cld, cw, table, cclasses, tb: int,
                    emulate: bool, axis_name: str, num_rows: int):
    """One GAT attention slot pass on the VMEM kernel: a MASK-weighted
    (``cw`` ∈ {0, 1}, built at plan time — attention ignores Â's values)
    run of the class-dispatched kernel over the combined-edge tiles.  The
    caller feeds whichever table the form ships — the fused
    ``[p ‖ u]`` ``(·, fout+1)`` table (both lanes aggregate in one pass:
    ``out[:, :fout]`` = N, ``out[:, fout]`` = D) or the split pair's
    feature / scalar tables in two calls.  ``cw`` arrives at whatever
    width the trainer shipped it (``ForwardSetup.ship_arrays`` narrows the
    0/1 tiles to int8 — the f32 form is real per-chip argument bytes at
    products scale) and upcasts here, like the GCN wrappers' ``lw``.
    Returns ``(num_rows, lanes)`` f32."""
    return spmm_pallas_classes(csrc, cld, cw.astype(jnp.float32), table,
                               cclasses, tb, emulate=emulate,
                               vma=(axis_name,))[:num_rows]
