"""Pallas TPU SpMM kernel (dst-tiled) — the hand-written alternative to the
XLA gather/segment-sum path in ``sgcn_tpu.ops.pspmm``.

Status and honest measurements (v5e; round-3 DIFFERENTIAL protocol — the
round-1/2 absolute numbers below carried a ~110 ms-per-dispatch tunnel
constant, see BASELINE.md): the graph SpMM is the framework's hot op and is
ROW-RATE-bound in XLA's gather (~350–460 Mrows/s regardless of index
pattern or row dtype; ~655 Mrows/s in-context for the shipped bucketed
slot-pass form, ~51 % of the 655 GB/s achieved stream ceiling).  Mosaic
exposes no batched-row DMA and its ``tpu.dynamic_gather`` is single-vreg,
so a Pallas kernel cannot beat the row rate from HBM; the round-3 speedups
came from gathering FEWER rows (bucketed width-major ELL, padding 1.71× →
1.08×, `sgcn_tpu.parallel.plan`).

This kernel holds the whole feature table VMEM-resident and accumulates per
edge from SMEM-prefetched indices — measured ~1.3× over the XLA path where
the table fits VMEM (≈ a few MB, n≈2k at f=128 on v5e); beyond VMEM the
Mosaic compile fails, so `spmm_pallas` is opt-in, not the default.  It is
kept as a first-class, tested op (interpret-mode CI + TPU parity): the
starting point for per-chip blocks small enough to pin in VMEM — which is
exactly what k-way partitioning produces as k grows (n/k ≈ 2k rows at
k≈64 for ogbn-arxiv, or any k with bf16 tables at n/k ≲ 16k).

Layout: edges are grouped into tiles of ``TB`` consecutive dst rows (plan
edge lists are dst-sorted already), each tile padded to ``Emax`` edges;
``build_dst_tiles`` converts any (edge_dst, edge_src, edge_w) triple.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def build_dst_tiles(edge_dst, edge_src, edge_w, num_rows: int, tb: int = 256):
    """Group dst-sorted edges into ceil(num_rows/tb) row tiles.

    Returns ``(tsrc, tld, tw, padded_rows)`` — the first three in the exact
    positional order ``spmm_pallas`` consumes, each (T, Emax); pad edges
    carry weight 0 and local dst tb-1.
    """
    edge_dst = np.asarray(edge_dst)
    edge_src = np.asarray(edge_src)
    edge_w = np.asarray(edge_w)
    t = -(-num_rows // tb)
    tile_of_edge = edge_dst // tb
    counts = np.bincount(tile_of_edge, minlength=t)
    emax = max(8, int(counts.max()))
    emax = -(-emax // 8) * 8
    tsrc = np.zeros((t, emax), np.int32)
    tw = np.zeros((t, emax), np.float32)
    tld = np.full((t, emax), tb - 1, np.int32)
    # edges are dst-sorted, so per-tile runs are contiguous
    starts = np.zeros(t + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    for i in range(t):
        s, e = starts[i], starts[i + 1]
        c = e - s
        tsrc[i, :c] = edge_src[s:e]
        tw[i, :c] = edge_w[s:e]
        tld[i, :c] = edge_dst[s:e] - i * tb
    return tsrc, tld, tw, t * tb


@partial(jax.jit, static_argnames=("tb", "interpret"))
def spmm_pallas(tsrc, tld, tw, table, tb: int = 256, interpret: bool = False):
    """Â·table via the tiled Pallas kernel.

    Args:
      tsrc/tld/tw: (T, Emax) tile arrays from ``build_dst_tiles``.
      table: (N, f) feature rows (local ‖ halo), f a multiple of 128 ideally.
      interpret: run in interpreter mode (CPU CI).

    Returns (T·tb, f); slice to the true row count.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t, emax = tsrc.shape
    f = table.shape[-1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,     # tsrc, tld, tw land in SMEM (scalar reads)
        grid=(t,),
        in_specs=[
            # whole feature table resident in VMEM — the kernel's premise
            # (and its size limit; see module docstring)
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tb, f), lambda i, *pf: (i, 0)),
        scratch_shapes=[pltpu.VMEM((tb, f), jnp.float32)],
    )

    def kernel(tsrc_pf, tld_pf, tw_pf, table_ref, out_ref, acc_ref):
        i = pl.program_id(0)
        acc_ref[:] = jnp.zeros_like(acc_ref)

        def body(e, _):
            src = tsrc_pf[i, e]
            ld = tld_pf[i, e]
            w = tw_pf[i, e]
            acc_ref[pl.ds(ld, 1), :] += w * table_ref[pl.ds(src, 1), :]
            return 0

        jax.lax.fori_loop(0, tsrc_pf.shape[1], body, 0)
        out_ref[:] = acc_ref[:]

    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t * tb, f), jnp.float32),
        interpret=interpret,
    )(tsrc, tld, tw, table)
