"""Partitioned SpMM with halo exchange — the framework's core distributed op.

Reference semantics being reproduced (TPU-first, not translated):

  * ``PSpMM`` autograd op: forward = halo exchange then local sparse matmul,
    backward = transposed matmul then the reversed exchange
    (``GPU/PGCN.py:121-134``; MPI flavor ``Parallel-GCN/main.c:233-316`` fwd,
    ``:338-438`` bwd).
  * The exchange ships owned boundary feature rows to exactly the chips whose
    local nonzeros reference them (``GPU/PGCN.py:85-119``).

TPU design:

  * every function here is **per-chip code** meant to run inside
    ``jax.shard_map`` over a 1D mesh axis (default ``'v'``);
  * the ragged P2P protocol becomes one static ``lax.all_to_all`` of a
    ``(k, S, f)`` buffer (S = padded per-peer bucket, see
    ``sgcn_tpu.parallel.plan``) — riding ICI, no ordering protocol needed;
  * local SpMM is a padded-edge-list segment-sum over the concatenated
    ``[local; halo]`` row table: dense gathers + one ``segment_sum``, which XLA
    fuses; padding edges carry weight 0 so they contribute nothing;
  * no ``custom_vjp`` is required: JAX transposes ``all_to_all`` to the reverse
    all_to_all, gathers to scatter-adds, and the segment-sum to a gather —
    yielding exactly the reference's swapped send/recv backward plan
    (``GPU/PGCN.py:93-97``) with ``Âᵀ`` (= ``Â``, symmetric) aggregation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.mesh import AXIS

# bound on the gather temps XLA's latency-hiding scheduler can keep live
# concurrently on the unrolled path (it overlaps up to ~16 slots); above it
# the bucketed slot reduce switches to a lax.scan over width slots, whose
# unroll factor is derived from _SCAN_LIVE_LIMIT so scan liveness stays
# bounded too
_CONCURRENT_TEMP_LIMIT = 3 * 1024**3 // 2
_SCHED_OVERLAP_SLOTS = 16
_SCAN_LIVE_LIMIT = 3 * 1024**3


def bucketed_slot_reduce(flat_idx, flat_w, buckets, contrib, init,
                         slot_bytes, scan_live_limit: int | None = None):
    """Σ over width slots of ``contrib(idx_t, w_t)`` per bucket — THE shared
    memory policy for every bucketed width-major layout (GCN SpMM, GAT
    attention passes).

    Unrolled while the scheduler's concurrent gather temps
    (``min(wb, _SCHED_OVERLAP_SLOTS) · slot_bytes(nb)``) fit the budget —
    each slot's gather fuses into its add; above it (ogbn-products-scale
    buckets: tens of multi-hundred-MB temps measured as 17+ GB of HLO temps
    on a 16 GB chip) a ``lax.scan`` serializes the slots.  The scan body is
    software-pipelined with the LARGEST unroll whose live temps still fit
    ``_SCAN_LIVE_LIMIT`` (≤4; measured 2.75 → 2.24 s/epoch at products
    scale going 1 → 4), so liveness stays provably bounded for every
    bucket shape.  The width-major flat layout makes each slot a
    contiguous ``(nb,)`` run, so the ``(wb, nb)`` reshape is free.

    ``contrib(idx (nb,), w (nb,)) -> pytree of (nb, ...) f32 arrays``;
    ``init(nb)`` builds the matching zero pytree; ``slot_bytes(nb)``
    estimates one slot's gather-temp bytes.  ``scan_live_limit`` lowers the
    scan-unroll liveness budget below the default — for callers that run
    SEVERAL slot reduces in one program (the GAT num/den passes): at
    products scale each pass unrolling to the full budget measured as the
    difference between fitting and a 264 MB OOM.  Returns the per-bucket
    reduced pytrees in bucket order.
    """
    live_limit = (_SCAN_LIVE_LIMIT if scan_live_limit is None
                  else scan_live_limit)
    outs = []
    off = 0
    for nb, wb in buckets:
        if (min(wb, _SCHED_OVERLAP_SLOTS) * slot_bytes(nb)
                <= _CONCURRENT_TEMP_LIMIT) or wb <= 2:
            acc = None
            for t in range(wb):
                seg = slice(off + t * nb, off + (t + 1) * nb)
                c = contrib(flat_idx[seg], flat_w[seg])
                acc = c if acc is None else jax.tree.map(jnp.add, acc, c)
        else:
            seg_i = flat_idx[off: off + nb * wb].reshape(wb, nb)
            seg_w = flat_w[off: off + nb * wb].reshape(wb, nb)
            # carry must match the body output's varying-axes type under
            # shard_map; adding 0·(an int32 element of the sharded index
            # array) marks the zeros varying — integer 0·x is exactly 0,
            # so (unlike 0·h[0,0]) an inf/NaN activation cannot poison it
            zero = seg_i[0, 0] * 0

            def body(carry, iw):
                i_t, w_t = iw
                return jax.tree.map(jnp.add, carry, contrib(i_t, w_t)), None

            acc0 = jax.tree.map(lambda x: x + zero.astype(x.dtype), init(nb))
            # cap 8 measured OOM at ogbn-products f32 (16.59/15.75 GB): the
            # budget models only slot temps, and the rest of the epoch
            # program leaves < _SCAN_LIVE_LIMIT of true headroom there
            unroll = max(1, min(4, live_limit // max(slot_bytes(nb), 1)))
            acc, _ = jax.lax.scan(body, acc0, (seg_i, seg_w), unroll=unroll)
        outs.append(acc)
        off += nb * wb
    return outs


def halo_exchange(h, send_idx, halo_src, axis_name: str = AXIS,
                  halo_dtype=None):
    """Exchange boundary rows; return this chip's halo row block.

    Args:
      h: (B, f) local feature rows.
      send_idx: (k, S) local row indices to ship to each peer (padded with 0 —
        receivers never gather padded slots).
      halo_src: (R,) flat indices into the received (k*S, f) buffer, in the
        plan's (owner, vertex-id) halo order.
      halo_dtype: optional narrower dtype for the WIRE only (the TPU-native
        lever the f32-only reference lacks): the send buffer is cast after
        the send-side gather and the halo rows are upcast back to ``h.dtype``
        after the halo gather, so exactly the ``all_to_all`` bytes halve
        (``'bfloat16'``) while every table, activation and accumulation
        stays f32.  Single-chip bf16 compute measured SLOWER (BASELINE.md:
        gathers are row-rate-bound and master-array casts are pure
        overhead); the wire is the one place narrow pays, because ICI
        bytes are the multi-chip bottleneck the partitioner minimizes.

    Returns:
      (R, f) halo rows (padding rows contain garbage; they are only referenced
      by weight-0 edges).
    """
    buf = jnp.take(h, send_idx, axis=0)                     # (k, S, f)
    if halo_dtype is not None:
        buf = buf.astype(halo_dtype)
    recv = a2a_or_identity(buf, axis_name)
    flat = recv.reshape(-1, h.shape[-1])                    # (k*S, f)
    return jnp.take(flat, halo_src, axis=0).astype(h.dtype)  # (R, f)


def ragged_live_rounds(rr_sizes) -> tuple:
    """Ring distances d (1-based) of the rounds with ``S_d > 0`` — exactly
    the rounds that EXIST in a traced ragged-schedule program (every loop
    below skips ``S_d = 0`` rounds, so they vanish at trace time: no
    ppermute, no buffer, no fold step).  The single shared encoding of that
    elision rule: the ragged ops here iterate it, and the static-analysis
    collective census (``sgcn_tpu/analysis``) derives its expected
    ``collective_permute`` count per exchange from it — change one without
    the other and the HLO audit fails the commit."""
    return tuple(d for d, sd in enumerate(rr_sizes, start=1) if sd > 0)


def ppermute_or_identity(buf, axis_name: str, d: int):
    """Round-``d`` ring shift of the ragged schedule: chip ``p`` sends
    ``buf`` to chip ``(p+d) % k`` (so each chip receives from ``(p−d) % k``)
    via ``lax.ppermute``.  Degrades to an ``optimization_barrier``-pinned
    identity on a size-1 mesh axis under the SAME fidelity contract as
    ``a2a_or_identity``: the shard-proxy measurement needs the send-side
    gather to stay materialized exactly as on a real k-chip mesh."""
    k = lax.axis_size(axis_name)
    if k == 1:
        (recv,) = lax.optimization_barrier((buf,))
        return recv
    return lax.ppermute(buf, axis_name,
                        perm=[(p, (p + d) % k) for p in range(k)])


def halo_exchange_ragged_multi(parts, rsend_idx, rhalo_dst, rr_sizes, r: int,
                               axis_name: str = AXIS, halo_dtype=None):
    """Ragged ppermute-ring exchange of SEVERAL row tables in ONE ring.

    The table-width-agnostic core of the ragged schedule: ``parts`` is a
    tuple of per-vertex arrays — each ``(B, d_i)`` (or ``(B,)`` for a scalar
    lane) — and every live round ships ONE concatenated
    ``(S_d, Σ d_i)``-lane buffer, so a feature table and its companion
    scalar (the GAT split path's ``(p, u)`` pair, two dense dispatches per
    exchange on the a2a schedule) cost a single ppermute per round.  The
    per-vertex send/receive layout (``rsend_idx``/``rhalo_dst``,
    ``CommPlan.ensure_ragged``) is model-independent: round ``d`` carries
    chip p → (p+d)%k in a buffer statically sized to that round's own max
    send count (``rr_sizes[d-1]``), rounds with S_d = 0 vanish at trace
    time, and received rows scatter (``.set``, each slot written exactly
    once) into their contiguous per-owner halo slice — so every part's halo
    table holds bit-identical rows to the dense exchange's, whatever its
    lane count.  Padding receive slots target row ``r`` and are dropped;
    padding halo rows therefore hold zeros (the dense exchange leaves
    garbage there — both are only ever referenced by weight-0/masked
    slots).  ``halo_dtype`` narrows the whole concatenated wire buffer
    only; mixed part dtypes ride the promoted dtype and are cast back per
    part on arrival.

    Returns a tuple of per-part halo tables, shaped ``(r,) + part.shape[1:]``.
    """
    lanes = [p.shape[1] if p.ndim == 2 else 1 for p in parts]
    halos = [jnp.zeros((r,) + p.shape[1:], p.dtype) for p in parts]
    live = ragged_live_rounds(rr_sizes)
    off = 0
    for d, sd in enumerate(rr_sizes, start=1):
        if d not in live:
            off += sd      # keep slice bookkeeping right under ANY rule
            continue
        idx = rsend_idx[off: off + sd]
        bufs = [jnp.take(p, idx, axis=0) for p in parts]
        if len(parts) == 1:
            buf = bufs[0]
        else:
            buf = jnp.concatenate(
                [b.reshape(sd, ln) for b, ln in zip(bufs, lanes)], axis=-1)
        if halo_dtype is not None:
            buf = buf.astype(halo_dtype)
        recv = ppermute_or_identity(buf, axis_name, d)
        dst = rhalo_dst[off: off + sd]
        col = 0
        for i, (p, ln) in enumerate(zip(parts, lanes)):
            seg = recv if len(parts) == 1 else recv[:, col: col + ln]
            seg = seg.reshape((sd,) + p.shape[1:]).astype(p.dtype)
            halos[i] = halos[i].at[dst].set(seg, mode="drop")
            col += ln
        off += sd
    return tuple(halos)


def halo_exchange_ragged(h, rsend_idx, rhalo_dst, rr_sizes, r: int,
                         axis_name: str = AXIS, halo_dtype=None):
    """Ragged ppermute-ring halo exchange; returns the (R, f) halo block.

    The plan-driven replacement for ``halo_exchange``'s dense all_to_all:
    the single-table form of ``halo_exchange_ragged_multi`` — per-round pad,
    not global pad, so the wire carries Σ_d k·S_d rows instead of k²·S.
    ``halo_dtype`` narrows the wire only, exactly like the dense exchange's
    lever.

    Args:
      h: (B, f) local feature rows.
      rsend_idx: (ΣS_d,) per-round send gather rows (round-major flat).
      rhalo_dst: (ΣS_d,) halo rank of each receive slot (``r`` = padding).
      rr_sizes: static per-round sizes, length k−1.
      r: halo table height.
    """
    (halo,) = halo_exchange_ragged_multi(
        (h,), rsend_idx, rhalo_dst, rr_sizes, r, axis_name, halo_dtype)
    return halo


def a2a_or_identity(buf, axis_name: str):
    """``lax.all_to_all`` of a per-peer-bucketed buffer, degrading to an
    identity on a size-1 mesh axis (jax's all_to_all rejects
    split_dim != axis_size).  The identity is pinned with an
    ``optimization_barrier``: XLA would otherwise fuse the send-side gather
    into the halo gather — fine for a true k=1 plan (empty halo), but the
    shard-proxy measurement (``sgcn_tpu.parallel.proxy``) runs a k>1 chip's
    program on one device and needs the send-buffer materialization to
    stay, exactly as on a real k-chip mesh.  Shared by every exchange
    (feature rows here, the GAT scalar buffer in ``models/gat.py``) so
    proxy fidelity has one home."""
    if lax.axis_size(axis_name) == 1:
        (recv,) = lax.optimization_barrier((buf,))
        return recv
    return lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0)


def spmm_local(edge_dst, edge_src, edge_w, table, num_rows: int):
    """Masked segment-sum SpMM: ``out[i] = Σ_e w_e · table[src_e]`` for dst_e=i.

    ``table`` is the concatenated ``[local (B); halo (R)]`` row block. Edges are
    sorted by dst at plan time. Mirrors the reference's accumulate-as-you-go
    structure ``AH = Â_local·H + Σ_r Â·Ĥ_r`` (``Parallel-GCN/main.c:269-299``)
    collapsed into one fused gather/segment-sum.
    """
    gathered = jnp.take(table, edge_src, axis=0) * edge_w[:, None]
    return jax.ops.segment_sum(
        gathered, edge_dst, num_segments=num_rows, indices_are_sorted=True
    )


def pspmm(h, halo, edge_dst, edge_src, edge_w):
    """Aggregate with an already-exchanged halo: ``Â_local · [h; halo]``."""
    table = jnp.concatenate([h, halo], axis=0)
    return spmm_local(edge_dst, edge_src, edge_w, table, h.shape[0])


def pspmm_exchange(h, send_idx, halo_src, edge_dst, edge_src, edge_w,
                   axis_name: str = AXIS):
    """``PSpMM`` over the combined ``[h; halo]`` edge list.

    Every edge's gather depends on the exchanged halo, so XLA cannot start
    the SpMM until the ``all_to_all`` lands.  Kept for ops that genuinely
    need the combined table (the GAT edge-softmax normalizes over local and
    halo edges together); the GCN hot path uses ``pspmm_overlap``.
    """
    halo = halo_exchange(h, send_idx, halo_src, axis_name)
    return pspmm(h, halo, edge_dst, edge_src, edge_w)


def pspmm_overlap(h, send_idx, halo_src,
                  ledge_dst, ledge_src, ledge_w,
                  hedge_dst, hedge_src, hedge_w,
                  axis_name: str = AXIS, halo_dtype=None):
    """``PSpMM`` with the reference's comm/compute-overlap structure.

    The edge list is split at plan time by source locality
    (``sgcn_tpu.parallel.plan``): the local-src segment-sum reads only ``h``
    and therefore has no data dependence on the ``all_to_all`` — XLA is free
    to run it while boundary rows are in flight — after which the halo-src
    segment-sum folds in the remote contribution.  This is exactly
    ``AH = Â·H_local + Σ_r Â·Ĥ_r`` of the MPI trainer
    (``Parallel-GCN/main.c:238-299``: post Irecv, compute local SpMM, fold
    arrivals), expressed as a dependence structure instead of explicit waits.

    Under JAX transposition the backward keeps the same split: the gradient
    all_to_all overlaps with the local-src transpose-SpMM.
    """
    halo = halo_exchange(h, send_idx, halo_src, axis_name, halo_dtype)
    # no data dependence on `halo` — XLA overlaps this with the exchange
    local = spmm_local(ledge_dst, ledge_src, ledge_w, h, h.shape[0])
    remote = spmm_local(hedge_dst, hedge_src, hedge_w, halo, h.shape[0])
    return local + remote


def spmm_ell(ell_idx, ell_w, tail_dst, tail_src, tail_w, h, buckets):
    """Local SpMM in bucketed-ELL layout + COO overflow tail.

    ``buckets = ((nb, wb), ...)`` is the plan's static degree-bucket
    structure (``sgcn_tpu.parallel.plan``): the next ``nb`` output rows each
    own ``wb`` flat slots of ``ell_idx``/``ell_w``, stored WIDTH-MAJOR (slot
    t of the bucket's rows is one contiguous (nb,) run).  Per slot this is
    one fused gather·weight + accumulate — no (nb, wb, f) intermediate
    exists, which is the point: the row-major gather+reduce form paid
    ~17 ms/epoch of XLA "data formatting" relayouts at ogbn-arxiv scale
    (round-3 trace), and the unrolled per-slot form measured 444 vs 367
    Mrows/s isolated.  The v5e gather is row-rate-bound (pattern/dtype-
    independent), so the bucketed layout's ~1.1-1.2× padding vs
    single-width ELL's ~1.7× is a direct time saving.
    """
    if sum(nb * wb for nb, wb in buckets) != ell_idx.shape[0]:
        raise ValueError(
            f"bucket structure {buckets} does not cover the flat ELL arrays "
            f"({ell_idx.shape[0]} slots) — pass the owning plan's ell_buckets")
    f = h.shape[-1]
    # slot temps are budgeted at 4 B/elem even when h is bf16 — deliberately
    # NOT promote(h, ell_w).itemsize: budgeting with the true bf16 itemsize
    # re-engages the unrolled branch for twice as many buckets, and the
    # resulting program measured 23.2 GB of HLO temps on a 15.75 GB chip at
    # ogbn-products scale (mixed precision already double-books HBM with the
    # bf16 casts of every master-f32 array, so the slot budget must stay
    # conservative; the f32-equivalent budget is that 2× safety factor)
    outs = bucketed_slot_reduce(
        ell_idx, ell_w, buckets,
        contrib=lambda idx, w: jnp.take(h, idx, axis=0) * w[:, None],
        init=lambda nb: jnp.zeros((nb, f), h.dtype),
        slot_bytes=lambda nb: nb * f * 4)
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    tg = jnp.take(h, tail_src, axis=0) * tail_w[:, None]
    # the tail is dst-sorted by construction (plan edges are dst-sorted and
    # padding appends dst=b-1), so a sorted segment_sum beats the scatter-add
    # form: measured 58.6 -> 54.0 ms/epoch (-8%) at ogbn-arxiv shape on a
    # power-law (BA) graph where hub spill puts 8% of edges in the tail
    # (no-op on ER benches, whose tails are empty)
    tsum = jax.ops.segment_sum(tg, tail_dst, num_segments=out.shape[0],
                               indices_are_sorted=True)
    return out + tsum


def _pspmm_ell_once(h, send_idx, halo_src, ell_idx, ell_w,
                    ltail_dst, ltail_src, ltail_w,
                    hedge_dst, hedge_src, hedge_w, buckets, axis_name,
                    halo_dtype=None):
    halo = halo_exchange(h, send_idx, halo_src, axis_name, halo_dtype)
    # local ELL aggregation has no data dependence on the exchange (overlap)
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, h, buckets)
    remote = spmm_local(hedge_dst, hedge_src, hedge_w, halo, h.shape[0])
    return local + remote


@partial(jax.custom_vjp, nondiff_argnums=(11, 12, 13))
def pspmm_ell_sym(h, send_idx, halo_src, ell_idx, ell_w,
                  ltail_dst, ltail_src, ltail_w,
                  hedge_dst, hedge_src, hedge_w, buckets,
                  axis_name=AXIS, halo_dtype=None):
    """``PSpMM`` for a SYMMETRIC Â: ELL local aggregation + overlap structure,
    with a custom backward that reuses the forward form.

    JAX's mechanical transpose of the gather is a scatter-add, ~3.6× slower
    than the gather form on v5e; for symmetric Â (the reference's standing
    assumption — its backward applies A, not Aᵀ,
    ``Parallel-GCN/main.c:374-404``) the gradient is just ``Â·g``, computed
    exactly like the forward, including the same halo exchange (the
    symmetric pattern makes the reversed comm identical to the forward
    comm).  Measured fwd+bwd at ogbn-arxiv scale: 20 ms vs 55 ms for the
    COO pair, grads bit-identical in f32 tolerance.

    Only valid when ``plan.symmetric``; callers must fall back to
    ``pspmm_overlap`` otherwise.
    """
    return _pspmm_ell_once(h, send_idx, halo_src, ell_idx, ell_w,
                           ltail_dst, ltail_src, ltail_w,
                           hedge_dst, hedge_src, hedge_w, buckets, axis_name,
                           halo_dtype)


def _pspmm_ell_sym_fwd(h, send_idx, halo_src, ell_idx, ell_w,
                       ltail_dst, ltail_src, ltail_w,
                       hedge_dst, hedge_src, hedge_w, buckets, axis_name,
                       halo_dtype):
    out = _pspmm_ell_once(h, send_idx, halo_src, ell_idx, ell_w,
                          ltail_dst, ltail_src, ltail_w,
                          hedge_dst, hedge_src, hedge_w, buckets, axis_name,
                          halo_dtype)
    res = (send_idx, halo_src, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
           hedge_dst, hedge_src, hedge_w)
    return out, res


def _pspmm_ell_sym_bwd(buckets, axis_name, halo_dtype, res, g):
    (send_idx, halo_src, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
     hedge_dst, hedge_src, hedge_w) = res
    # the gradient exchange rides the same narrow wire as the forward's —
    # both directions of ICI traffic halve under halo_dtype='bfloat16'
    gh = _pspmm_ell_once(g, send_idx, halo_src, ell_idx, ell_w,
                         ltail_dst, ltail_src, ltail_w,
                         hedge_dst, hedge_src, hedge_w, buckets, axis_name,
                         halo_dtype)
    zeros = [None] * 10
    return (gh, *zeros)


pspmm_ell_sym.defvjp(_pspmm_ell_sym_fwd, _pspmm_ell_sym_bwd)


# -------------------------------------------------------------------- ragged
# Ragged ppermute-ring PSpMM: the per-round exchange of halo_exchange_ragged
# with FOLD-AS-YOU-ARRIVE remote aggregation — round d's halo-src edges
# (split per owner at plan time, src re-based to the round's receive buffer)
# scatter-add straight into the output accumulator, so each round's remote
# contribution folds while later rounds are still in flight: the TPU
# dependence-structure expression of the reference's post-Irecv
# compute-local / accumulate-arrivals loop (Parallel-GCN/main.c:238-299).
#
# f32 bit-parity with the dense schedule is STRUCTURAL, not approximate: the
# plan sorts the dense hedge family by (dst, round, recv-pos), and XLA's
# scatter-add applies updates in order, so the round-major chain of scatters
# below performs, per output slot, the exact addition sequence of the dense
# path's single halo-src segment-sum (verified by tests/test_ragged.py).


def _ragged_remote(x, rsend_idx, redge_dst, redge_src, redge_w,
                   rr_sizes, rr_edge_sizes, num_rows: int, axis_name,
                   halo_dtype):
    """Σ_d (round-d scatter-add of Â_halo·recv_d) over the ppermute ring."""
    remote = jnp.zeros((num_rows, x.shape[-1]), x.dtype)
    live = ragged_live_rounds(rr_sizes)
    off_s = off_e = 0
    for d, (sd, ed) in enumerate(zip(rr_sizes, rr_edge_sizes), start=1):
        if d not in live:                 # no pair at this ring distance
            off_s += sd   # keep slice bookkeeping right under ANY rule
            off_e += ed
            continue
        buf = jnp.take(x, rsend_idx[off_s: off_s + sd], axis=0)  # (S_d, f)
        if halo_dtype is not None:
            buf = buf.astype(halo_dtype)                         # wire only
        recv = ppermute_or_identity(buf, axis_name, d).astype(x.dtype)
        g = (jnp.take(recv, redge_src[off_e: off_e + ed], axis=0)
             * redge_w[off_e: off_e + ed, None])
        remote = remote.at[redge_dst[off_e: off_e + ed]].add(
            g, indices_are_sorted=True)
        off_s += sd
        off_e += ed
    return remote


def _pspmm_ragged_once(h, rsend_idx, ell_idx, ell_w,
                       ltail_dst, ltail_src, ltail_w,
                       redge_dst, redge_src, redge_w,
                       buckets, rr_sizes, rr_edge_sizes, axis_name,
                       halo_dtype):
    # local ELL aggregation has no data dependence on ANY round (overlap)
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, h, buckets)
    remote = _ragged_remote(h, rsend_idx, redge_dst, redge_src, redge_w,
                            rr_sizes, rr_edge_sizes, h.shape[0], axis_name,
                            halo_dtype)
    return local + remote


@partial(jax.custom_vjp, nondiff_argnums=(10, 11, 12, 13, 14))
def pspmm_ragged_sym(h, rsend_idx, ell_idx, ell_w,
                     ltail_dst, ltail_src, ltail_w,
                     redge_dst, redge_src, redge_w,
                     buckets, rr_sizes, rr_edge_sizes,
                     axis_name=AXIS, halo_dtype=None):
    """``PSpMM`` over the ragged ppermute ring for a SYMMETRIC Â.

    Same math as ``pspmm_ell_sym`` — ELL local aggregation plus the halo
    contribution — but the exchange is k−1 per-round-sized ppermutes
    instead of one globally-padded all_to_all, and the remote term folds
    round by round (see ``_ragged_remote``).  The custom backward reuses
    the forward form on ``g`` (Âᵀg = Âg for symmetric Â): the gradient
    rides the same ragged ring, same per-round sizes, same narrow-wire
    ``halo_dtype`` lever — the ragged analogue of the reference's swapped
    send/recv backward maps (``GPU/PGCN.py:93-97``).

    Only valid when ``plan.symmetric``; the trainer gates on it.
    """
    return _pspmm_ragged_once(h, rsend_idx, ell_idx, ell_w,
                              ltail_dst, ltail_src, ltail_w,
                              redge_dst, redge_src, redge_w,
                              buckets, rr_sizes, rr_edge_sizes, axis_name,
                              halo_dtype)


def _pspmm_ragged_sym_fwd(h, rsend_idx, ell_idx, ell_w,
                          ltail_dst, ltail_src, ltail_w,
                          redge_dst, redge_src, redge_w,
                          buckets, rr_sizes, rr_edge_sizes, axis_name,
                          halo_dtype):
    out = _pspmm_ragged_once(h, rsend_idx, ell_idx, ell_w,
                             ltail_dst, ltail_src, ltail_w,
                             redge_dst, redge_src, redge_w,
                             buckets, rr_sizes, rr_edge_sizes, axis_name,
                             halo_dtype)
    res = (rsend_idx, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
           redge_dst, redge_src, redge_w)
    return out, res


def _pspmm_ragged_sym_bwd(buckets, rr_sizes, rr_edge_sizes, axis_name,
                          halo_dtype, res, g):
    (rsend_idx, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
     redge_dst, redge_src, redge_w) = res
    gh = _pspmm_ragged_once(g, rsend_idx, ell_idx, ell_w,
                            ltail_dst, ltail_src, ltail_w,
                            redge_dst, redge_src, redge_w,
                            buckets, rr_sizes, rr_edge_sizes, axis_name,
                            halo_dtype)
    return (gh, *[None] * 9)


pspmm_ragged_sym.defvjp(_pspmm_ragged_sym_fwd, _pspmm_ragged_sym_bwd)


# ------------------------------------------------------------------ replicas
# Hot-halo replication (CaPGNN-style, arXiv:2508.13716): the plan's top-B
# boundary rows by λ·degree live as PERSISTENT REPLICAS on their consumer
# chips (``CommPlan.ensure_replicas``).  A replica step exchanges only the
# shrunken no-replica buckets (``nrep_*`` — replicated rows leave the wire
# entirely, forward AND backward) and fills the replica halo slots from a
# carried per-layer replica table; a refresh (sync) step runs EXACTLY the
# full exact exchange — same collectives, same fold order, f32-bit-identical
# math — and re-reads the replica rows out of the fresh halo as the next
# carry.  Gradient replicas mirror the structure through the same cotangent
# channel as ``pspmm_stale``'s ``ghalo_in``: differentiate the caller w.r.t.
# its ``greps`` carry and the "grad" that comes back IS next refresh's
# gradient-replica table (fresh on sync steps, the pass-through carry
# otherwise).  Unlike the stale mode, every exchange here is SYNCHRONOUS
# (same-step consumer): replication shrinks wire bytes, not exposure.
# Symmetric-Â only, like every custom-VJP op in this file.


def _replica_halo(x, rep, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
                  rep_slots, axis_name, halo_dtype, fresh):
    """One replica-aware halo exchange; returns ``(halo, rep_next)``.

    ``fresh``: the FULL exchange (bit-identical to ``halo_exchange``) plus
    the replica refresh ``halo[rep_slots]`` — PADDING carry slots
    (``rep_slots`` holds ``r`` there, out of range) are zeroed, not left
    with the clip-gather's junk row: they are never consumed (the ``.set``
    drops them), but the drift gauges sum over the whole carry, and
    step-varying junk in pad slots would masquerade as replica drift.
    Otherwise: the shrunken exchange, with replica slots overwritten from
    the carry and the carry passed through unchanged."""
    if fresh:
        halo = halo_exchange(x, send_idx, halo_src, axis_name, halo_dtype)
        valid = (rep_slots < halo.shape[0])[:, None].astype(halo.dtype)
        return halo, jnp.take(halo, rep_slots, axis=0, mode="clip") * valid
    halo = halo_exchange(x, nrep_send_idx, nrep_halo_src, axis_name,
                         halo_dtype)
    halo = halo.at[rep_slots].set(rep.astype(halo.dtype), mode="drop")
    return halo, rep


def _pspmm_replica_once(x, rep_in, send_idx, halo_src, nrep_send_idx,
                        nrep_halo_src, rep_slots, ell_idx, ell_w,
                        ltail_dst, ltail_src, ltail_w,
                        hedge_dst, hedge_src, hedge_w,
                        buckets, axis_name, halo_dtype, fresh):
    halo, rep_next = _replica_halo(
        x, rep_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, axis_name, halo_dtype, fresh)
    # same dependence structure as the exact path: the local ELL pass has
    # no data dependence on the exchange (overlap), the halo fold waits
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x,
                     buckets)
    remote = spmm_local(hedge_dst, hedge_src, hedge_w, halo, x.shape[0])
    return local + remote, rep_next


@partial(jax.custom_vjp, nondiff_argnums=(16, 17, 18, 19))
def pspmm_replica(x, rep_in, grep_in, send_idx, halo_src,
                  nrep_send_idx, nrep_halo_src, rep_slots,
                  ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                  hedge_dst, hedge_src, hedge_w, buckets,
                  axis_name=AXIS, halo_dtype=None, fresh=False):
    """``PSpMM`` with persistent hot-halo replicas on the dense a2a.

    Replica (``fresh=False``) step: the a2a ships the SHRUNKEN
    ``(k, S')`` buckets (replicated rows off the wire, both directions),
    the halo table's replica slots fill from ``rep_in``/``grep_in``, and
    both carries pass through unchanged.  Refresh (``fresh=True``) step:
    the full exact exchange — the program is the exact path plus the
    replica-row gathers, so a ``--sync-every 1`` trajectory is
    f32-bit-identical to the no-replica path — and both carries come back
    fresh (features via ``rep_next``, gradients via the ``grep_in``
    cotangent).  Returns ``(out, rep_next)``; the carry output's cotangent
    is structurally zero (it crosses the step boundary).
    """
    return _pspmm_replica_once(
        x, rep_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
        hedge_dst, hedge_src, hedge_w, buckets, axis_name, halo_dtype,
        fresh)


def _pspmm_replica_fwd(x, rep_in, grep_in, send_idx, halo_src,
                       nrep_send_idx, nrep_halo_src, rep_slots,
                       ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                       hedge_dst, hedge_src, hedge_w, buckets,
                       axis_name, halo_dtype, fresh):
    out = _pspmm_replica_once(
        x, rep_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
        hedge_dst, hedge_src, hedge_w, buckets, axis_name, halo_dtype,
        fresh)
    res = (grep_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
           rep_slots, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
           hedge_dst, hedge_src, hedge_w)
    return out, res


def _pspmm_replica_bwd(buckets, axis_name, halo_dtype, fresh, res, cts):
    (grep_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src, rep_slots,
     ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
     hedge_dst, hedge_src, hedge_w) = res
    g, _ = cts               # carry cotangent is structurally zero
    # gradient exchange mirrors the forward exactly: shrunken buckets +
    # gradient-replica carry on replica steps, the full exchange (whose
    # replica rows refresh the carry through this cotangent) on syncs
    ghalo, grep_next = _replica_halo(
        g, grep_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, axis_name, halo_dtype, fresh)
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + spmm_local(hedge_dst, hedge_src, hedge_w, ghalo, g.shape[0]))
    return (gx, None, grep_next, *[None] * 13)


pspmm_replica.defvjp(_pspmm_replica_fwd, _pspmm_replica_bwd)


def _replica_ring_halo(x, rep, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst,
                       rep_slots, rep_ring_pos, rr_sizes, nrep_rr_sizes,
                       halo_r, axis_name, halo_dtype, fresh):
    """One replica-aware ragged-ring exchange.

    ``fresh``: ship the FULL per-round ring and return the round-major
    receive concat (the PR-6 carry layout — folding it through ``redge_*``
    is f32-bit-identical to the exact ragged path) plus the replica rows
    gathered at ``rep_ring_pos``.  Otherwise: ship the SHRUNKEN ring
    (``nrep_rr_sizes`` — live rounds per ``ragged_live_rounds``, the shared
    elision rule), scatter receives into the halo table, overwrite replica
    slots from the carry, and pass the carry through.  Returns
    ``(ring_concat_or_halo_table, rep_next)`` — the caller folds the first
    element per mode (``redge_*`` ring fold when fresh, dense ``hedge_*``
    fold otherwise)."""
    f = x.shape[-1]
    if fresh:
        segs = []
        live = ragged_live_rounds(rr_sizes)
        off = 0
        for d, sd in enumerate(rr_sizes, start=1):
            if d not in live:
                off += sd    # keep slice bookkeeping right under ANY rule
                continue
            buf = jnp.take(x, rsend_idx[off: off + sd], axis=0)
            if halo_dtype is not None:
                buf = buf.astype(halo_dtype)
            segs.append(ppermute_or_identity(buf, axis_name, d)
                        .astype(x.dtype))
            off += sd
        ring = (jnp.zeros((1, f), x.dtype) if not segs
                else (segs[0] if len(segs) == 1 else jnp.concatenate(segs)))
        # zero padding carry slots (rep_slots == r there) — same drift-gauge
        # hygiene as the a2a refresh: pad rows are never consumed, but junk
        # in them would pollute Σ(rep_next − rep_in)²
        valid = (rep_slots < halo_r)[:, None].astype(x.dtype)
        return ring, jnp.take(ring, rep_ring_pos, axis=0, mode="clip") * valid
    halo = jnp.zeros((halo_r, f), x.dtype)
    live = ragged_live_rounds(nrep_rr_sizes)
    off = 0
    for d, sd in enumerate(nrep_rr_sizes, start=1):
        if d not in live:
            off += sd        # keep slice bookkeeping right under ANY rule
            continue
        buf = jnp.take(x, nrep_rsend_idx[off: off + sd], axis=0)
        if halo_dtype is not None:
            buf = buf.astype(halo_dtype)
        recv = ppermute_or_identity(buf, axis_name, d).astype(x.dtype)
        halo = halo.at[nrep_rhalo_dst[off: off + sd]].set(recv, mode="drop")
        off += sd
    halo = halo.at[rep_slots].set(rep.astype(x.dtype), mode="drop")
    return halo, rep


def _pspmm_replica_ragged_once(x, rep_in, rsend_idx, nrep_rsend_idx,
                               nrep_rhalo_dst, rep_slots, rep_ring_pos,
                               ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                               hedge_dst, hedge_src, hedge_w,
                               redge_dst, redge_src, redge_w,
                               buckets, rr_sizes, rr_edge_sizes,
                               nrep_rr_sizes, halo_r, axis_name, halo_dtype,
                               fresh):
    tab, rep_next = _replica_ring_halo(
        x, rep_in, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
        rep_ring_pos, rr_sizes, nrep_rr_sizes, halo_r, axis_name,
        halo_dtype, fresh)
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x,
                     buckets)
    if fresh:
        # the full ring concat folds through the exact ragged path's
        # per-round redge_* scatter sequence (bit-identical — PR-6 contract)
        remote = _stale_ragged_fold(tab, redge_dst, redge_src, redge_w,
                                    rr_sizes, rr_edge_sizes, x.shape[0])
    else:
        # the shrunken ring lands in the halo TABLE (replica slots from the
        # carry), folded by the dense halo-src edge family — replica steps
        # are approximate between refreshes, so round-order parity is not a
        # contract here
        remote = spmm_local(hedge_dst, hedge_src, hedge_w, tab, x.shape[0])
    return local + remote, rep_next


@partial(jax.custom_vjp, nondiff_argnums=(19, 20, 21, 22, 23, 24, 25, 26))
def pspmm_replica_ragged(x, rep_in, grep_in, rsend_idx,
                         nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
                         rep_ring_pos,
                         ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                         hedge_dst, hedge_src, hedge_w,
                         redge_dst, redge_src, redge_w,
                         buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes,
                         halo_r, axis_name=AXIS, halo_dtype=None,
                         fresh=False):
    """``PSpMM`` with persistent hot-halo replicas on the ragged ring.

    Replica (``fresh=False``) step: k−1 per-round ppermutes sized by the
    SHRUNKEN ``nrep_rr_sizes`` (replicated rows off every round's wire,
    both directions; empty rounds elided per ``ragged_live_rounds``), halo
    replica slots filled from the carries.  Refresh (``fresh=True``) step:
    the full ring whose round-major concat folds through ``redge_*`` —
    f32-bit-identical to the exact ragged path, so ``--sync-every 1``
    reproduces the no-replica trajectory — and both carries refresh
    (features via ``rep_next`` at ``rep_ring_pos``, gradients via the
    ``grep_in`` cotangent).  Returns ``(out, rep_next)``.  Symmetric-Â
    only.
    """
    return _pspmm_replica_ragged_once(
        x, rep_in, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
        rep_ring_pos, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
        hedge_dst, hedge_src, hedge_w, redge_dst, redge_src, redge_w,
        buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes, halo_r, axis_name,
        halo_dtype, fresh)


def _pspmm_replica_ragged_fwd(x, rep_in, grep_in, rsend_idx,
                              nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
                              rep_ring_pos, ell_idx, ell_w,
                              ltail_dst, ltail_src, ltail_w,
                              hedge_dst, hedge_src, hedge_w,
                              redge_dst, redge_src, redge_w,
                              buckets, rr_sizes, rr_edge_sizes,
                              nrep_rr_sizes, halo_r, axis_name, halo_dtype,
                              fresh):
    out = _pspmm_replica_ragged_once(
        x, rep_in, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
        rep_ring_pos, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
        hedge_dst, hedge_src, hedge_w, redge_dst, redge_src, redge_w,
        buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes, halo_r, axis_name,
        halo_dtype, fresh)
    res = (grep_in, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
           rep_ring_pos, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
           hedge_dst, hedge_src, hedge_w, redge_dst, redge_src, redge_w)
    return out, res


def _pspmm_replica_ragged_bwd(buckets, rr_sizes, rr_edge_sizes,
                              nrep_rr_sizes, halo_r, axis_name, halo_dtype,
                              fresh, res, cts):
    (grep_in, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
     rep_ring_pos, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
     hedge_dst, hedge_src, hedge_w, redge_dst, redge_src, redge_w) = res
    g, _ = cts               # carry cotangent is structurally zero
    gtab, grep_next = _replica_ring_halo(
        g, grep_in, rsend_idx, nrep_rsend_idx, nrep_rhalo_dst, rep_slots,
        rep_ring_pos, rr_sizes, nrep_rr_sizes, halo_r, axis_name,
        halo_dtype, fresh)
    if fresh:
        gremote = _stale_ragged_fold(gtab, redge_dst, redge_src, redge_w,
                                     rr_sizes, rr_edge_sizes, g.shape[0])
    else:
        gremote = spmm_local(hedge_dst, hedge_src, hedge_w, gtab,
                             g.shape[0])
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + gremote)
    return (gx, None, grep_next, *[None] * 16)


pspmm_replica_ragged.defvjp(_pspmm_replica_ragged_fwd,
                            _pspmm_replica_ragged_bwd)


# ------------------------------------------------------ replicas × staleness
# The COMPOSED mode (``--replica-budget B --halo-staleness 1``): the
# one-step-stale carry of ``pspmm_stale`` rides the SHRUNKEN no-replica
# exchange of ``pspmm_replica``.  The stale halo carry SUBSUMES the replica
# tables — no separate rep/grep carry exists: a stale step ships only the
# shrunken ``nrep_*`` buffers (with no same-step consumer, so the
# already-smaller exchange also leaves the critical path) and scatters its
# receives back into the carried halo table, leaving the replica slots at
# the values the last sync wrote; a sync step runs the FULL exchange
# consumed fresh — exactly ``pspmm_stale``'s sync program, so
# ``--sync-every 1`` is f32-bit-identical to the exact (and no-replica)
# path.  The ragged flavor carries the ring envelope of
# ``pspmm_stale_ragged`` and scatters shrunken-round receives into it at
# ``nrep_ring_dst`` (each kept slot's position in the FULL ring concat).
# Gradient carries mirror the structure through the ``ghalo_in`` cotangent
# channel.  Symmetric-Â only, like every composed op here.


def _replica_stale_exchange(x, halo_in, send_idx, halo_src, nrep_send_idx,
                            nrep_halo_src, rep_slots, axis_name, wire_dtype,
                            fresh):
    """Issue step t's exchange; return ``halo_next`` (the dense ``(R, f)``
    carry).  ``fresh``: the full exchange — bit-identical to
    ``halo_exchange``, every slot (replica slots included) refreshed.
    Otherwise: the shrunken ``nrep_*`` exchange scattered over the kept
    slots, replica slots re-seated from the carry (their values propagate
    sync → sync through the carried table)."""
    if fresh:
        return halo_exchange(x, send_idx, halo_src, axis_name, wire_dtype)
    halo = halo_exchange(x, nrep_send_idx, nrep_halo_src, axis_name,
                         wire_dtype)
    rep_vals = jnp.take(halo_in, rep_slots, axis=0, mode="clip")
    return halo.at[rep_slots].set(rep_vals, mode="drop")


def _pspmm_replica_stale_once(x, halo_in, send_idx, halo_src,
                              nrep_send_idx, nrep_halo_src, rep_slots,
                              ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                              hedge_dst, hedge_src, hedge_w,
                              buckets, axis_name, wire_dtype, fresh):
    halo_next = _replica_stale_exchange(
        x, halo_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, axis_name, wire_dtype, fresh)
    # stale step: the fold reads the CARRY — the shrunken exchange has no
    # same-step consumer, so it rides behind compute like pspmm_stale's;
    # sync step: the fold waits for the full exchange (exact structure)
    halo_used = halo_next if fresh else halo_in
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x,
                     buckets)
    remote = spmm_local(hedge_dst, hedge_src, hedge_w, halo_used,
                        x.shape[0])
    return local + remote, halo_next


@partial(jax.custom_vjp, nondiff_argnums=(17, 18, 19, 20, 21))
def pspmm_replica_stale(x, halo_in, ghalo_in, base_in, send_idx, halo_src,
                        nrep_send_idx, nrep_halo_src, rep_slots,
                        ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                        hedge_dst, hedge_src, hedge_w, buckets,
                        axis_name=AXIS, wire_dtype=None, gwire_dtype=None,
                        fresh=False):
    """``PSpMM`` composing hot-halo replication with the one-step-stale
    carry on the dense a2a (see the section comment above).

    Stale (``fresh=False``) step: the a2a ships the SHRUNKEN ``(k, S')``
    buckets with no in-step consumer; the consumed halo is the carry, and
    ``halo_next`` is the carry with the kept slots overwritten by this
    step's receives (replica slots keep their last-sync values).  Sync
    (``fresh=True``) step: exactly ``pspmm_stale``'s full-sync program —
    f32-bit-identical to the exact path.  ``base_in`` passes through
    untouched (the halo-delta cache does not compose with replication —
    the trainer gates it); returns ``(out, halo_next, base_next)`` with
    the same carry arity as ``pspmm_stale`` so the stale forward stays
    uniform.  The gradient ring mirrors the structure through the
    ``ghalo_in`` cotangent channel."""
    out, halo_next = _pspmm_replica_stale_once(
        x, halo_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
        hedge_dst, hedge_src, hedge_w, buckets, axis_name, wire_dtype,
        fresh)
    return out, halo_next, base_in


def _pspmm_replica_stale_fwd(x, halo_in, ghalo_in, base_in, send_idx,
                             halo_src, nrep_send_idx, nrep_halo_src,
                             rep_slots, ell_idx, ell_w,
                             ltail_dst, ltail_src, ltail_w,
                             hedge_dst, hedge_src, hedge_w, buckets,
                             axis_name, wire_dtype, gwire_dtype, fresh):
    out, halo_next = _pspmm_replica_stale_once(
        x, halo_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
        hedge_dst, hedge_src, hedge_w, buckets, axis_name, wire_dtype,
        fresh)
    res = (ghalo_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
           rep_slots, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
           hedge_dst, hedge_src, hedge_w)
    return (out, halo_next, base_in), res


def _pspmm_replica_stale_bwd(buckets, axis_name, wire_dtype, gwire_dtype,
                             fresh, res, cts):
    (ghalo_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src, rep_slots,
     ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
     hedge_dst, hedge_src, hedge_w) = res
    g, _, _ = cts            # carry cotangents are structurally zero
    # step t's gradient exchange mirrors the forward: shrunken buckets
    # merged into the carried table on stale steps (no same-step consumer),
    # the full exchange on syncs — it leaves via the ghalo_in channel
    gh_next = _replica_stale_exchange(
        g, ghalo_in, send_idx, halo_src, nrep_send_idx, nrep_halo_src,
        rep_slots, axis_name, gwire_dtype, fresh)
    gh_used = gh_next if fresh else ghalo_in
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + spmm_local(hedge_dst, hedge_src, hedge_w, gh_used, g.shape[0]))
    return (gx, None, gh_next, None, *[None] * 13)


pspmm_replica_stale.defvjp(_pspmm_replica_stale_fwd,
                           _pspmm_replica_stale_bwd)


def _replica_stale_ring_exchange(x, halo_in, rsend_idx, nrep_rsend_idx,
                                 nrep_ring_dst, rr_sizes, nrep_rr_sizes,
                                 axis_name, wire_dtype, fresh):
    """Issue step t's ring exchange; return the round-major
    ``(Σ_d S_d, f)`` ring-envelope carry.  ``fresh``: the full per-round
    ring concat (``_stale_ragged_exchange``'s non-delta path — bit-exact
    with the exact ragged wire).  Otherwise: the SHRUNKEN ring (live
    rounds of ``nrep_rr_sizes``) scattered into the carried envelope at
    each kept slot's full-ring position; replica positions keep their
    last-sync values."""
    if fresh:
        halo_next, _ = _stale_ragged_exchange(
            x, halo_in, halo_in, rsend_idx, rr_sizes, axis_name, False,
            wire_dtype, fresh)
        return halo_next
    halo_next = halo_in
    live = ragged_live_rounds(nrep_rr_sizes)
    off = 0
    for d, sd in enumerate(nrep_rr_sizes, start=1):
        if d not in live:
            off += sd      # keep slice bookkeeping right under ANY rule
            continue
        buf = jnp.take(x, nrep_rsend_idx[off: off + sd], axis=0)
        if wire_dtype is not None:
            buf = buf.astype(wire_dtype)
        recv = ppermute_or_identity(buf, axis_name, d).astype(x.dtype)
        halo_next = halo_next.at[nrep_ring_dst[off: off + sd]].set(
            recv, mode="drop")
        off += sd
    return halo_next


def _pspmm_replica_stale_ragged_once(x, halo_in, rsend_idx, nrep_rsend_idx,
                                     nrep_ring_dst, ell_idx, ell_w,
                                     ltail_dst, ltail_src, ltail_w,
                                     redge_dst, redge_src, redge_w,
                                     buckets, rr_sizes, rr_edge_sizes,
                                     nrep_rr_sizes, axis_name, wire_dtype,
                                     fresh):
    halo_next = _replica_stale_ring_exchange(
        x, halo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst, rr_sizes,
        nrep_rr_sizes, axis_name, wire_dtype, fresh)
    halo_used = halo_next if fresh else halo_in
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x,
                     buckets)
    # the fold always consumes the FULL ring envelope through the exact
    # redge_* sequence — a sync step therefore reproduces the exact ragged
    # path's bits, and a stale step folds the carried mixture (kept slots
    # one step old, replica slots last-sync old)
    remote = _stale_ragged_fold(halo_used, redge_dst, redge_src, redge_w,
                                rr_sizes, rr_edge_sizes, x.shape[0])
    return local + remote, halo_next


@partial(jax.custom_vjp, nondiff_argnums=(15, 16, 17, 18, 19, 20, 21, 22))
def pspmm_replica_stale_ragged(x, halo_in, ghalo_in, base_in, rsend_idx,
                               nrep_rsend_idx, nrep_ring_dst,
                               ell_idx, ell_w, ltail_dst, ltail_src,
                               ltail_w, redge_dst, redge_src, redge_w,
                               buckets, rr_sizes, rr_edge_sizes,
                               nrep_rr_sizes, axis_name=AXIS,
                               wire_dtype=None, gwire_dtype=None,
                               fresh=False):
    """``PSpMM`` composing hot-halo replication with the round-structured
    stale carry on the ragged ring — the replica carry IS a region of the
    stale ring envelope (``nrep_ring_dst`` maps shrunken receives into the
    full concat; replica positions are simply never overwritten between
    syncs).

    Stale step: live rounds of the SHRUNKEN ``nrep_rr_sizes`` ring, no
    in-step consumer.  Sync step: the full ring consumed fresh —
    f32-bit-identical to the exact ragged path (``pspmm_stale_ragged``'s
    contract chains through).  ``base_in`` passes through (no delta
    composition); same carry arity as ``pspmm_stale_ragged``.
    Symmetric-Â only."""
    out, halo_next = _pspmm_replica_stale_ragged_once(
        x, halo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst, ell_idx,
        ell_w, ltail_dst, ltail_src, ltail_w, redge_dst, redge_src,
        redge_w, buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes,
        axis_name, wire_dtype, fresh)
    return out, halo_next, base_in


def _pspmm_replica_stale_ragged_fwd(x, halo_in, ghalo_in, base_in,
                                    rsend_idx, nrep_rsend_idx,
                                    nrep_ring_dst, ell_idx, ell_w,
                                    ltail_dst, ltail_src, ltail_w,
                                    redge_dst, redge_src, redge_w,
                                    buckets, rr_sizes, rr_edge_sizes,
                                    nrep_rr_sizes, axis_name, wire_dtype,
                                    gwire_dtype, fresh):
    out, halo_next = _pspmm_replica_stale_ragged_once(
        x, halo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst, ell_idx,
        ell_w, ltail_dst, ltail_src, ltail_w, redge_dst, redge_src,
        redge_w, buckets, rr_sizes, rr_edge_sizes, nrep_rr_sizes,
        axis_name, wire_dtype, fresh)
    res = (ghalo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst, ell_idx,
           ell_w, ltail_dst, ltail_src, ltail_w, redge_dst, redge_src,
           redge_w)
    return (out, halo_next, base_in), res


def _pspmm_replica_stale_ragged_bwd(buckets, rr_sizes, rr_edge_sizes,
                                    nrep_rr_sizes, axis_name, wire_dtype,
                                    gwire_dtype, fresh, res, cts):
    (ghalo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst, ell_idx, ell_w,
     ltail_dst, ltail_src, ltail_w, redge_dst, redge_src, redge_w) = res
    g, _, _ = cts            # carry cotangents are structurally zero
    gh_next = _replica_stale_ring_exchange(
        g, ghalo_in, rsend_idx, nrep_rsend_idx, nrep_ring_dst, rr_sizes,
        nrep_rr_sizes, axis_name, gwire_dtype, fresh)
    gh_used = gh_next if fresh else ghalo_in
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + _stale_ragged_fold(gh_used, redge_dst, redge_src, redge_w,
                               rr_sizes, rr_edge_sizes, g.shape[0]))
    return (gx, None, gh_next, None, *[None] * 11)


pspmm_replica_stale_ragged.defvjp(_pspmm_replica_stale_ragged_fwd,
                                  _pspmm_replica_stale_ragged_bwd)


# --------------------------------------------------------- partial refresh
# Drift-driven PARTIAL replica refresh (``--refresh-band``, CaPGNN's cache
# policy, arXiv:2508.13716): instead of PR-10's all-or-nothing refresh, a
# refresh step ships ONLY the replica rows whose sender-side drift crosses
# the band, as a quantized DELTA against the refresh baseline — both ends
# accumulate the identical increment (the ``_stale_exchange`` lockstep
# contract), so refreshed rows land in exact sender/receiver agreement and
# un-refreshed rows ship exact zeros (no change on either end).  The wire
# is the shrunken replica-step exchange PLUS one replica-only side-channel
# a2a per direction (``ronly_*`` buckets: exactly the rows
# ``ensure_replicas`` deleted); the gradient side channel refreshes the
# gradient replicas for the SAME masked rows with set semantics (one extra
# 0/1 indicator lane tells the receiver which slots carry fresh values).
# Dense-a2a transport only — the trainer gates the composition.


def _partial_mask(x, base_in, rep_rows, rep_row_count, band):
    """Sender-side per-row refresh decision: row i refreshes iff
    ``‖x_i − base_i‖² > band² · ‖base_i‖²`` (relative drift — a zero
    baseline with any drift always refreshes).  Returns ``(diff, mask,
    row_valid)`` over the padded (RS, f) owned-replica table."""
    xr = jnp.take(x, rep_rows, axis=0)                       # (RS, f)
    row_valid = (jnp.arange(rep_rows.shape[0]) < rep_row_count)
    diff = (xr - base_in) * row_valid[:, None].astype(x.dtype)
    drift2 = jnp.sum(jnp.square(diff), axis=-1)
    ref2 = jnp.sum(jnp.square(base_in), axis=-1)
    mask = (drift2 > (band * band) * ref2) & row_valid
    return diff, mask, row_valid


def _pspmm_replica_partial_once(x, rep_in, base_in, nrep_send_idx,
                                nrep_halo_src, rep_slots, rep_rows,
                                rep_row_count, ronly_send_idx, ronly_counts,
                                ronly_base_pos, rep_recv_src,
                                ell_idx, ell_w, ltail_dst, ltail_src,
                                ltail_w, hedge_dst, hedge_src, hedge_w,
                                buckets, axis_name, halo_dtype, band):
    f = x.shape[-1]
    wdt = x.dtype if halo_dtype is None else jnp.dtype(halo_dtype)
    halo = halo_exchange(x, nrep_send_idx, nrep_halo_src, axis_name,
                         halo_dtype)
    diff, mask, _ = _partial_mask(x, base_in, rep_rows, rep_row_count, band)
    # the quantized increment, per OWNED replicated row: both ends add THIS
    # value, so sender baseline and every consumer replica stay in lockstep
    qinc = (diff * mask[:, None].astype(x.dtype)).astype(wdt).astype(x.dtype)
    slot_valid = (jnp.arange(ronly_send_idx.shape[-1])[None, :]
                  < ronly_counts[:, None])                    # (peers, RS')
    slot_active = (slot_valid
                   & jnp.take(mask, ronly_base_pos, axis=0))  # masked-in
    wire = (jnp.take(qinc, ronly_base_pos, axis=0)
            * slot_valid[..., None].astype(x.dtype)).astype(wdt)
    recv = a2a_or_identity(wire, axis_name)
    flat = recv.reshape(-1, f).astype(x.dtype)
    rep_valid = (rep_slots < halo.shape[0])[:, None].astype(x.dtype)
    inc = jnp.take(flat, rep_recv_src, axis=0) * rep_valid
    rep_next = rep_in + inc
    base_next = base_in + qinc
    halo = halo.at[rep_slots].set(rep_next.astype(halo.dtype), mode="drop")
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x,
                     buckets)
    remote = spmm_local(hedge_dst, hedge_src, hedge_w, halo, x.shape[0])
    # per-chip count of side-channel slots that carried a fresh row — the
    # ACTUAL shipped true rows this layer (each consumer copy counts, like
    # every send-volume gauge); the trainer psums and books it
    nship = jnp.sum(slot_active.astype(jnp.int32))
    return local + remote, rep_next, base_next, nship, slot_active


@partial(jax.custom_vjp, nondiff_argnums=(21, 22, 23, 24))
def pspmm_replica_partial(x, rep_in, grep_in, base_in, nrep_send_idx,
                          nrep_halo_src, rep_slots, rep_rows, rep_row_count,
                          ronly_send_idx, ronly_counts, ronly_base_pos,
                          rep_recv_src, ell_idx, ell_w,
                          ltail_dst, ltail_src, ltail_w,
                          hedge_dst, hedge_src, hedge_w, buckets,
                          axis_name=AXIS, halo_dtype=None, band=0.0):
    """``PSpMM`` with a drift-driven PARTIAL replica refresh (the
    ``--refresh-band`` refresh step — see the section comment).

    Ships the shrunken replica-step exchange plus the replica-only side
    channel of masked deltas; consumers see ``rep_next`` (refreshed where
    shipped, carried otherwise) in their replica halo slots.  The backward
    mirrors it: the gradient side channel refreshes ``grep`` for the SAME
    masked rows (fresh values + indicator lane).  Returns ``(out,
    rep_next, base_next, nship)`` where ``nship`` is this chip's count of
    side-channel slots that actually carried a row — the booking figure
    for CommStats/step_cost.  Symmetric-Â, dense-a2a transport only."""
    out, rep_next, base_next, nship, _ = _pspmm_replica_partial_once(
        x, rep_in, base_in, nrep_send_idx, nrep_halo_src, rep_slots,
        rep_rows, rep_row_count, ronly_send_idx, ronly_counts,
        ronly_base_pos, rep_recv_src, ell_idx, ell_w, ltail_dst, ltail_src,
        ltail_w, hedge_dst, hedge_src, hedge_w, buckets, axis_name,
        halo_dtype, band)
    return out, rep_next, base_next, nship


def _pspmm_replica_partial_fwd(x, rep_in, grep_in, base_in, nrep_send_idx,
                               nrep_halo_src, rep_slots, rep_rows,
                               rep_row_count, ronly_send_idx, ronly_counts,
                               ronly_base_pos, rep_recv_src, ell_idx, ell_w,
                               ltail_dst, ltail_src, ltail_w,
                               hedge_dst, hedge_src, hedge_w, buckets,
                               axis_name, halo_dtype, band):
    out, rep_next, base_next, nship, slot_active = \
        _pspmm_replica_partial_once(
            x, rep_in, base_in, nrep_send_idx, nrep_halo_src, rep_slots,
            rep_rows, rep_row_count, ronly_send_idx, ronly_counts,
            ronly_base_pos, rep_recv_src, ell_idx, ell_w, ltail_dst,
            ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w, buckets,
            axis_name, halo_dtype, band)
    res = (grep_in, slot_active, nrep_send_idx, nrep_halo_src, rep_slots,
           rep_rows, ronly_base_pos, rep_recv_src, ell_idx, ell_w,
           ltail_dst, ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w)
    return (out, rep_next, base_next, nship), res


def _pspmm_replica_partial_bwd(buckets, axis_name, halo_dtype, band, res,
                               cts):
    (grep_in, slot_active, nrep_send_idx, nrep_halo_src, rep_slots,
     rep_rows, ronly_base_pos, rep_recv_src, ell_idx, ell_w,
     ltail_dst, ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w) = res
    g, _, _, _ = cts         # carry/count cotangents are structurally zero
    f = g.shape[-1]
    wdt = g.dtype if halo_dtype is None else jnp.dtype(halo_dtype)
    ghalo = halo_exchange(g, nrep_send_idx, nrep_halo_src, axis_name,
                          halo_dtype)
    # gradient side channel, SAME mask as the forward: fresh gradient rows
    # for the masked slots plus one 0/1 indicator lane (set semantics —
    # the receiver cannot otherwise tell "not refreshed" from a zero row)
    grows = jnp.take(g, rep_rows, axis=0)                     # (RS, f)
    act = slot_active.astype(g.dtype)[..., None]              # (peers,RS',1)
    gsel = jnp.take(grows, ronly_base_pos, axis=0) * act
    gwire = jnp.concatenate([gsel, act], axis=-1).astype(wdt)
    grecv = a2a_or_identity(gwire, axis_name)
    gflat = grecv.reshape(-1, f + 1).astype(g.dtype)
    vals = jnp.take(gflat, rep_recv_src, axis=0)
    rep_valid = (rep_slots < ghalo.shape[0])[:, None].astype(g.dtype)
    refreshed = vals[:, f:] * rep_valid                       # (RP, 1)
    grep_next = grep_in * (1.0 - refreshed) + vals[:, :f] * refreshed
    gtab = ghalo.at[rep_slots].set(grep_next.astype(ghalo.dtype),
                                   mode="drop")
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + spmm_local(hedge_dst, hedge_src, hedge_w, gtab, g.shape[0]))
    return (gx, None, grep_next, None, *[None] * 17)


pspmm_replica_partial.defvjp(_pspmm_replica_partial_fwd,
                             _pspmm_replica_partial_bwd)


# --------------------------------------------------------------------- stale
# Pipelined one-step-stale exchange (PipeGCN-style, arXiv:2203.10428): layer ℓ
# of step t aggregates with the halo received during step t−1, and step t's
# exchange is issued with NO consumer inside the step — XLA is free to
# schedule the all_to_all entirely behind local SpMM + dense compute, turning
# the per-layer exchange barrier into a background transfer.  The backward
# mirrors it: the gradient halo consumed at step t was exchanged at step t−1
# (bounded-staleness features AND gradients, the combination PipeGCN shows
# converges at no accuracy loss).  Symmetric-Â only, like ``pspmm_ell_sym``.


def _stale_exchange(x, halo_in, base_in, send_idx, halo_src, axis_name,
                    delta, wire_dtype, fresh):
    """Issue step t's halo exchange; return ``(halo_next, base_next)``.

    ``delta`` (CaPGNN-style halo-delta caching, arXiv:2508.13716): the wire
    carries ``x_t − base`` per boundary row, quantized to ``wire_dtype``
    (bf16 — half the a2a bytes), and BOTH ends accumulate the identical
    quantized increment — the sender into ``base`` (its model of what every
    receiver holds), the receiver into its cached halo — so the two stay in
    exact lockstep and quantization error never compounds into disagreement.
    A ``fresh`` step re-bases with the FULL f32 row on the wire: both ends
    reset to the exact value, so accumulated rounding drift goes to zero
    (not to one more bf16 rounding) and a delta run at ``sync_every=1`` is
    exact-mode math.  The attribution model charges these steps the f32
    wire itemsize (``obs/attribution.py`` — the per-step itemsize split).
    """
    full = jnp.take(x, send_idx, axis=0)                     # (k, S, f)
    if delta:
        if fresh:
            recv = a2a_or_identity(full, axis_name)
            flat = recv.reshape(-1, x.shape[-1])
            return jnp.take(flat, halo_src, axis=0), full
        wdt = jnp.bfloat16 if wire_dtype is None else jnp.dtype(wire_dtype)
        wire = (full - base_in).astype(wdt)
        recv = a2a_or_identity(wire, axis_name)
        flat = recv.reshape(-1, x.shape[-1]).astype(x.dtype)
        inc = jnp.take(flat, halo_src, axis=0)
        return halo_in + inc, base_in + wire.astype(base_in.dtype)
    halo_next = halo_exchange(x, send_idx, halo_src, axis_name, wire_dtype)
    return halo_next, base_in


def _pspmm_stale_once(x, halo_in, base_in, send_idx, halo_src, ell_idx, ell_w,
                      ltail_dst, ltail_src, ltail_w,
                      hedge_dst, hedge_src, hedge_w,
                      buckets, axis_name, delta, wire_dtype, fresh):
    halo_next, base_next = _stale_exchange(
        x, halo_in, base_in, send_idx, halo_src, axis_name, delta,
        wire_dtype, fresh)
    # stale step: the remote term reads the CARRY — nothing in this step
    # depends on the exchange just issued, so it runs behind the compute;
    # fresh (sync) step: the remote term waits for the exchange, exactly
    # the exact-mode dependence structure
    halo_used = halo_next if fresh else halo_in
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x, buckets)
    remote = spmm_local(hedge_dst, hedge_src, hedge_w, halo_used, x.shape[0])
    return local + remote, halo_next, base_next


@partial(jax.custom_vjp, nondiff_argnums=(14, 15, 16, 17, 18, 19))
def pspmm_stale(x, halo_in, ghalo_in, base_in, send_idx, halo_src,
                ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                hedge_dst, hedge_src, hedge_w, buckets,
                axis_name=AXIS, delta=False, wire_dtype=None,
                gwire_dtype=None, fresh=False):
    """``PSpMM`` with a one-step-stale halo carry — the pipelined contract.

    Forward: ``out = Â_local·x + Â_halo·halo_in`` (the carry, exchanged last
    step) and step t's exchange is issued into ``halo_next`` with no
    in-step consumer.  Backward (symmetric Â): ``g_x = Â_local·g +
    Â_halo·ghalo_in`` — the stale GRADIENT halo — and the fresh gradient
    exchange ``halo_exchange(g)`` is emitted as the cotangent of the
    ``ghalo_in`` argument.  That channel is deliberate plumbing, not a real
    derivative: differentiate the caller w.r.t. its ``ghalo`` carry
    (``jax.value_and_grad(..., argnums=(params, ghalos))``) and the "grad"
    that comes back IS next step's gradient-halo carry.  ``fresh=True``
    compiles the periodic full-sync step: both halos are consumed fresh
    (exact-mode math) and the carries are refreshed as a byproduct.

    Returns ``(out, halo_next, base_next)``; the carries are aux outputs
    (their cotangents are ignored — they cross the step boundary, which
    per-step autodiff never differentiates through).
    """
    return _pspmm_stale_once(
        x, halo_in, base_in, send_idx, halo_src, ell_idx, ell_w,
        ltail_dst, ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w,
        buckets, axis_name, delta, wire_dtype, fresh)


def _pspmm_stale_fwd(x, halo_in, ghalo_in, base_in, send_idx, halo_src,
                     ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                     hedge_dst, hedge_src, hedge_w, buckets,
                     axis_name, delta, wire_dtype, gwire_dtype, fresh):
    out = _pspmm_stale_once(
        x, halo_in, base_in, send_idx, halo_src, ell_idx, ell_w,
        ltail_dst, ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w,
        buckets, axis_name, delta, wire_dtype, fresh)
    res = (ghalo_in, send_idx, halo_src, ell_idx, ell_w,
           ltail_dst, ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w)
    return out, res


def _pspmm_stale_bwd(buckets, axis_name, delta, wire_dtype, gwire_dtype,
                     fresh, res, cts):
    (ghalo_in, send_idx, halo_src, ell_idx, ell_w,
     ltail_dst, ltail_src, ltail_w, hedge_dst, hedge_src, hedge_w) = res
    g, _, _ = cts            # carry cotangents are structurally zero
    # issue step t's gradient exchange; like the forward's, it has no
    # consumer in the stale step (g_x reads the CARRY), so it too rides
    # behind compute.  It leaves through the ghalo_in cotangent channel.
    gh_next = halo_exchange(g, send_idx, halo_src, axis_name, gwire_dtype)
    gh_used = gh_next if fresh else ghalo_in
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + spmm_local(hedge_dst, hedge_src, hedge_w, gh_used, g.shape[0]))
    return (gx, None, gh_next, None, *[None] * 10)


pspmm_stale.defvjp(_pspmm_stale_fwd, _pspmm_stale_bwd)


# ------------------------------------------------------------- stale × ragged
# The composed mode (PipeGCN-complete): the one-step-stale carry of
# ``pspmm_stale`` ON the per-round ppermute ring of ``pspmm_ragged_sym`` —
# both perf levers at once.  The carry is ROUND-STRUCTURED: instead of the
# dense ``(R, f)`` halo table (gathered out of a globally-padded ``(k, S)``
# receive window), each layer carries the ring's receive buffers themselves,
# round-major — round d of the ring occupies slots ``[Σ_{d'<d} S_{d'},
# Σ_{d'<d} S_{d'} + S_d)`` of a ``(Σ_d S_d, f)`` table (``CommPlan.rr_sizes``
# sizes the rounds; empty rounds occupy zero slots and vanish at trace time).
# The fold consumes the carry through the SAME per-round ``redge_*``
# scatter-add sequence as ``_ragged_remote``, so a full-sync step is
# f32-bit-identical to the exact ragged path (and hence to the dense exact
# path — the PR-4 parity contract chains through), while a stale step's
# per-round exchanges have no same-step consumer at all: round d of step t's
# ppermute rides behind round d+1's fold of the CARRIED buffers and behind
# every local slot pass.  The bf16 halo-delta cache composes per round: each
# round's wire carries its own quantized increment against a round-slice of
# the (ring-shaped, not ``(k, S, f)``) baseline.


def _stale_ragged_exchange(x, halo_in, base_in, rsend_idx, rr_sizes,
                           axis_name, delta, wire_dtype, fresh):
    """Issue step t's per-round ring exchange; return ``(halo_next,
    base_next)`` in the round-major carry layout described above.

    Per live round: ``delta`` stale steps ship the bf16 increment against
    the round's baseline slice and BOTH ends accumulate it (the
    ``_stale_exchange`` lockstep contract, per round); a ``fresh`` delta
    step re-bases with the full f32 buffer (exact, drift reset to zero);
    non-delta rounds ship the full value at ``wire_dtype`` — exactly the
    exact-mode ring's wire, so a full-sync step receives the exact ragged
    exchange's bits."""
    segs_h, segs_b = [], []
    live = ragged_live_rounds(rr_sizes)
    off = 0
    for d, sd in enumerate(rr_sizes, start=1):
        if d not in live:
            off += sd      # keep slice bookkeeping right under ANY rule
            continue
        full = jnp.take(x, rsend_idx[off: off + sd], axis=0)   # (S_d, f)
        if delta and not fresh:
            wdt = (jnp.bfloat16 if wire_dtype is None
                   else jnp.dtype(wire_dtype))
            base = base_in[off: off + sd]
            wire = (full - base).astype(wdt)
            recv = ppermute_or_identity(wire, axis_name, d)
            segs_h.append(halo_in[off: off + sd]
                          + recv.astype(x.dtype))
            segs_b.append(base + wire.astype(base.dtype))
        else:
            buf = full
            if not delta and wire_dtype is not None:
                buf = buf.astype(wire_dtype)
            recv = ppermute_or_identity(buf, axis_name, d)
            segs_h.append(recv.astype(x.dtype))
            if delta:                       # fresh re-base: exact f32 wire
                segs_b.append(full)
        off += sd
    if not segs_h:                          # k=1 / all-empty ring: (1, f) dummy
        return halo_in, base_in
    halo_next = segs_h[0] if len(segs_h) == 1 else jnp.concatenate(segs_h)
    if not delta:
        return halo_next, base_in
    base_next = segs_b[0] if len(segs_b) == 1 else jnp.concatenate(segs_b)
    return halo_next, base_next


def _stale_ragged_fold(halo_tab, redge_dst, redge_src, redge_w,
                       rr_sizes, rr_edge_sizes, num_rows: int):
    """Σ_d (round-d scatter-add of Â_halo·carry_d): ``_ragged_remote``'s
    fold with the round receive buffers read from the round-major carry
    table instead of this step's wire — same per-slot addition sequence,
    so consuming a FRESH carry reproduces the exact ragged path's bits."""
    remote = jnp.zeros((num_rows, halo_tab.shape[-1]), halo_tab.dtype)
    live = ragged_live_rounds(rr_sizes)
    off_s = off_e = 0
    for d, (sd, ed) in enumerate(zip(rr_sizes, rr_edge_sizes), start=1):
        if d not in live:
            off_s += sd   # keep slice bookkeeping right under ANY rule
            off_e += ed
            continue
        recv = halo_tab[off_s: off_s + sd]
        g = (jnp.take(recv, redge_src[off_e: off_e + ed], axis=0)
             * redge_w[off_e: off_e + ed, None])
        remote = remote.at[redge_dst[off_e: off_e + ed]].add(
            g, indices_are_sorted=True)
        off_s += sd
        off_e += ed
    return remote


def _pspmm_stale_ragged_once(x, halo_in, base_in, rsend_idx, ell_idx, ell_w,
                             ltail_dst, ltail_src, ltail_w,
                             redge_dst, redge_src, redge_w,
                             buckets, rr_sizes, rr_edge_sizes, axis_name,
                             delta, wire_dtype, fresh):
    halo_next, base_next = _stale_ragged_exchange(
        x, halo_in, base_in, rsend_idx, rr_sizes, axis_name, delta,
        wire_dtype, fresh)
    # stale step: the fold reads the CARRY — no round of this step's ring
    # has a same-step consumer, so every ppermute rides behind compute;
    # fresh (sync) step: the fold waits round by round, exactly the exact
    # ragged path's fold-as-you-arrive dependence structure
    halo_used = halo_next if fresh else halo_in
    local = spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, x, buckets)
    remote = _stale_ragged_fold(halo_used, redge_dst, redge_src, redge_w,
                                rr_sizes, rr_edge_sizes, x.shape[0])
    return local + remote, halo_next, base_next


@partial(jax.custom_vjp, nondiff_argnums=(13, 14, 15, 16, 17, 18, 19, 20))
def pspmm_stale_ragged(x, halo_in, ghalo_in, base_in, rsend_idx,
                       ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                       redge_dst, redge_src, redge_w,
                       buckets, rr_sizes, rr_edge_sizes,
                       axis_name=AXIS, delta=False, wire_dtype=None,
                       gwire_dtype=None, fresh=False):
    """``PSpMM`` with a one-step-stale ROUND-STRUCTURED halo carry — the
    composition of ``pspmm_stale``'s pipelined contract with
    ``pspmm_ragged_sym``'s per-round ppermute ring.

    Forward: ``out = Â_local·x + fold(halo_in)`` where ``halo_in`` is the
    round-major receive-buffer carry exchanged during step t−1, and step
    t's k−1 per-round ppermutes are issued into ``halo_next`` with no
    in-step consumer.  Backward (symmetric Â): ``g_x = Â_local·g +
    fold(ghalo_in)`` and the fresh gradient ring exchange leaves through
    the ``ghalo_in`` cotangent channel — the same deliberate plumbing as
    ``pspmm_stale`` (differentiate the caller w.r.t. its ``ghalos`` carry
    and the "grad" that comes back IS next step's carry).  ``fresh=True``
    compiles the full-sync step: both carries are consumed fresh, which is
    f32-bit-identical to the exact ragged path (``tests/test_stale_ragged``
    pins the ``sync_every=1`` trajectory ``==`` the dense exact one).

    Returns ``(out, halo_next, base_next)``; the carries are aux outputs
    whose cotangents are structurally zero (they cross the step boundary).
    Symmetric-Â only, like every ragged/stale op.
    """
    return _pspmm_stale_ragged_once(
        x, halo_in, base_in, rsend_idx, ell_idx, ell_w,
        ltail_dst, ltail_src, ltail_w, redge_dst, redge_src, redge_w,
        buckets, rr_sizes, rr_edge_sizes, axis_name, delta, wire_dtype,
        fresh)


def _pspmm_stale_ragged_fwd(x, halo_in, ghalo_in, base_in, rsend_idx,
                            ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
                            redge_dst, redge_src, redge_w,
                            buckets, rr_sizes, rr_edge_sizes, axis_name,
                            delta, wire_dtype, gwire_dtype, fresh):
    out = _pspmm_stale_ragged_once(
        x, halo_in, base_in, rsend_idx, ell_idx, ell_w,
        ltail_dst, ltail_src, ltail_w, redge_dst, redge_src, redge_w,
        buckets, rr_sizes, rr_edge_sizes, axis_name, delta, wire_dtype,
        fresh)
    res = (ghalo_in, rsend_idx, ell_idx, ell_w, ltail_dst, ltail_src,
           ltail_w, redge_dst, redge_src, redge_w)
    return out, res


def _pspmm_stale_ragged_bwd(buckets, rr_sizes, rr_edge_sizes, axis_name,
                            delta, wire_dtype, gwire_dtype, fresh, res, cts):
    (ghalo_in, rsend_idx, ell_idx, ell_w, ltail_dst, ltail_src, ltail_w,
     redge_dst, redge_src, redge_w) = res
    g, _, _ = cts            # carry cotangents are structurally zero
    # step t's gradient ring exchange: full-value wire at gwire_dtype (the
    # delta cache is a feature-wire lever), no same-step consumer on stale
    # steps — it leaves through the ghalo_in cotangent channel
    gh_next, _ = _stale_ragged_exchange(
        g, ghalo_in, ghalo_in, rsend_idx, rr_sizes, axis_name, False,
        gwire_dtype, fresh)
    gh_used = gh_next if fresh else ghalo_in
    gx = (spmm_ell(ell_idx, ell_w, ltail_dst, ltail_src, ltail_w, g, buckets)
          + _stale_ragged_fold(gh_used, redge_dst, redge_src, redge_w,
                               rr_sizes, rr_edge_sizes, g.shape[0]))
    return (gx, None, gh_next, None, *[None] * 9)


pspmm_stale_ragged.defvjp(_pspmm_stale_ragged_fwd, _pspmm_stale_ragged_bwd)
