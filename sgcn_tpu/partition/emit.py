"""Partitioning artifact emitters — the L2 pipeline-stage file family.

The reference's offline partitioners hand data to the trainers only through
files (SURVEY.md §1):

  * GPU flavor: flat text part vector ``<name>.<k>.{gp,hp,rp}``
    (``GPU/graph/main.cpp:53-65``, ``GPU/hypergraph/main.cpp:51-63``) and the
    SHP pickle ``partvec.{hp,stchp}.<k>`` (``GPU/SHP/main.py:131-140``);
  * MPI flavor: per-rank files ``A.<r>`` / ``H.<r>`` / ``Y.<r>`` (matrix
    triplets with GLOBAL ids, ``GCN-HP/main.cpp:213-282``), the connectivity
    plan ``conn.<r>`` + buffer sizes ``buff.<r>`` (``:147-211``), and
    ``config`` (``:117-131``).

We emit the same family (formats documented per function — semantically
equivalent, 0-based ids). ``conn``/``buff`` contents are derived from the same
``build_comm_plan`` used at train time, which keeps the offline artifacts and
the runtime exchange consistent by construction.
"""

from __future__ import annotations

import os
import pickle

import numpy as np
import scipy.sparse as sp

from ..io.config import ModelConfig, write_config
from ..parallel.plan import build_comm_plan


# ---------------------------------------------------------------- part vectors
def write_partvec(path: str, pv: np.ndarray) -> None:
    """Flat whitespace-separated text (GPU flavor, ``GPU/graph/main.cpp:53-65``)."""
    with open(path, "w") as f:
        f.write(" ".join(str(int(p)) for p in pv) + "\n")


def read_partvec(path: str) -> np.ndarray:
    with open(path) as f:
        return np.array([int(t) for t in f.read().split()], dtype=np.int64)


def write_partvec_pickle(path: str, pv: np.ndarray) -> None:
    """Pickled list (SHP flavor, ``GPU/SHP/main.py:131-140``)."""
    with open(path, "wb") as f:
        pickle.dump([int(p) for p in pv], f)


def read_partvec_pickle(path: str) -> np.ndarray:
    with open(path, "rb") as f:
        return np.array(pickle.load(f), dtype=np.int64)


# ------------------------------------------------------------- per-rank family
def write_rank_files(outdir: str, a: sp.spmatrix,
                     y: sp.spmatrix, pv: np.ndarray, k: int,
                     cfg: ModelConfig) -> None:
    """Emit ``A.r / H.r / Y.r / conn.r / buff.r / config`` for r in 0..k-1.

    Formats (0-based ids, global shapes — locality lives in the nnz pattern,
    exactly as in the reference, ``Parallel-GCN/main.c:609-685``):

      * ``A.r``:   ``n nnz_r`` then ``i j v`` triplet lines (rows owned by r);
      * ``H.r``:   ``nrows`` then one global row id per line (owned rows) —
        like the reference's ``print_parts2`` (``GCN-HP/main.cpp:251-282``),
        ids only; the trainer synthesizes the feature rows
        (``Parallel-GCN/main.c:650-685``), so no feature values are stored;
      * ``Y.r``:   ``n nnz_r`` then ``i j v`` triplets of owned label rows;
      * ``conn.r``: ``nt`` then per target ``q cnt g1 ... gcnt`` — global ids
        of boundary rows r must send to q each layer;
      * ``buff.r``: ``ns`` then per source ``q cnt`` — rows r receives from q
        (recv buffer sizing, ``Parallel-GCN/main.c:456-504``);
      * ``config``: shared model config line.
    """
    os.makedirs(outdir, exist_ok=True)
    a = sp.coo_matrix(a)
    y = sp.coo_matrix(y)
    n = a.shape[0]
    pv = np.asarray(pv, dtype=np.int64)
    # id row order: the .r text formats assume local index == rank by
    # ascending global id within the part (Parallel-GCN reader contract)
    plan = build_comm_plan(sp.csr_matrix(a), pv, k, row_order="id")
    # local_idx ranks vertices by global id within each part, so owned[r]
    # (ascending global ids of r's vertices) maps local index -> global id
    owned = [np.where(pv == r)[0] for r in range(k)]

    arow_mask = [pv[a.row] == r for r in range(k)]
    yrow_mask = [pv[y.row] == r for r in range(k)]
    for r in range(k):
        am = arow_mask[r]
        with open(os.path.join(outdir, f"A.{r}"), "w") as f:
            f.write(f"{n} {int(am.sum())}\n")
            for i, j, v in zip(a.row[am], a.col[am], a.data[am]):
                f.write(f"{i} {j} {v:.8g}\n")
        with open(os.path.join(outdir, f"H.{r}"), "w") as f:
            f.write(f"{len(owned[r])}\n")
            for g in owned[r]:
                f.write(f"{g}\n")
        ym = yrow_mask[r]
        with open(os.path.join(outdir, f"Y.{r}"), "w") as f:
            f.write(f"{n} {int(ym.sum())}\n")
            for i, j, v in zip(y.row[ym], y.col[ym], y.data[ym]):
                f.write(f"{i} {j} {v:.8g}\n")
        # conn.r: send lists (targets); buff.r: recv sizes (sources)
        with open(os.path.join(outdir, f"conn.{r}"), "w") as f:
            targets = [q for q in range(k)
                       if q != r and plan.send_counts[r, q] > 0]
            f.write(f"{len(targets)}\n")
            for q in targets:
                cnt = plan.send_counts[r, q]
                gids = owned[r][plan.send_idx[r, q, :cnt]]
                f.write(f"{q} {cnt} " + " ".join(str(g) for g in gids) + "\n")
        with open(os.path.join(outdir, f"buff.{r}"), "w") as f:
            sources = [q for q in range(k)
                       if q != r and plan.send_counts[q, r] > 0]
            f.write(f"{len(sources)}\n")
            for q in sources:
                f.write(f"{q} {int(plan.send_counts[q, r])}\n")
    write_config(os.path.join(outdir, "config"), cfg)


def read_conn(path: str) -> dict[int, np.ndarray]:
    """conn.r → {target rank: global ids to send}."""
    out: dict[int, np.ndarray] = {}
    with open(path) as f:
        nt = int(f.readline())
        for _ in range(nt):
            toks = f.readline().split()
            q, cnt = int(toks[0]), int(toks[1])
            out[q] = np.array([int(t) for t in toks[2:2 + cnt]], dtype=np.int64)
    return out


def read_buff(path: str) -> dict[int, int]:
    """buff.r → {source rank: rows received}."""
    out: dict[int, int] = {}
    with open(path) as f:
        ns = int(f.readline())
        for _ in range(ns):
            q, cnt = f.readline().split()
            out[int(q)] = int(cnt)
    return out
