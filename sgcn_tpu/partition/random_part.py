"""Random partitioning — the reference's ``-r`` / ``.rp`` baseline flavor.

Reference: ``GCN-HP/main.cpp:133-145`` (uniform random assignment) and
``GPU/hypergraph/main.cpp:134-173`` (random with exact balance).
"""

from __future__ import annotations

import numpy as np


def random_partition(n: int, k: int, seed: int = 0) -> np.ndarray:
    """Uniform iid random part vector (may be unbalanced, like ``-r``)."""
    return np.random.default_rng(seed).integers(0, k, size=n).astype(np.int64)


def balanced_random_partition(n: int, k: int, seed: int = 0) -> np.ndarray:
    """Random permutation chopped into equal parts (exact balance)."""
    perm = np.random.default_rng(seed).permutation(n)
    part = np.empty(n, dtype=np.int64)
    part[perm] = np.arange(n) % k
    return part
