from .random_part import random_partition, balanced_random_partition

__all__ = ["random_partition", "balanced_random_partition"]
