from .random_part import random_partition, balanced_random_partition
from .native import (partition_graph, partition_hypergraph_colnet,
                     partition_hypergraph_colnet_cache)
from .emit import (
    read_buff, read_conn, read_partvec, read_partvec_pickle,
    write_partvec, write_partvec_pickle, write_rank_files,
)

__all__ = [
    "random_partition", "balanced_random_partition",
    "partition_graph", "partition_hypergraph_colnet",
    "partition_hypergraph_colnet_cache",
    "read_buff", "read_conn", "read_partvec", "read_partvec_pickle",
    "write_partvec", "write_partvec_pickle", "write_rank_files",
]
