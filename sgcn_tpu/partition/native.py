"""ctypes bindings for the native multilevel partitioners (native/sgcnpart.cpp).

Role parity: ``METIS_PartGraphKway`` as called by ``GCN-GP/main.cpp:334`` and
``GPU/graph/main.cpp:300-361`` (graph model, edge-cut objective), and
``PaToH_Part`` as called by ``GCN-HP/main.cpp:317-354`` / KaHyPar in
``GPU/SHP/main.py:17-32`` (column-net hypergraph model, connectivity-1 / km1
objective, cells weighted by row nnz).

The shared library is built on demand with ``make -C native`` (g++ only, no
third-party deps — we implement the multilevel algorithms ourselves).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np
import scipy.sparse as sp

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsgcnpart.so")

_lib = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    # always invoke make: the target is incremental, so this is a no-op when
    # fresh and rebuilds transparently after sgcnpart.cpp edits
    proc = subprocess.run(["make", "-C", _NATIVE_DIR, "libsgcnpart.so"],
                          capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native partitioner build failed:\n{proc.stdout}\n{proc.stderr}")
    lib = ctypes.CDLL(_LIB_PATH)
    lib.sgcn_partition_graph.restype = ctypes.c_int
    lib.sgcn_partition_graph.argtypes = [
        ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,   # adjwgt (nullable)
        ctypes.c_void_p,   # vwgt (nullable)
        ctypes.c_int, ctypes.c_double, ctypes.c_int,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.sgcn_partition_hypergraph.restype = ctypes.c_int
    lib.sgcn_partition_hypergraph.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,   # cwgt (nullable)
        ctypes.c_int, ctypes.c_double, ctypes.c_int,
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.sgcn_partition_hypergraph_cache.restype = ctypes.c_int
    lib.sgcn_partition_hypergraph_cache.argtypes = [
        ctypes.c_int32, ctypes.c_int32,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_void_p,   # cwgt (nullable)
        ctypes.c_int, ctypes.c_double, ctypes.c_int,
        ctypes.c_int32,    # replica_budget
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    _lib = lib
    return lib


def partition_graph(a: sp.spmatrix, k: int, imbalance: float = 0.03,
                    seed: int = 1) -> tuple[np.ndarray, int]:
    """Multilevel k-way graph partition of the symmetrized pattern of ``a``.

    Matches the reference pipeline: symmetrize, drop self-loops, unit edge
    weights (``GCN-GP/main.cpp:114-121``, ``GPU/graph/main.cpp:123-131``).
    Returns (partvec int64 (n,), edge cut).
    """
    a = sp.csr_matrix(a)
    n = a.shape[0]
    pat = a.copy()
    pat.data[:] = 1.0
    sym = ((pat + pat.T) > 0).astype(np.float32)
    sym.setdiag(0)
    sym.eliminate_zeros()
    sym = sp.csr_matrix(sym)
    lib = _load()
    part = np.empty(n, dtype=np.int32)
    cut = ctypes.c_int64(0)
    rc = lib.sgcn_partition_graph(
        n, sym.indptr.astype(np.int64), sym.indices.astype(np.int32),
        None, None, k, imbalance, seed, part, ctypes.byref(cut))
    if rc != 0:
        raise RuntimeError(f"sgcn_partition_graph failed rc={rc}")
    return part.astype(np.int64), int(cut.value)


def partition_hypergraph_colnet(a: sp.spmatrix, k: int,
                                imbalance: float = 0.03,
                                seed: int = 1) -> tuple[np.ndarray, int]:
    """Column-net hypergraph partition: cells = rows (weight = row nnz),
    nets = columns, km1/connectivity-1 objective (``GCN-HP/main.cpp:289-345``).

    Returns (partvec int64 (n,), km1 = Σ(λ−1)).
    """
    a = sp.csr_matrix(a)
    n, m = a.shape
    lib = _load()
    part = np.empty(n, dtype=np.int32)
    km1 = ctypes.c_int64(0)
    cwgt = np.maximum(np.diff(a.indptr), 1).astype(np.int64)
    rc = lib.sgcn_partition_hypergraph(
        n, m, a.indptr.astype(np.int64), a.indices.astype(np.int32),
        cwgt.ctypes.data_as(ctypes.c_void_p), k, imbalance, seed, part,
        ctypes.byref(km1))
    if rc != 0:
        raise RuntimeError(f"sgcn_partition_hypergraph failed rc={rc}")
    return part.astype(np.int64), int(km1.value)


def partition_hypergraph_colnet_cache(
        a: sp.spmatrix, k: int, replica_budget: int,
        imbalance: float = 0.03,
        seed: int = 1) -> tuple[np.ndarray, int, int]:
    """Cache-aware column-net partition (hot-halo replication,
    ``docs/replication.md``): the same RB/direct driver as
    ``partition_hypergraph_colnet``, then the cut is CO-OPTIMIZED with the
    replica budget — a net whose source vertex is replicated costs 0, so
    refinement under zeroed weights stops fighting the cache.

    Returns ``(partvec int64 (n,), km1, km1_cache)`` where ``km1_cache`` is
    km1 minus the top-``replica_budget`` nets' contribution (selection by
    (λ−1)·pins — the hypergraph face of the plan-time λ·degree ranking);
    by construction ``km1_cache`` <= the same objective evaluated on the
    cache-blind partition at equal seed/balance.
    """
    a = sp.csr_matrix(a)
    n, m = a.shape
    lib = _load()
    part = np.empty(n, dtype=np.int32)
    km1 = ctypes.c_int64(0)
    km1_cache = ctypes.c_int64(0)
    cwgt = np.maximum(np.diff(a.indptr), 1).astype(np.int64)
    rc = lib.sgcn_partition_hypergraph_cache(
        n, m, a.indptr.astype(np.int64), a.indices.astype(np.int32),
        cwgt.ctypes.data_as(ctypes.c_void_p), k, imbalance, seed,
        int(replica_budget), part, ctypes.byref(km1),
        ctypes.byref(km1_cache))
    if rc != 0:
        raise RuntimeError(
            f"sgcn_partition_hypergraph_cache failed rc={rc}")
    return part.astype(np.int64), int(km1.value), int(km1_cache.value)


def cache_aware_km1(a: sp.spmatrix, part: np.ndarray,
                    replica_budget: int) -> int:
    """Evaluate the cache-aware km1 objective of ANY partition — numpy
    mirror of the native ``cache_objective`` (unweighted nets, the
    column-net model's default): km1 = Σ_j (λ_j − 1) minus the
    contribution of the top-``replica_budget`` nets by (λ−1)·pins
    (deterministic net-id tie-break, like the native side).  The
    cache-blind arm of the bench A/B is scored with THIS, so the native
    co-optimizer's ≤ claim is checked against an independent
    implementation."""
    a = sp.csc_matrix(a)
    part = np.asarray(part)
    n_nets = a.shape[1]
    lam = np.zeros(n_nets, np.int64)
    pins = np.diff(a.indptr)
    for j in range(n_nets):
        rows = a.indices[a.indptr[j]: a.indptr[j + 1]]
        if len(rows):
            lam[j] = len(np.unique(part[rows]))
    contrib = np.maximum(lam - 1, 0)
    score = contrib * pins
    cut = np.nonzero(lam >= 2)[0]
    order = cut[np.lexsort((cut, -score[cut]))]
    chosen = order[: max(0, int(replica_budget))]
    return int(contrib.sum() - contrib[chosen].sum())
