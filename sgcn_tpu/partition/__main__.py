"""Partitioner CLI — role of the reference's four partitioner executables.

``python -m sgcn_tpu.partition -a A.mtx -k 8 -m hp``            → ``A.mtx.8.hp``
``python -m sgcn_tpu.partition -a A.mtx -k 8 -m gp,rp``         → both flavors
``python -m sgcn_tpu.partition -a A.mtx -k 2,3,9,15,21,27 -m hp,rp``
                                                → the reference run.sh k-sweep
``python -m sgcn_tpu.partition -a A.mtx -k 4 -m hp --rank-files out/ -y Y.mtx -l 2 --hidden 16``
                                                → A.r/H.r/Y.r/conn.r/buff.r/config

Reference analogues: ``GCN-HP`` (PaToH colnet + rank files), ``GCN-GP``
(METIS + rank files), ``GPU/graph`` (METIS partvec ``.gp`` + random ``.rp``),
``GPU/hypergraph`` (PaToH partvec ``.hp`` + ``.rp``), and the batch drivers
``GPU/{graph,hypergraph}/run.sh:1-13`` whose k-sweeps (k∈{1,2,3,9,27} /
{2,3,9,15,21,27}) are the ``-k`` comma-list form.  A native C++ CLI with
the same core (``native/sgcnpart``) is also built by ``make -C native``.
"""

from __future__ import annotations

import argparse
import time

import numpy as np

from ..io.config import ModelConfig
from ..io.mtx import read_mtx
from .emit import write_partvec, write_rank_files
from .random_part import balanced_random_partition


def main() -> None:
    p = argparse.ArgumentParser(description="sgcn_tpu partitioner")
    p.add_argument("-a", "--adjacency", required=True)
    p.add_argument("-k", "--nparts", required=True,
                   help="part count, or a comma list (k-sweep: the "
                        "reference's run.sh family, e.g. 2,3,9,15,21,27)")
    p.add_argument("-m", "--modes", default="hp",
                   help="comma list of gp|hp|rp (graph/hypergraph/random)")
    p.add_argument("-e", "--imbalance", type=float, default=0.03)
    p.add_argument("-s", "--seed", type=int, default=1)
    p.add_argument("-o", "--out-prefix", default=None,
                   help="default: <adjacency path>")
    p.add_argument("--rank-files", default=None,
                   help="also emit per-rank A.r/H.r/Y.r/conn.r/buff.r/config to this dir (first mode)")
    p.add_argument("-y", "--labels", default=None, help=".mtx labels for rank files")
    p.add_argument("-l", "--nlayers", type=int, default=2)
    p.add_argument("--hidden", type=int, default=16)
    args = p.parse_args()

    a = read_mtx(args.adjacency)
    n = a.shape[0]
    prefix = args.out_prefix or args.adjacency
    try:
        ks = [int(x) for x in str(args.nparts).split(",")]
    except ValueError:
        raise SystemExit(f"bad -k value {args.nparts!r}") from None
    first_pv = first_k = None
    for k in ks:
        for mode in args.modes.split(","):
            t0 = time.perf_counter()
            if mode == "gp":
                from .native import partition_graph
                pv, metric = partition_graph(a, k, args.imbalance, args.seed)
                mname = "edgecut"
            elif mode == "hp":
                from .native import partition_hypergraph_colnet
                pv, metric = partition_hypergraph_colnet(a, k, args.imbalance,
                                                         args.seed)
                mname = "km1"
            elif mode == "rp":
                pv = balanced_random_partition(n, k, args.seed)
                metric, mname = -1, "none"
            else:
                raise SystemExit(f"unknown mode {mode}")
            dt = time.perf_counter() - t0
            out = f"{prefix}.{k}.{mode}"
            write_partvec(out, pv)
            sizes = np.bincount(pv, minlength=k)
            print(f"{mode}: {out}  {mname}={metric}  max_part={sizes.max()}  "
                  f"time_s={dt:.3f}", flush=True)
            if first_pv is None:
                first_pv, first_k = pv, k

    if args.rank_files:
        import scipy.sparse as sp
        y = read_mtx(args.labels) if args.labels else sp.eye(n, 2, format="csr")
        nclasses = y.shape[1]
        cfg = ModelConfig(nlayers=args.nlayers, nvtx=n,
                          widths=[args.hidden] * (args.nlayers - 1) + [nclasses])
        write_rank_files(args.rank_files, a, y, first_pv, first_k, cfg)
        print(f"rank files → {args.rank_files}", flush=True)


if __name__ == "__main__":
    main()
