"""``python -m sgcn_tpu`` — entry-point directory for the tool family.

The reference ships seven separately-built executables (SURVEY.md §1); here
each role is a module CLI under one package.  This dispatcher only prints
the map — each tool owns its own flags (``--help`` on any of them).
"""

from __future__ import annotations

import sys

_TOOLS = (
    ("sgcn_tpu.prep", "normalize Â, emit A/H/Y.mtx + config "
                      "(preprocess/GrB-GNN-IDG.py role)"),
    ("sgcn_tpu.partition", "graph/hypergraph/random partitioner, part "
                           "vectors + per-rank files (GCN-GP/GCN-HP/"
                           "GPU partvec roles)"),
    ("sgcn_tpu.train", "distributed full-batch / mini-batch / GAT / "
                       "accuracy trainers (grbgcn + GPU/*.py roles)"),
    ("sgcn_tpu.shp", "stochastic hypergraph model (GPU/SHP role)"),
    ("sgcn_tpu.baselines", "oracle (DGL role) and cagnet (CAGNET role) "
                           "comparison baselines"),
    ("sgcn_tpu.serve", "AOT-compiled partitioned inference under "
                       "synthetic query traffic (docs/serving.md)"),
    ("sgcn_tpu.analysis", "static analysis: compiled-program contract "
                          "audit + AST hygiene (docs/static_analysis.md)"),
)


def main() -> int:
    # arguments mean a mistyped tool invocation (`python -m sgcn_tpu train`
    # instead of `python -m sgcn_tpu.train`) — fail loudly, don't no-op
    out = sys.stderr if len(sys.argv) > 1 else sys.stdout
    if len(sys.argv) > 1:
        print(f"unknown arguments {sys.argv[1:]} — the tools are separate "
              "modules:", file=out)
    else:
        print("sgcn_tpu — TPU-native partitioned GCN/GAT training\n",
              file=out)
    print("tools (run any with --help; see docs/MIGRATION.md for the "
          "reference-command map):", file=out)
    for mod, desc in _TOOLS:
        print(f"  python -m {mod:22s} {desc}", file=out)
    return 2 if len(sys.argv) > 1 else 0


if __name__ == "__main__":
    sys.exit(main())
