"""CLI: ``python -m sgcn_tpu.prep -a graph.mtx -o outdir -n name -l 2 -f 16 -c 2``.

Reference equivalent: ``python preprocess/GrB-GNN-IDG.py`` (same role in the
pipeline; see SURVEY.md §1 L1).
"""

import argparse

from ..io.mtx import read_mtx
from .normalize import preprocess


def main() -> None:
    p = argparse.ArgumentParser(description="sgcn_tpu input-data generator")
    p.add_argument("-a", "--adjacency", required=True, help="input .mtx graph")
    p.add_argument("-o", "--out", required=True, help="output directory")
    p.add_argument("-n", "--name", required=True, help="dataset name prefix")
    p.add_argument("-l", "--nlayers", type=int, default=2)
    p.add_argument("-f", "--hidden", type=int, default=16)
    p.add_argument("-c", "--nclasses", type=int, default=2)
    p.add_argument("-s", "--seed", type=int, default=0)
    args = p.parse_args()
    a = read_mtx(args.adjacency)
    cfg = preprocess(a, args.out, args.name, args.nlayers, args.hidden, args.nclasses, args.seed)
    print(f"wrote {args.name}.A/H/Y.mtx + config (n={cfg.nvtx}, widths={cfg.widths}) to {args.out}")


if __name__ == "__main__":
    main()
