"""Preprocessing: adjacency normalization + synthetic feature/label generation.

Capability parity with the reference input-data generator
(``preprocess/GrB-GNN-IDG.py``): strip existing self-loops, add the identity,
and symmetrically normalize ``Â = D_r^{-1/2} (A + I) D_c^{-1/2}``
(reference ``:45-68``); emit an all-ones feature matrix (``:72-74``) and a
2-column one-hot label matrix (``:76-78``); write ``<name>.{A,H,Y}.mtx`` plus
the ``config`` sidecar (``:80-88``).

Implementation is pure scipy/numpy (vectorized, no per-nnz Python loops).
"""

from __future__ import annotations

import os

import numpy as np
import scipy.sparse as sp

from ..io.config import ModelConfig, write_config
from ..io.mtx import write_mtx


def normalize_adjacency(a: sp.spmatrix, add_self_loops: bool = True) -> sp.csr_matrix:
    """``Â = D_r^{-1/2} (A + I) D_c^{-1/2}`` with prior self-loop stripping.

    ``D_r`` / ``D_c`` are the row / column degree (nnz-count) matrices of
    ``A + I`` — degree counts, not value sums, matching the reference which
    normalizes by the number of incident edges.
    """
    a = sp.csr_matrix(a, dtype=np.float32)
    a = a - sp.diags(a.diagonal())          # strip existing self-loops
    a.eliminate_zeros()
    if add_self_loops:
        a = (a + sp.eye(a.shape[0], dtype=np.float32, format="csr")).tocsr()
    coo = a.tocoo()
    # degree = number of structural nonzeros per row / column
    dr = np.bincount(coo.row, minlength=a.shape[0]).astype(np.float32)
    dc = np.bincount(coo.col, minlength=a.shape[1]).astype(np.float32)
    with np.errstate(divide="ignore"):
        dri = np.where(dr > 0, 1.0 / np.sqrt(dr), 0.0).astype(np.float32)
        dci = np.where(dc > 0, 1.0 / np.sqrt(dc), 0.0).astype(np.float32)
    vals = coo.data * dri[coo.row] * dci[coo.col]
    return sp.csr_matrix((vals, (coo.row, coo.col)), shape=a.shape)


def synthetic_features(n: int, f: int = 1) -> sp.csr_matrix:
    """All-ones n×f feature matrix (reference ``preprocess/GrB-GNN-IDG.py:72-74``)."""
    return sp.csr_matrix(np.ones((n, f), dtype=np.float32))


def synthetic_labels(n: int, nclasses: int = 2, seed: int = 0) -> sp.csr_matrix:
    """One-hot n×nclasses label matrix with a deterministic class assignment.

    The reference assigns each vertex one of two classes at random
    (``preprocess/GrB-GNN-IDG.py:76-78``); we use a seeded RNG for
    reproducibility.
    """
    rng = np.random.default_rng(seed)
    cls = rng.integers(0, nclasses, size=n)
    return sp.csr_matrix(
        (np.ones(n, dtype=np.float32), (np.arange(n), cls)), shape=(n, nclasses)
    )


def preprocess(
    a: sp.spmatrix,
    out_dir: str,
    name: str,
    nlayers: int = 2,
    hidden: int = 16,
    nclasses: int = 2,
    seed: int = 0,
) -> ModelConfig:
    """Full preprocessing pipeline: normalize, synthesize H/Y, write all artifacts.

    Produces ``<name>.A.mtx``, ``<name>.H.mtx``, ``<name>.Y.mtx`` and ``config``
    in ``out_dir`` — the file family every downstream stage of the reference
    pipeline consumes (``preprocess/GrB-GNN-IDG.py:80-88``).
    """
    os.makedirs(out_dir, exist_ok=True)
    n = a.shape[0]
    ahat = normalize_adjacency(a)
    h = synthetic_features(n)
    y = synthetic_labels(n, nclasses, seed)
    write_mtx(os.path.join(out_dir, f"{name}.A.mtx"), ahat)
    write_mtx(os.path.join(out_dir, f"{name}.H.mtx"), h)
    write_mtx(os.path.join(out_dir, f"{name}.Y.mtx"), y)
    widths = [hidden] * (nlayers - 1) + [nclasses]
    cfg = ModelConfig(nlayers=nlayers, nvtx=n, widths=widths)
    write_config(os.path.join(out_dir, "config"), cfg)
    return cfg
