from .normalize import normalize_adjacency, synthetic_features, synthetic_labels, preprocess

__all__ = ["normalize_adjacency", "synthetic_features", "synthetic_labels", "preprocess"]
