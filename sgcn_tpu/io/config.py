"""The ``config`` sidecar file: ``"nlayers nvtx f1 ... f_{L-1} nout"``.

Format defined by the reference preprocessor (``preprocess/GrB-GNN-IDG.py:84-88``)
and partitioner (``GCN-HP/main.cpp:117-131``), consumed by the trainers
(``Parallel-GCN/main.c:687-714``).  Note the reference's quirk: ``nneurons[0]``
is the vertex count and layer widths are offset by one; we store the semantic
fields explicitly and can emit/parse the legacy line exactly.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class ModelConfig:
    nlayers: int          # number of GCN layers
    nvtx: int             # number of vertices (global)
    widths: list[int] = field(default_factory=list)  # f1 ... f_{L-1}, nout

    @property
    def nout(self) -> int:
        return self.widths[-1]

    def layer_dims(self, fin: int) -> list[tuple[int, int]]:
        """(in, out) dims per layer given the input feature width."""
        dims = [fin] + list(self.widths)
        return list(zip(dims[:-1], dims[1:]))


def read_config(path: str) -> ModelConfig:
    with open(path) as f:
        toks = f.read().split()
    nlayers, nvtx = int(toks[0]), int(toks[1])
    widths = [int(t) for t in toks[2:]]
    return ModelConfig(nlayers=nlayers, nvtx=nvtx, widths=widths)


def write_config(path: str, cfg: ModelConfig) -> None:
    toks = [str(cfg.nlayers), str(cfg.nvtx)] + [str(w) for w in cfg.widths]
    with open(path, "w") as f:
        f.write(" ".join(toks) + "\n")
