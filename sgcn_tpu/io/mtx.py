"""MatrixMarket coordinate I/O.

The whole reference pipeline communicates through MatrixMarket files on disk
(adjacency ``<name>.A.mtx``, features ``<name>.H.mtx``, labels ``<name>.Y.mtx``;
see ``preprocess/GrB-GNN-IDG.py:80-88`` in the reference).  We use
``scipy.io.mmread``-compatible semantics but keep our own thin reader/writer so
that (a) pattern and symmetric files round-trip deterministically and (b) there
is no dependency beyond scipy.
"""

from __future__ import annotations

import numpy as np
import scipy.io
import scipy.sparse as sp


def read_mtx(path: str) -> sp.csr_matrix:
    """Read a MatrixMarket file into CSR float32.

    Symmetric / skew / pattern storage is expanded (mirrors the reference's
    readers, which honor the symmetric qualifier — ``GCN-HP/main.cpp:366-405``).
    Pattern files get all-ones values.

    scipy ≥1.12's mmread is the multithreaded fast_matrix_market C++ parser —
    measured FASTER than a hand-rolled single-threaded native reader here, so
    it IS the native-loader path (the role of the reference's C readers,
    ``Parallel-GCN/main.c:609-648``).  The C++ CLI has its own buffer-scanning
    parser (``native/sgcnpart.cpp`` ``sgcn_read_mtx``) for fully-native runs.
    """
    m = scipy.io.mmread(path)
    m = sp.csr_matrix(m, dtype=np.float32)
    m.sum_duplicates()
    return m


def write_mtx(path: str, m: sp.spmatrix, comment: str = "") -> None:
    """Write CSR/COO to MatrixMarket coordinate general format (1-based)."""
    scipy.io.mmwrite(path, sp.coo_matrix(m), comment=comment, precision=8)


def read_dense_features(path: str) -> np.ndarray:
    """Read an ``H.mtx`` feature matrix as dense (n, f) float32 — the form
    every trainer consumes (``GPU/PGCN.py:186-188`` builds H dense)."""
    return np.asarray(read_mtx(path).todense(), np.float32)


def read_onehot_labels(path: str) -> np.ndarray:
    """Read a ``Y.mtx`` one-hot label matrix as (n,) int32 class ids
    (the preprocessor writes one-hot rows, ``preprocess/GrB-GNN-IDG.py:76-78``)."""
    return np.asarray(read_mtx(path).todense()).argmax(1).astype(np.int32)
