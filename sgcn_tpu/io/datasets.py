"""Built-in datasets and graph generators for tests, fixtures, and benches.

The reference ships tiny fixture graphs for smoke tests (karate at
``GPU/SHP/data/karate/karate.mtx`` — 34 vertices; gemat11 at
``GPU/hypergraph/data/gemat11/``) and pulls real benchmark graphs from
sparse.tamu.edu / OGB as ``.mtx`` (``README.md:11``).  Zero-egress here, so:

  * ``karate()`` — Zachary's karate club (public-domain 1977 sociogram, the
    same graph as the reference's fixture) built from the edge list, with the
    standard instructor/administrator faction labels;
  * ``planted_partition()`` — learnable community graphs for accuracy tests;
  * ``er_graph()`` — ogbn-scale synthetic graphs for benchmarking (the shape
    stand-in for ogbn-arxiv/products when the real download is unavailable);
  * ``save_fixture()`` — emit any of them as ``.mtx`` (+ labels) for CLI
    round-trip tests.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

# Zachary karate club, 0-indexed undirected edges (public-domain data).
_KARATE_EDGES = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31),
    (1, 2), (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30),
    (2, 3), (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32),
    (3, 7), (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16),
    (6, 16), (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]

# community membership after the split (0 = instructor's faction).
_KARATE_LABELS = np.array(
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 0, 0, 0, 0, 1, 1, 0, 0, 1, 0, 1, 0,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1], dtype=np.int32)


def karate() -> tuple[sp.csr_matrix, np.ndarray]:
    """(adjacency, labels) — 34 vertices, 78 undirected edges."""
    e = np.array(_KARATE_EDGES, dtype=np.int64)
    row = np.concatenate([e[:, 0], e[:, 1]])
    col = np.concatenate([e[:, 1], e[:, 0]])
    a = sp.csr_matrix(
        (np.ones(len(row), np.float32), (row, col)), shape=(34, 34))
    return a, _KARATE_LABELS.copy()


def planted_partition(n: int = 96, nclasses: int = 3, p_in: float = 0.25,
                      p_out: float = 0.02, noise: float = 0.4,
                      seed: int = 0):
    """Community graph + noisy one-hot features a GCN can learn.

    Returns (adjacency, features, labels).
    """
    rng = np.random.default_rng(seed)
    labels = (np.arange(n) % nclasses).astype(np.int32)
    prob = np.where(labels[:, None] == labels[None, :], p_in, p_out)
    dense = rng.random((n, n)) < prob
    dense = np.triu(dense, 1)
    dense = dense | dense.T
    a = sp.csr_matrix(dense.astype(np.float32))
    feats = np.eye(nclasses, dtype=np.float32)[labels]
    feats = feats + rng.normal(0, noise, (n, nclasses)).astype(np.float32)
    return a, feats, labels


def er_graph(n: int, avg_deg: int = 14, seed: int = 0) -> sp.csr_matrix:
    """Random symmetric graph with ~n·avg_deg/2 edges (benchmark stand-in
    for the ogbn-* graphs when offline)."""
    rng = np.random.default_rng(seed)
    m = n * avg_deg // 2
    src = rng.integers(0, n, size=m)
    dst = rng.integers(0, n, size=m)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    a = sp.coo_matrix((np.ones(len(src), np.float32), (src, dst)), shape=(n, n))
    return sp.csr_matrix(((a + a.T) > 0).astype(np.float32))


def save_fixture(prefix: str, a: sp.spmatrix,
                 labels: np.ndarray | None = None) -> dict[str, str]:
    """Write ``<prefix>.A.mtx`` (normalized Â) and optionally ``<prefix>.Y.mtx``
    (one-hot labels) — the preprocessor's output family
    (``preprocess/GrB-GNN-IDG.py:80-88``)."""
    from ..prep import normalize_adjacency
    from .mtx import write_mtx
    paths = {}
    ahat = normalize_adjacency(sp.csr_matrix(a))
    write_mtx(f"{prefix}.A.mtx", ahat)
    paths["A"] = f"{prefix}.A.mtx"
    if labels is not None:
        n = len(labels)
        nclasses = int(labels.max()) + 1
        y = sp.csr_matrix(
            (np.ones(n, np.float32), (np.arange(n), labels)),
            shape=(n, nclasses))
        write_mtx(f"{prefix}.Y.mtx", y)
        paths["Y"] = f"{prefix}.Y.mtx"
    return paths
