"""Built-in datasets and graph generators for tests, fixtures, and benches.

The reference ships tiny fixture graphs for smoke tests (karate at
``GPU/SHP/data/karate/karate.mtx`` — 34 vertices; gemat11 at
``GPU/hypergraph/data/gemat11/``) and pulls real benchmark graphs from
sparse.tamu.edu / OGB as ``.mtx`` (``README.md:11``).  Zero-egress here, so:

  * ``karate()`` — Zachary's karate club (public-domain 1977 sociogram, the
    same graph as the reference's fixture) built from the edge list, with the
    standard instructor/administrator faction labels;
  * ``planted_partition()`` — learnable community graphs for accuracy tests;
  * ``er_graph()`` — ogbn-scale synthetic graphs for benchmarking (the shape
    stand-in for ogbn-arxiv/products when the real download is unavailable);
  * ``cora_like()`` — citation-style graph with sparse binary bag-of-words
    features in cora's exact format (the reference's accuracy experiment runs
    on cora, ``GPU/PGCN-Accuracy.py`` / ``README.md:110``);
  * ``load_npz_dataset()`` / ``save_npz_dataset()`` — the on-disk ``.npz``
    layout real planetoid/ogbn snapshots ship in (``adj_*`` CSR triplets +
    ``attr_*`` + ``labels``), so a user with a downloaded ``cora.npz`` /
    ``ogbn-arxiv`` snapshot feeds it straight to the trainers;
  * ``planetoid_split()`` — the fixed per-class train / held-out test split
    semantics of the planetoid benchmarks;
  * ``save_fixture()`` — emit any of them as the ``.mtx`` family
    (``A/H/Y``) the reference's pipeline communicates through.
"""

from __future__ import annotations

import numpy as np
import scipy.sparse as sp

# Zachary karate club, 0-indexed undirected edges (public-domain data).
_KARATE_EDGES = [
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8), (0, 10),
    (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21), (0, 31),
    (1, 2), (1, 3), (1, 7), (1, 13), (1, 17), (1, 19), (1, 21), (1, 30),
    (2, 3), (2, 7), (2, 8), (2, 9), (2, 13), (2, 27), (2, 28), (2, 32),
    (3, 7), (3, 12), (3, 13), (4, 6), (4, 10), (5, 6), (5, 10), (5, 16),
    (6, 16), (8, 30), (8, 32), (8, 33), (9, 33), (13, 33), (14, 32), (14, 33),
    (15, 32), (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32), (23, 33),
    (24, 25), (24, 27), (24, 31), (25, 31), (26, 29), (26, 33), (27, 33),
    (28, 31), (28, 33), (29, 32), (29, 33), (30, 32), (30, 33), (31, 32),
    (31, 33), (32, 33),
]

# community membership after the split (0 = instructor's faction).
_KARATE_LABELS = np.array(
    [0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 0, 0, 0, 0, 1, 1, 0, 0, 1, 0, 1, 0,
     1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1], dtype=np.int32)


def karate() -> tuple[sp.csr_matrix, np.ndarray]:
    """(adjacency, labels) — 34 vertices, 78 undirected edges."""
    e = np.array(_KARATE_EDGES, dtype=np.int64)
    row = np.concatenate([e[:, 0], e[:, 1]])
    col = np.concatenate([e[:, 1], e[:, 0]])
    a = sp.csr_matrix(
        (np.ones(len(row), np.float32), (row, col)), shape=(34, 34))
    return a, _KARATE_LABELS.copy()


def planted_partition(n: int = 96, nclasses: int = 3, p_in: float = 0.25,
                      p_out: float = 0.02, noise: float = 0.4,
                      seed: int = 0):
    """Community graph + noisy one-hot features a GCN can learn.

    Returns (adjacency, features, labels).
    """
    rng = np.random.default_rng(seed)
    labels = (np.arange(n) % nclasses).astype(np.int32)
    prob = np.where(labels[:, None] == labels[None, :], p_in, p_out)
    dense = rng.random((n, n)) < prob
    dense = np.triu(dense, 1)
    dense = dense | dense.T
    a = sp.csr_matrix(dense.astype(np.float32))
    feats = np.eye(nclasses, dtype=np.float32)[labels]
    feats = feats + rng.normal(0, noise, (n, nclasses)).astype(np.float32)
    return a, feats, labels


def er_graph(n: int, avg_deg: int = 14, seed: int = 0) -> sp.csr_matrix:
    """Random symmetric graph with ~n·avg_deg/2 edges (benchmark stand-in
    for the ogbn-* graphs when offline)."""
    rng = np.random.default_rng(seed)
    m = n * avg_deg // 2
    src = rng.integers(0, n, size=m)
    dst = rng.integers(0, n, size=m)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    a = sp.coo_matrix((np.ones(len(src), np.float32), (src, dst)), shape=(n, n))
    return sp.csr_matrix(((a + a.T) > 0).astype(np.float32))


def dcsbm_graph(n: int, ncomm: int = 64, avg_deg: int = 14,
                p_in: float = 0.85, alpha: float = 2.5,
                seed: int = 0) -> sp.csr_matrix:
    """Degree-corrected stochastic block model: power-law degrees AND
    planted community structure — the closest synthetic stand-in for the
    real ogbn graphs, which have BOTH (``ba_graph`` has the degree tail but
    is an expander: no partitioner can beat random by much there, measured
    1.07× at products scale; real ogbn-products partitions well because of
    its community structure).

    Vertices get Pareto(α) degree propensities; each edge endpoint is drawn
    ∝ propensity, with the partner drawn from the same community with
    probability ``p_in`` (else uniform across the graph).  Fully vectorized.
    """
    rng = np.random.default_rng(seed)
    comm = rng.integers(0, ncomm, size=n)
    w = rng.pareto(alpha, size=n) + 1.0          # degree propensities
    m = n * avg_deg // 2
    # endpoint sampling ∝ w, globally and within each community
    order = np.argsort(comm, kind="stable")      # community-contiguous view
    wc = w[order]
    starts = np.searchsorted(comm[order], np.arange(ncomm + 1))
    cum = np.cumsum(wc)
    cum_tot = cum[-1]
    src = order[np.searchsorted(cum, rng.random(m) * cum_tot)]
    intra = rng.random(m) < p_in
    # intra partner: inverse-CDF restricted to src's community slice
    lo, hi = starts[comm[src]], starts[comm[src] + 1]
    c_lo = np.where(lo > 0, cum[lo - 1], 0.0)
    c_hi = cum[hi - 1]
    pick = c_lo + rng.random(m) * (c_hi - c_lo)
    dst_in = order[np.searchsorted(cum, pick)]
    dst_out = order[np.searchsorted(cum, rng.random(m) * cum_tot)]
    dst = np.where(intra, dst_in, dst_out)
    keep = src != dst
    a = sp.coo_matrix((np.ones(keep.sum(), np.float32),
                       (src[keep], dst[keep])), shape=(n, n))
    return sp.csr_matrix(((a + a.T) > 0).astype(np.float32))


def ba_graph(n: int, m: int = 7, seed: int = 0) -> sp.csr_matrix:
    """Preferential-attachment (Barabási–Albert) graph: ~n·m edges with a
    power-law degree tail — the degree profile of the real ogbn-*/citation
    graphs the reference benchmarks on, and the one the degree-bucketed ELL
    layout (``parallel/plan.py``) is designed around; ``er_graph`` has no
    hubs, so only this generator exercises the hub-spill machinery at
    benchmark scale.

    Vectorized attachment: each new vertex draws ``m`` targets uniformly
    from the running endpoint list (endpoint frequency ∝ degree — the
    standard repeated-nodes trick), built in geometric batches so the
    Python-level loop is O(log n) long.
    """
    rng = np.random.default_rng(seed)
    if n <= m:
        raise ValueError(f"need n > m (got n={n}, m={m})")
    # seed: an (m+1)-vertex chain — vertex i attaches to i-1 (any connected
    # seed works; degrees equalize within a few batches)
    src = [np.arange(1, m + 1)]
    dst = [np.arange(0, m)]
    endpoints = [np.concatenate(src + dst)]
    count = m + 1
    while count < n:
        batch = min(max(count // 2, 1), n - count)   # grow geometrically
        pool = np.concatenate(endpoints)
        # new vertices in this batch attach to endpoints sampled from the
        # pool frozen at the batch start (a standard batched approximation
        # of sequential preferential attachment)
        new = np.repeat(np.arange(count, count + batch), m)
        # pool ids are all < count <= every new id, so no new vertex can be
        # drawn as its own (or a same-batch) target
        targets = pool[rng.integers(0, len(pool), size=batch * m)]
        src.append(new)
        dst.append(targets)
        endpoints.append(np.concatenate([new, targets]))
        count += batch
    s = np.concatenate(src)
    d = np.concatenate(dst)
    keep = s != d
    a = sp.coo_matrix((np.ones(keep.sum(), np.float32), (s[keep], d[keep])),
                      shape=(n, n))
    return sp.csr_matrix(((a + a.T) > 0).astype(np.float32))


def cora_like(n: int = 600, nclasses: int = 7, vocab: int = 64,
              words_per_doc: int = 12, avg_deg: int = 4,
              p_intra: float = 0.9, seed: int = 0):
    """Citation-network generator in cora's exact data format.

    Cora (the reference's accuracy-experiment dataset,
    ``GPU/PGCN-Accuracy.py`` / ``README.md:110``) is 2708 papers, 7 classes,
    sparse binary bag-of-words features over a 1433-word vocabulary, citation
    edges mostly intra-topic.  Zero egress forbids downloading it, so this
    reproduces the *format and learnability structure*: each class has a
    preferred word subset (a topic), each document samples ``words_per_doc``
    words from a mixture of its topic and the background, and citations
    attach preferentially within class with a heavy-tailed degree profile.

    Returns ``(adjacency csr, features csr binary (n, vocab), labels int32)``.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, nclasses, size=n).astype(np.int32)
    # topic word distributions: each class concentrates on vocab/nclasses words
    word_logits = np.full((nclasses, vocab), 0.1)
    block = vocab // nclasses
    for c in range(nclasses):
        word_logits[c, c * block:(c + 1) * block] = 3.0
    word_p = np.exp(word_logits)
    word_p /= word_p.sum(axis=1, keepdims=True)
    rows, cols = [], []
    for i in range(n):
        w = rng.choice(vocab, size=words_per_doc, replace=False,
                       p=word_p[labels[i]])
        rows.extend([i] * len(w))
        cols.extend(w)
    feats = sp.csr_matrix(
        (np.ones(len(rows), np.float32), (rows, cols)), shape=(n, vocab))
    feats.sum_duplicates()
    feats.data[:] = 1.0                      # binary bag-of-words, like cora
    # citations: preferential attachment within class (heavy-tailed degrees)
    m = n * avg_deg // 2
    src = rng.integers(0, n, size=2 * m)
    # heavy tail: square a uniform to bias destinations toward low ids
    dst_pool = (rng.random(2 * m) ** 2 * n).astype(np.int64)
    intra = rng.random(2 * m) < p_intra
    same = labels[src] == labels[dst_pool]
    keep = (src != dst_pool) & (intra == same)
    src, dst = src[keep][:m], dst_pool[keep][:m]
    a = sp.coo_matrix((np.ones(len(src), np.float32), (src, dst)),
                      shape=(n, n))
    a = sp.csr_matrix(((a + a.T) > 0).astype(np.float32))
    return a, feats, labels


def planetoid_split(labels: np.ndarray, per_class: int = 20,
                    ntest: int = 1000, seed: int = 0):
    """Planetoid split semantics: ``per_class`` train nodes per class, a
    held-out test block of ``ntest`` nodes, the rest unused (the reference's
    cora run uses this fixed-split protocol; its synthetic-bench splits are
    random batches, ``GPU/PGCN-Accuracy.py:228-251``).

    Returns ``(train_mask, test_mask)`` float32 0/1 vectors.
    """
    labels = np.asarray(labels)
    n = len(labels)
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n)
    train = np.zeros(n, np.float32)
    for c in np.unique(labels):
        picks = perm[labels[perm] == c][:per_class]
        train[picks] = 1.0
    test = np.zeros(n, np.float32)
    pool = perm[train[perm] == 0.0]
    test[pool[-min(ntest, len(pool)):]] = 1.0
    return train, test


# on-disk .npz layout used by the public planetoid/ogbn snapshot dumps
# (CSR triplets for the graph and the sparse attribute matrix + labels)
_NPZ_ADJ = ("adj_data", "adj_indices", "adj_indptr", "adj_shape")
_NPZ_ATTR = ("attr_data", "attr_indices", "attr_indptr", "attr_shape")


def save_npz_dataset(path: str, a: sp.spmatrix, features, labels) -> None:
    """Write the standard sparse-graph ``.npz`` snapshot layout."""
    a = sp.csr_matrix(a)
    arrs = dict(zip(_NPZ_ADJ, (a.data, a.indices, a.indptr, a.shape)))
    if sp.issparse(features):
        f = sp.csr_matrix(features)
        arrs.update(zip(_NPZ_ATTR, (f.data, f.indices, f.indptr, f.shape)))
    else:
        arrs["attr_matrix"] = np.asarray(features, np.float32)
    arrs["labels"] = np.asarray(labels)
    np.savez_compressed(path, **arrs)


def load_npz_dataset(path: str):
    """Read a planetoid/ogbn-style ``.npz`` snapshot.

    Accepts both sparse (``attr_data/indices/indptr/shape``) and dense
    (``attr_matrix``) feature storage, the two layouts the public snapshot
    dumps use.  Returns ``(adjacency csr, features float32 ndarray, labels
    int32)`` — features densified because the trainers consume dense rows.
    """
    adj_data, adj_indices, adj_indptr, adj_shape = _NPZ_ADJ
    attr_data, attr_indices, attr_indptr, attr_shape = _NPZ_ATTR
    with np.load(path, allow_pickle=False) as z:
        a = sp.csr_matrix(
            (z[adj_data], z[adj_indices], z[adj_indptr]),
            shape=tuple(z[adj_shape]))
        if "attr_matrix" in z:
            feats = np.asarray(z["attr_matrix"], np.float32)
        else:
            feats = np.asarray(sp.csr_matrix(
                (z[attr_data], z[attr_indices], z[attr_indptr]),
                shape=tuple(z[attr_shape])).todense(), np.float32)
        labels = np.asarray(z["labels"]).astype(np.int32)
    a = sp.csr_matrix(a, dtype=np.float32)
    a.sum_duplicates()
    return a, feats, labels


def save_fixture(prefix: str, a: sp.spmatrix,
                 labels: np.ndarray | None = None,
                 features=None) -> dict[str, str]:
    """Write ``<prefix>.A.mtx`` (normalized Â) and optionally ``<prefix>.H.mtx``
    (features) / ``<prefix>.Y.mtx`` (one-hot labels) — the preprocessor's
    output family (``preprocess/GrB-GNN-IDG.py:80-88``)."""
    from ..prep import normalize_adjacency
    from .mtx import write_mtx
    paths = {}
    ahat = normalize_adjacency(sp.csr_matrix(a))
    write_mtx(f"{prefix}.A.mtx", ahat)
    paths["A"] = f"{prefix}.A.mtx"
    if features is not None:
        write_mtx(f"{prefix}.H.mtx", sp.csr_matrix(features))
        paths["H"] = f"{prefix}.H.mtx"
    if labels is not None:
        n = len(labels)
        nclasses = int(labels.max()) + 1
        y = sp.csr_matrix(
            (np.ones(n, np.float32), (np.arange(n), labels)),
            shape=(n, nclasses))
        write_mtx(f"{prefix}.Y.mtx", y)
        paths["Y"] = f"{prefix}.Y.mtx"
    return paths
