from .mtx import read_mtx, write_mtx
from .config import ModelConfig, read_config, write_config

__all__ = ["read_mtx", "write_mtx", "ModelConfig", "read_config", "write_config"]
