// sgcnpart — multilevel k-way graph and column-net hypergraph partitioners.
//
// TPU-era replacement for the capabilities the reference gets from vendored
// METIS (GCN-GP/main.cpp:290-348, METIS_PartGraphKway, edge-cut objective) and
// PaToH/KaHyPar (GCN-HP/main.cpp:284-356, column-net model, connectivity-1
// objective).  We cannot redistribute those libraries, so this is our own
// implementation of the same algorithm family:
//
//   graph:      heavy-edge-matching coarsening -> greedy k-way growing on the
//               coarsest graph -> greedy boundary refinement on each level
//               (edge-cut objective, balance constraint).
//   hypergraph: heavy-connectivity matching on cells -> greedy growing ->
//               boundary FM-style km1 refinement with per-net part-pin counts
//               (connectivity-1 objective; cells = matrix rows weighted by
//               nnz, nets = columns — the column-net model of the reference).
//
// Exposed as a C ABI for ctypes (sgcn_tpu/partition/native.py) and as a small
// CLI (main() at the bottom) mirroring the reference partitioner executables.
//
// Quality bar (SURVEY.md §7.1): self-reported cut / lambda-1 must beat random
// partitioning by a wide margin and respect the balance constraint; bit-parity
// with METIS/PaToH is a non-goal.

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <numeric>
#include <queue>
#include <string>
#include <tuple>
#include <unordered_map>
#include <vector>

namespace {

using i32 = int32_t;
using i64 = int64_t;

// Portable deterministic RNG (splitmix64).  std::shuffle /
// std::uniform_int_distribution are implementation-defined mappings, so
// seeded partitions would differ across standard libraries; every draw here
// is pinned to this generator instead.
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next() {
    s += 0x9E3779B97F4A7C15ULL;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  // uniform in [0, n); modulo bias is irrelevant at these magnitudes
  uint64_t below(uint64_t n) { return n ? next() % n : 0; }
};

template <typename T>
void fy_shuffle(std::vector<T>& v, Rng& rng) {
  for (size_t i = v.size(); i > 1; --i)
    std::swap(v[i - 1], v[rng.below(i)]);
}

struct Graph {
  i32 n = 0;
  std::vector<i64> xadj;    // n+1
  std::vector<i32> adj;     // neighbor ids
  std::vector<float> wgt;   // edge weights
  std::vector<i64> vwgt;    // vertex weights
  i64 total_vwgt = 0;
};

// ---------------------------------------------------------------- coarsening
struct MatchResult {
  std::vector<i32> cmap;    // fine vertex -> coarse vertex
  i32 cn = 0;
};

MatchResult heavy_edge_matching(const Graph& g, Rng& rng) {
  std::vector<i32> order(g.n);
  std::iota(order.begin(), order.end(), 0);
  fy_shuffle(order, rng);
  std::vector<i32> match(g.n, -1);
  for (i32 v : order) {
    if (match[v] != -1) continue;
    i32 best = -1;
    float best_w = -1.0f;
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
      i32 u = g.adj[e];
      if (u == v || match[u] != -1) continue;
      if (g.wgt[e] > best_w) { best_w = g.wgt[e]; best = u; }
    }
    if (best != -1) { match[v] = best; match[best] = v; }
    else match[v] = v;
  }
  MatchResult r;
  r.cmap.assign(g.n, -1);
  for (i32 v = 0; v < g.n; ++v) {
    if (r.cmap[v] != -1) continue;
    i32 u = match[v];
    r.cmap[v] = r.cn;
    if (u != v && u != -1) r.cmap[u] = r.cn;
    ++r.cn;
  }
  return r;
}

Graph contract(const Graph& g, const MatchResult& m) {
  Graph c;
  c.n = m.cn;
  c.vwgt.assign(m.cn, 0);
  for (i32 v = 0; v < g.n; ++v) c.vwgt[m.cmap[v]] += g.vwgt[v];
  c.total_vwgt = g.total_vwgt;
  c.xadj.assign(m.cn + 1, 0);
  // bucket fine vertices by coarse id
  std::vector<i32> fine_of(g.n);
  std::vector<i64> cstart(m.cn + 1, 0);
  for (i32 v = 0; v < g.n; ++v) cstart[m.cmap[v] + 1]++;
  for (i32 cv = 0; cv < m.cn; ++cv) cstart[cv + 1] += cstart[cv];
  {
    std::vector<i64> pos(cstart.begin(), cstart.end() - 1);
    for (i32 v = 0; v < g.n; ++v) fine_of[pos[m.cmap[v]]++] = v;
  }
  std::unordered_map<i32, float> nbr;
  nbr.reserve(256);
  for (i32 cv = 0; cv < m.cn; ++cv) {
    nbr.clear();
    for (i64 p = cstart[cv]; p < cstart[cv + 1]; ++p) {
      i32 v = fine_of[p];
      for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
        i32 cu = m.cmap[g.adj[e]];
        if (cu == cv) continue;
        nbr[cu] += g.wgt[e];
      }
    }
    c.xadj[cv + 1] = c.xadj[cv] + (i64)nbr.size();
    for (auto& kv : nbr) { c.adj.push_back(kv.first); c.wgt.push_back(kv.second); }
  }
  return c;
}

// ------------------------------------------------------- initial partitioning
// Greedy k-way growing: spread seeds, grow parts by absorbing the frontier
// vertex with the strongest connection to the part, under the balance cap.
void greedy_grow(const Graph& g, int k, double cap, std::vector<i32>& part,
                 Rng& rng) {
  part.assign(g.n, -1);
  std::vector<i64> pw(k, 0);
  std::vector<float> conn(g.n, 0.0f);   // connection of v to the growing part
  std::vector<i32> order(g.n);
  std::iota(order.begin(), order.end(), 0);
  fy_shuffle(order, rng);
  size_t cursor = 0;
  for (int p = 0; p < k; ++p) {
    // seed: first unassigned vertex in the shuffled order
    while (cursor < order.size() && part[order[cursor]] != -1) ++cursor;
    if (cursor >= order.size()) break;
    i32 seed = order[cursor];
    std::fill(conn.begin(), conn.end(), 0.0f);
    std::vector<i32> frontier{seed};
    part[seed] = p; pw[p] += g.vwgt[seed];
    // grow until this part reaches total/k (leave slack for the last parts)
    i64 target = g.total_vwgt / k;
    while (pw[p] < target) {
      // refresh connections from newly absorbed vertices
      for (i32 v : frontier)
        for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
          i32 u = g.adj[e];
          if (part[u] == -1) conn[u] += g.wgt[e];
        }
      frontier.clear();
      // pick best-connected unassigned vertex (linear scan; coarsest graph is small)
      i32 best = -1; float best_c = -1.0f;
      for (i32 u = 0; u < g.n; ++u)
        if (part[u] == -1 && conn[u] > best_c) { best_c = conn[u]; best = u; }
      if (best == -1 || best_c <= 0.0f) {
        // disconnected: jump to any unassigned vertex
        for (i32 u = 0; u < g.n; ++u) if (part[u] == -1) { best = u; break; }
        if (best == -1) break;
      }
      if (pw[p] + g.vwgt[best] > (i64)(cap)) break;
      part[best] = p; pw[p] += g.vwgt[best];
      frontier.push_back(best);
    }
  }
  // leftovers -> lightest part
  for (i32 v = 0; v < g.n; ++v)
    if (part[v] == -1) {
      int lp = (int)(std::min_element(pw.begin(), pw.end()) - pw.begin());
      part[v] = lp; pw[lp] += g.vwgt[v];
    }
}

// ------------------------------------------------------------- refinement
// Edge-cut refinement state shared by the sweep and FM phases — the graph-side
// mirror of Km1Refiner below (same structure: greedy boundary sweeps carry the
// bulk, a lazy-heap FM hill-climbing pass escapes local minima where size
// affords it).  Role parity: the refinement inside METIS_PartGraphKway
// (GCN-GP/main.cpp:334) is this same KL/FM family.
struct CutRefiner {
  const Graph& g;
  const int k;
  const double cap;
  std::vector<i32>& part;
  std::vector<i64> pw;
  std::vector<float> conn;   // scratch: weight of v's edges into each part

  CutRefiner(const Graph& g_, int k_, double cap_, std::vector<i32>& part_)
      : g(g_), k(k_), cap(cap_), part(part_), conn(k_) {
    pw.assign(k, 0);
    for (i32 v = 0; v < g.n; ++v) pw[part[v]] += g.vwgt[v];
  }

  // Best feasible move for v: cut gain = conn[target] - conn[current].
  // Ties prefer the lighter target part.  target = -1 when v is interior or
  // no part has room.
  float best_move(i32 v, i32& target) {
    const int pv = part[v];
    std::fill(conn.begin(), conn.end(), 0.0f);
    bool boundary = false;
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e) {
      int pu = part[g.adj[e]];
      conn[pu] += g.wgt[e];
      boundary |= pu != pv;
    }
    target = -1;
    float best_gain = 0.0f;
    for (int p = 0; p < k; ++p) {
      if (p == pv) continue;
      if (pw[p] + g.vwgt[v] > (i64)cap) continue;
      float d = conn[p] - conn[pv];
      if (target == -1 || d > best_gain ||
          (d == best_gain && pw[p] < pw[target])) {
        best_gain = d; target = p;
      }
    }
    if (!boundary) target = -1;
    return target == -1 ? 0.0f : best_gain;
  }

  void apply(i32 v, i32 to) {
    pw[part[v]] -= g.vwgt[v]; pw[to] += g.vwgt[v];
    part[v] = to;
  }

  using Gain = float;
  i32 n_items() const { return g.n; }

  // Greedy boundary passes applying only positive-gain moves (the default
  // greedy variant of KL/FM refinement of the METIS family).
  void sweeps(int max_passes) {
    for (int pass = 0; pass < max_passes; ++pass) {
      i64 moves = 0;
      for (i32 v = 0; v < g.n; ++v) {
        i32 t; float gn = best_move(v, t);
        if (t >= 0 && gn > 0.0f) { apply(v, t); ++moves; }
      }
      if (moves == 0) break;
    }
  }
};

// One FM hill-climbing pass, shared by the cut and km1 refiners (the
// gain-ordered refinement of the KL/FM–PaToH family).  A lazy max-heap
// replaces classic gain-bucket arrays — k-way gains are not small bounded
// integers, and the heap keeps the balance-aware tie-break explicit:
//   * seed with every boundary item's best feasible move,
//   * repeatedly apply the globally best move, negative gains included
//     (the hill-climbing a greedy sweep lacks), locking moved items,
//   * remember the best prefix of the move sequence, roll back past it.
// Deterministic: no randomness; heap ties resolve on (gain, item, target).
// Stale heap entries revalidate on pop; neighbors are NOT eagerly requeued
// (on coarse instances a merged item touches thousands of nets and eager
// requeue is quadratic per move) — the surrounding pass loop reseeds the
// heap from scratch, so improved items are only serviced slightly later.
// Cost is bounded (drift window + pop cap) so multilevel drivers can afford
// it above the coarsest level.  R exposes n_items(), best_move(v, target&),
// apply(v, to), part, and a Gain type.
template <typename R>
typename R::Gain fm_pass(R& r) {
  using Gain = typename R::Gain;
  struct Move { i32 item, from; };
  using Entry = std::tuple<Gain, i32, i32>;         // (gain, item, target)
  const i32 n = r.n_items();
  std::priority_queue<Entry> heap;
  std::vector<char> locked(n, 0);
  for (i32 v = 0; v < n; ++v) {
    i32 t; Gain gn = r.best_move(v, t);
    if (t >= 0) heap.emplace(gn, v, t);
  }
  std::vector<Move> moves;
  Gain cum = 0, best_cum = 0;
  size_t best_len = 0;
  int since_best = 0;
  const int drift =                                 // hill-climb tolerance
      std::max(30, std::min(n / 16, 256));
  // Stale-entry revalidation pops don't advance since_best; cap total pops
  // so adversarial churn (many requeues between applies) stays bounded.
  size_t pops = 0;
  const size_t pop_cap = 16u * (size_t)n + 1024;
  while (!heap.empty() && since_best < drift && pops++ < pop_cap &&
         moves.size() < (size_t)n) {
    auto [gn, v, t] = heap.top(); heap.pop();
    if (locked[v]) continue;
    i32 t2; Gain g2 = r.best_move(v, t2);
    if (t2 < 0) continue;
    if (g2 != gn || t2 != t) {                      // stale: requeue current
      heap.emplace(g2, v, t2);
      continue;
    }
    moves.push_back({v, r.part[v]});
    r.apply(v, t);
    locked[v] = 1;
    cum += gn;
    if (cum > best_cum) { best_cum = cum; best_len = moves.size(); since_best = 0; }
    else ++since_best;
  }
  for (size_t i = moves.size(); i > best_len; --i)
    r.apply(moves[i - 1].item, moves[i - 1].from);  // roll back past the peak
  return best_cum;
}

// Combined graph refinement: convergent sweeps always; FM hill-climbing where
// the instance size affords it (same policy as refine_km1, including the
// tiny-instance FM boost).
void refine_cut(const Graph& g, int k, double cap, std::vector<i32>& part,
                int max_passes) {
  CutRefiner r(g, k, cap, part);
  r.sweeps(max_passes);
  if (g.n > 50000) return;
  const int fm_cap = std::min(max_passes, g.n <= 2000 ? 8 : 4);
  for (int pass = 0; pass < fm_cap; ++pass) {
    if (fm_pass(r) <= 0.0f) break;
    r.sweeps(2);
  }
}

// Force balance on the graph side (mirror of rebalance_km1): move vertices
// out of overweight parts into the least-damaging part with room; refine_cut
// afterwards claws quality back.
void rebalance_cut(const Graph& g, int k, double cap, std::vector<i32>& part) {
  std::vector<i64> pw(k, 0);
  for (i32 v = 0; v < g.n; ++v) pw[part[v]] += g.vwgt[v];
  std::vector<float> conn(k);
  for (int pass = 0; pass < 30; ++pass) {
    bool over = false;
    for (int p = 0; p < k; ++p) over |= pw[p] > (i64)cap;
    if (!over) break;
    i64 moves = 0;
    for (i32 v = 0; v < g.n; ++v) {
      int pv = part[v];
      if (pw[pv] <= (i64)cap) continue;
      std::fill(conn.begin(), conn.end(), 0.0f);
      for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e)
        conn[part[g.adj[e]]] += g.wgt[e];
      int best = -1; float best_gain = 0.0f;
      for (int p = 0; p < k; ++p) {
        if (p == pv || pw[p] + g.vwgt[v] > (i64)cap) continue;
        float d = conn[p] - conn[pv];
        if (best == -1 || d > best_gain) { best_gain = d; best = p; }
      }
      if (best != -1) {
        pw[pv] -= g.vwgt[v]; pw[best] += g.vwgt[v];
        part[v] = best; ++moves;
      }
    }
    if (moves == 0) break;
  }
}

i64 edge_cut(const Graph& g, const std::vector<i32>& part) {
  double cut = 0;
  for (i32 v = 0; v < g.n; ++v)
    for (i64 e = g.xadj[v]; e < g.xadj[v + 1]; ++e)
      if (part[v] != part[g.adj[e]]) cut += g.wgt[e];
  return (i64)(cut / 2.0 + 0.5);
}

// ------------------------------------------------------------ multilevel driver
void partition_graph_ml(const Graph& g0, int k, double imbalance, int seed,
                        std::vector<i32>& part) {
  Rng rng((uint64_t)seed);
  std::vector<Graph> levels;
  std::vector<MatchResult> maps;
  levels.push_back(g0);
  const i32 coarse_target = std::max(64, 24 * k);
  while (levels.back().n > coarse_target) {
    MatchResult m = heavy_edge_matching(levels.back(), rng);
    if (m.cn > (i32)(0.97 * levels.back().n)) break;   // matching stalled
    Graph c = contract(levels.back(), m);
    maps.push_back(std::move(m));
    levels.push_back(std::move(c));
  }
  double cap = (1.0 + imbalance) * (double)g0.total_vwgt / k;
  // multi-start at the coarsest level (mirror of the hypergraph driver):
  // several greedy-grow seedings, each refined, keep the best cut
  {
    const Graph& gc = levels.back();
    double coarse_cap = cap * 1.10;     // slack while coarse; finest
                                        // refinement restores the real cap
    i64 best_cut = -1;
    std::vector<i32> best_part;
    const int trials = g0.n <= 2000 ? 16 : 8;   // tiny: search harder
    for (int trial = 0; trial < trials; ++trial) {
      std::vector<i32> cand;
      greedy_grow(gc, k, coarse_cap, cand, rng);
      refine_cut(gc, k, coarse_cap, cand, 10);
      i64 c = edge_cut(gc, cand);
      if (best_cut < 0 || c < best_cut) { best_cut = c; best_part = std::move(cand); }
    }
    part = std::move(best_part);
  }
  // project back up with refinement at each level
  for (int li = (int)levels.size() - 2; li >= 0; --li) {
    const MatchResult& m = maps[li];
    std::vector<i32> fine(levels[li].n);
    for (i32 v = 0; v < levels[li].n; ++v) fine[v] = part[m.cmap[v]];
    part = std::move(fine);
    refine_cut(levels[li], k, cap, part, li == 0 ? 8 : 4);
  }
  rebalance_cut(g0, k, cap, part);
  refine_cut(g0, k, cap, part, 3);
}

// ======================================================= hypergraph (colnet)
struct Hypergraph {
  i32 ncells = 0, nnets = 0;
  std::vector<i64> cellptr;   // cell -> nets
  std::vector<i32> cellnets;
  std::vector<i64> netptr;    // net -> pins(cells)
  std::vector<i32> netpins;
  std::vector<i64> cwgt;      // cell weights
  std::vector<i64> nwgt;      // net weights (identical nets merge, r5)
  i64 total_cwgt = 0;
};

Hypergraph from_cells(i32 ncells, i32 nnets, const i64* cellptr,
                      const i32* cellnets, const i64* cwgt) {
  Hypergraph h;
  h.ncells = ncells; h.nnets = nnets;
  h.cellptr.assign(cellptr, cellptr + ncells + 1);
  h.cellnets.assign(cellnets, cellnets + cellptr[ncells]);
  h.cwgt.assign(ncells, 1);
  if (cwgt) h.cwgt.assign(cwgt, cwgt + ncells);
  h.total_cwgt = std::accumulate(h.cwgt.begin(), h.cwgt.end(), (i64)0);
  h.nwgt.assign(nnets, 1);
  // invert to net -> pins
  h.netptr.assign(nnets + 1, 0);
  for (i64 e = 0; e < (i64)h.cellnets.size(); ++e) h.netptr[h.cellnets[e] + 1]++;
  for (i32 j = 0; j < nnets; ++j) h.netptr[j + 1] += h.netptr[j];
  h.netpins.resize(h.cellnets.size());
  std::vector<i64> pos(h.netptr.begin(), h.netptr.end() - 1);
  for (i32 c = 0; c < ncells; ++c)
    for (i64 e = h.cellptr[c]; e < h.cellptr[c + 1]; ++e)
      h.netpins[pos[h.cellnets[e]]++] = c;
  return h;
}

// Rebuild cell -> nets from net -> pins.  Scanning nets ascending makes each
// cell's list sorted and duplicate-free (each net contributes one entry).
void rebuild_cellnets(Hypergraph& h) {
  h.cellptr.assign(h.ncells + 1, 0);
  for (i32 c : h.netpins) h.cellptr[c + 1]++;
  for (i32 c = 0; c < h.ncells; ++c) h.cellptr[c + 1] += h.cellptr[c];
  h.cellnets.assign(h.netpins.size(), 0);
  std::vector<i64> pos(h.cellptr.begin(), h.cellptr.end() - 1);
  for (i32 j = 0; j < h.nnets; ++j)
    for (i64 p = h.netptr[j]; p < h.netptr[j + 1]; ++p)
      h.cellnets[pos[h.netpins[p]]++] = j;
}

// Net compaction (the PaToH family's identical-net trick, r5 speed pass):
//   * single-pin nets can never be cut (λ ≤ 1 ⇒ km1 contribution 0) — drop;
//   * nets with the SAME pin set contribute identically to km1/gains — merge
//     into one net carrying the summed weight.
// Exact for the weighted km1 objective every consumer below now uses.  The
// payoff compounds through the V-cycle: without it every coarse level drags
// the full fine-level net count through pincounts/km1/greedy scans (measured
// 55-80% of partitioner wall-clock at 0.6-2.45M cells before this change).
void compact_nets(Hypergraph& h) {
  const i32 nn = h.nnets;
  if (h.nwgt.empty()) h.nwgt.assign(nn, 1);
  // hash each net's pin sequence (pins are sorted: netpins is built by
  // scanning cells/nets ascending everywhere in this file)
  std::vector<uint64_t> hash(nn);
  for (i32 j = 0; j < nn; ++j) {
    uint64_t hv = 1469598103934665603ull;
    for (i64 p = h.netptr[j]; p < h.netptr[j + 1]; ++p) {
      hv ^= (uint64_t)(uint32_t)h.netpins[p];
      hv *= 1099511628211ull;
    }
    hash[j] = hv;
  }
  std::unordered_map<uint64_t, std::vector<i32>> groups;
  groups.reserve(nn);
  std::vector<i32> remap(nn, -1);      // old net -> new net (-1 = dropped)
  std::vector<i64> new_nwgt;
  std::vector<i64> new_netptr{0};
  std::vector<i32> new_netpins;
  new_nwgt.reserve(nn);
  i32 nj = 0;
  auto same_pins = [&](i32 a, i32 b) {
    i64 la = h.netptr[a + 1] - h.netptr[a];
    if (la != h.netptr[b + 1] - h.netptr[b]) return false;
    return std::equal(h.netpins.begin() + h.netptr[a],
                      h.netpins.begin() + h.netptr[a + 1],
                      h.netpins.begin() + h.netptr[b]);
  };
  for (i32 j = 0; j < nn; ++j) {
    if (h.netptr[j + 1] - h.netptr[j] < 2) continue;   // single-pin: drop
    auto& bucket = groups[hash[j]];
    i32 found = -1;
    for (i32 rep : bucket)
      if (same_pins(rep, j)) { found = remap[rep]; break; }
    if (found >= 0) {
      new_nwgt[found] += h.nwgt[j];
      remap[j] = found;
      continue;
    }
    bucket.push_back(j);
    remap[j] = nj++;
    new_nwgt.push_back(h.nwgt[j]);
    new_netpins.insert(new_netpins.end(), h.netpins.begin() + h.netptr[j],
                       h.netpins.begin() + h.netptr[j + 1]);
    new_netptr.push_back((i64)new_netpins.size());
  }
  h.nnets = nj;
  h.nwgt = std::move(new_nwgt);
  h.netptr = std::move(new_netptr);
  h.netpins = std::move(new_netpins);
  rebuild_cellnets(h);
}

// heavy-connectivity matching: match cells sharing the most nets
MatchResult hc_matching(const Hypergraph& h, Rng& rng,
                        i64 big_net_threshold) {
  std::vector<i32> order(h.ncells);
  std::iota(order.begin(), order.end(), 0);
  fy_shuffle(order, rng);
  std::vector<i32> match(h.ncells, -1);
  // flat scratch + touched-list instead of a hash map: this loop is the
  // single-core hot path at products scale (2.45M cells × ~2.5k candidate
  // scans), and the array form measured several× faster than unordered_map
  std::vector<i64> shared(h.ncells, 0);
  std::vector<i32> touched;
  touched.reserve(4096);
  // Per-cell candidate-scan budget (r5 speed pass): matching needs a
  // heavy-ish partner, not THE heaviest — capping pin touches bounds the
  // deg² term that dominated coarsening wall-clock at products scale.
  // Per-cell net lists are SORTED by net id (rebuild_cellnets), and net
  // ids follow vertex order, so a plain prefix would systematically favor
  // low-id neighborhoods on id-structured families (BA ages, dcsbm
  // communities) — start the truncated scan at a random rotation instead.
  const i64 scan_budget = 2048;
  for (i32 v : order) {
    if (match[v] != -1) continue;
    i64 budget = scan_budget;
    const i64 vdeg = h.cellptr[v + 1] - h.cellptr[v];
    const i64 rot = vdeg > 0 ? (i64)(rng.next() % (uint64_t)vdeg) : 0;
    for (i64 i = 0; i < vdeg && budget > 0; ++i) {
      const i64 e = h.cellptr[v] + (i + rot) % vdeg;
      i32 net = h.cellnets[e];
      i64 deg = h.netptr[net + 1] - h.netptr[net];
      if (deg > big_net_threshold) continue;        // skip huge nets (cost)
      budget -= deg;
      const i64 w = h.nwgt.empty() ? 1 : h.nwgt[net];
      for (i64 p = h.netptr[net]; p < h.netptr[net + 1]; ++p) {
        i32 u = h.netpins[p];
        if (u != v && match[u] == -1) {
          if (shared[u] == 0) touched.push_back(u);
          shared[u] += w;
        }
      }
    }
    i32 best = -1;
    i64 best_s = 0;
    for (i32 u : touched) {
      if (shared[u] > best_s) { best_s = shared[u]; best = u; }
      shared[u] = 0;
    }
    touched.clear();
    if (best != -1) { match[v] = best; match[best] = v; }
    else match[v] = v;
  }
  MatchResult r;
  r.cmap.assign(h.ncells, -1);
  for (i32 v = 0; v < h.ncells; ++v) {
    if (r.cmap[v] != -1) continue;
    i32 u = match[v];
    r.cmap[v] = r.cn;
    if (u != v && u != -1) r.cmap[u] = r.cn;
    ++r.cn;
  }
  return r;
}

Hypergraph contract_h(const Hypergraph& h, const MatchResult& m) {
  Hypergraph c;
  c.ncells = m.cn; c.nnets = h.nnets;
  c.cwgt.assign(m.cn, 0);
  for (i32 v = 0; v < h.ncells; ++v) c.cwgt[m.cmap[v]] += h.cwgt[v];
  c.total_cwgt = h.total_cwgt;
  // coarse cell -> dedup'd union of nets
  std::vector<std::vector<i32>> nets(m.cn);
  for (i32 v = 0; v < h.ncells; ++v) {
    auto& dst = nets[m.cmap[v]];
    for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e)
      dst.push_back(h.cellnets[e]);
  }
  c.cellptr.assign(m.cn + 1, 0);
  for (i32 cv = 0; cv < m.cn; ++cv) {
    auto& d = nets[cv];
    std::sort(d.begin(), d.end());
    d.erase(std::unique(d.begin(), d.end()), d.end());
    c.cellptr[cv + 1] = c.cellptr[cv] + (i64)d.size();
  }
  c.cellnets.reserve(c.cellptr[m.cn]);
  for (i32 cv = 0; cv < m.cn; ++cv)
    c.cellnets.insert(c.cellnets.end(), nets[cv].begin(), nets[cv].end());
  c.nwgt = h.nwgt.empty() ? std::vector<i64>(h.nnets, 1) : h.nwgt;
  // rebuild net -> pins, then compact: dropping now-single-pin nets and
  // merging now-identical ones is what keeps coarse levels from dragging
  // the fine level's full net count through every pincount/gain scan
  c.netptr.assign(c.nnets + 1, 0);
  for (i32 x : c.cellnets) c.netptr[x + 1]++;
  for (i32 j = 0; j < c.nnets; ++j) c.netptr[j + 1] += c.netptr[j];
  c.netpins.resize(c.cellnets.size());
  std::vector<i64> pos(c.netptr.begin(), c.netptr.end() - 1);
  for (i32 cv = 0; cv < m.cn; ++cv)
    for (i64 e = c.cellptr[cv]; e < c.cellptr[cv + 1]; ++e)
      c.netpins[pos[c.cellnets[e]]++] = cv;
  compact_nets(c);
  return c;
}

// km1 objective helpers: per-net pin counts per part (dense nnets × k)
struct PinCounts {
  std::vector<i32> cnt;   // nnets * k
  int k;
  i32* row(i32 net) { return cnt.data() + (i64)net * k; }
};

i64 km1_total(const Hypergraph& h, PinCounts& pc) {
  i64 s = 0;
  for (i32 j = 0; j < h.nnets; ++j) {
    i32* r = pc.row(j);
    int lambda = 0;
    for (int p = 0; p < pc.k; ++p) lambda += r[p] > 0;
    if (lambda > 1)
      s += (h.nwgt.empty() ? 1 : h.nwgt[j]) * (i64)(lambda - 1);
  }
  return s;
}

void build_pincounts(const Hypergraph& h, const std::vector<i32>& part,
                     PinCounts& pc) {
  pc.cnt.assign((i64)h.nnets * pc.k, 0);
  for (i32 j = 0; j < h.nnets; ++j) {
    i32* r = pc.row(j);
    for (i64 p = h.netptr[j]; p < h.netptr[j + 1]; ++p) r[part[h.netpins[p]]]++;
  }
}

// Connectivity-aware greedy placement on the coarsest hypergraph: cells are
// placed in random order into the part their nets already touch most
// (constructive form of the km1 gain).  Two placement disciplines, chosen
// per multi-start trial for diversity:
//   prefer_target=false — any cap-feasible part (best when the cap binds:
//     communities fill their part to the brim before spilling);
//   prefer_target=true — parts still under the ideal weight total/k first
//     (best when the cap is loose: stops early parts swallowing whole
//     neighborhoods and starving the rest).
void greedy_grow_h(const Hypergraph& h, int k, double cap,
                   std::vector<i32>& part, Rng& rng,
                   bool prefer_target) {
  part.assign(h.ncells, -1);
  std::vector<i32> order(h.ncells);
  std::iota(order.begin(), order.end(), 0);
  fy_shuffle(order, rng);
  std::vector<i64> pw(k, 0);
  const i64 target = h.total_cwgt / k;
  // net -> set of parts present, tracked as dense counts
  std::vector<i32> netpart((i64)h.nnets * k, 0);
  std::vector<i64> affinity(k);
  for (i32 idx = 0; idx < h.ncells; ++idx) {
    i32 v = order[idx];
    std::fill(affinity.begin(), affinity.end(), 0);
    for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e) {
      const i32 net = h.cellnets[e];
      const i64 w = h.nwgt.empty() ? 1 : h.nwgt[net];
      const i32* r = netpart.data() + (i64)net * k;
      for (int p = 0; p < k; ++p) affinity[p] += (r[p] > 0) * w;
    }
    int best = -1; i64 best_a = -1;
    if (prefer_target)
      for (int p = 0; p < k; ++p)   // first choice: parts still under target
        if (pw[p] + h.cwgt[v] <= target && affinity[p] > best_a) {
          best_a = affinity[p]; best = p;
        }
    if (best == -1)
      for (int p = 0; p < k; ++p)   // anywhere the cap allows
        if (pw[p] + h.cwgt[v] <= (i64)cap && affinity[p] > best_a) {
          best_a = affinity[p]; best = p;
        }
    if (best == -1)   // everything full (rounding): lightest part
      best = (int)(std::min_element(pw.begin(), pw.end()) - pw.begin());
    part[v] = best; pw[best] += h.cwgt[v];
    for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e)
      netpart[(i64)h.cellnets[e] * k + best]++;
  }
}

// km1 refinement state shared by the sweep and FM phases below.
struct Km1Refiner {
  const Hypergraph& h;
  const int k;
  const double cap;
  std::vector<i32>& part;
  PinCounts pc;
  std::vector<i64> pw;
  std::vector<i64> cnt;     // scratch: net weight of v present in part p
  std::vector<char> cut;    // per net: pins in >= 2 parts (λ >= 2)

  Km1Refiner(const Hypergraph& h_, int k_, double cap_, std::vector<i32>& part_)
      : h(h_), k(k_), cap(cap_), part(part_), cnt(k_) {
    pc.k = k;
    build_pincounts(h, part, pc);
    pw.assign(k, 0);
    for (i32 v = 0; v < h.ncells; ++v) pw[part[v]] += h.cwgt[v];
    cut.assign(h.nnets, 0);
    for (i32 j = 0; j < h.nnets; ++j) {
      const i32* r = pc.row(j);
      int lambda = 0;
      for (int p = 0; p < k && lambda < 2; ++p) lambda += r[p] > 0;
      cut[j] = lambda >= 2;
    }
  }

  i64 netw(i32 j) const { return h.nwgt.empty() ? 1 : h.nwgt[j]; }

  // Best feasible move for v.  Weighted km1 gain of moving v from pv to p:
  //   + weight of every net where v is pv's last pin (leaving removes pv)
  //   - weight of every net where p has no pin yet (arriving adds p)
  //   = leave_bonus - (degw(v) - weight of v's nets where p already present).
  // Ties prefer the lighter target part.  target = -1 when v is interior or
  // no part has room.  Interior test first: a cell none of whose nets are
  // cut sees every pin in pv — deg work instead of deg·k (the r5 sweep
  // early-out; at products scale most cells are interior once the
  // partition settles, and the full-gain fall-through is exactly the old
  // code, so results are unchanged).
  i64 best_move(i32 v, i32& target) {
    const int pv = part[v];
    bool anycut = false;
    for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e)
      if (cut[h.cellnets[e]]) { anycut = true; break; }
    if (!anycut) { target = -1; return 0; }
    std::fill(cnt.begin(), cnt.end(), 0);
    i64 leave_bonus = 0, degw = 0;
    for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e) {
      const i32 net = h.cellnets[e];
      const i64 w = netw(net);
      degw += w;
      const i32* r = pc.row(net);
      if (r[pv] == 1) leave_bonus += w;
      for (int p = 0; p < k; ++p)
        if (p != pv && r[p] > 0) cnt[p] += w;
    }
    target = -1;
    i64 best_gain = 0;
    for (int p = 0; p < k; ++p) {
      if (p == pv) continue;
      if (pw[p] + h.cwgt[v] > (i64)cap) continue;
      i64 gn = leave_bonus - (degw - cnt[p]);
      if (target == -1 || gn > best_gain ||
          (gn == best_gain && pw[p] < pw[target])) {
        best_gain = gn; target = p;
      }
    }
    return target == -1 ? 0 : best_gain;
  }

  void apply(i32 v, i32 to) {
    const int pv = part[v];
    for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e) {
      const i32 net = h.cellnets[e];
      i32* r = pc.row(net);
      r[pv]--; r[to]++;
      // λ can only change through the touched parts; recount lazily
      int lambda = 0;
      for (int p = 0; p < k && lambda < 2; ++p) lambda += r[p] > 0;
      cut[net] = lambda >= 2;
    }
    pw[pv] -= h.cwgt[v]; pw[to] += h.cwgt[v];
    part[v] = to;
  }

  using Gain = i64;
  i32 n_items() const { return h.ncells; }

  // Greedy boundary sweeps: linear-time passes applying only positive-gain
  // moves in cell order; converge fast and carry the bulk of refinement at
  // every scale.  Hill-climbing is the shared fm_pass() above.
  void sweeps(int max_passes) {
    for (int pass = 0; pass < max_passes; ++pass) {
      i64 moves = 0;
      for (i32 v = 0; v < h.ncells; ++v) {
        i32 t; i64 g = best_move(v, t);
        if (t >= 0 && g > 0) { apply(v, t); ++moves; }
      }
      if (moves == 0) break;
    }
  }
};

// Combined refinement: fast convergent sweeps always; FM hill-climbing where
// the instance size affords it, with sweeps mopping up after each FM gain.
void refine_km1(const Hypergraph& h, int k, double cap, std::vector<i32>& part,
                int max_passes) {
  Km1Refiner r(h, k, cap, part);
  r.sweeps(max_passes);
  if (h.ncells > 50000) return;
  const int fm_cap = std::min(max_passes, h.ncells <= 2000 ? 8 : 4);
  for (int pass = 0; pass < fm_cap; ++pass) {
    if (fm_pass(r) <= 0) break;
    r.sweeps(2);
  }
}

// Force balance: move cells out of overweight parts into the least-damaging
// part with room (gain may be negative — feasibility first, then refine_km1
// claws quality back).
void rebalance_km1(const Hypergraph& h, int k, double cap,
                   std::vector<i32>& part) {
  PinCounts pc; pc.k = k;
  build_pincounts(h, part, pc);
  std::vector<i64> pw(k, 0);
  for (i32 v = 0; v < h.ncells; ++v) pw[part[v]] += h.cwgt[v];
  std::vector<i64> gain(k);
  for (int pass = 0; pass < 30; ++pass) {
    bool over = false;
    for (int p = 0; p < k; ++p) over |= pw[p] > (i64)cap;
    if (!over) break;
    i64 moves = 0;
    for (i32 v = 0; v < h.ncells; ++v) {
      int pv = part[v];
      if (pw[pv] <= (i64)cap) continue;
      std::fill(gain.begin(), gain.end(), 0);
      i64 leave_bonus = 0, degw = 0;
      for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e) {
        const i32 net = h.cellnets[e];
        const i64 w = h.nwgt.empty() ? 1 : h.nwgt[net];
        degw += w;
        i32* r = pc.row(net);
        if (r[pv] == 1) leave_bonus += w;
        for (int p = 0; p < k; ++p)
          if (p != pv && r[p] > 0) gain[p] += w;
      }
      int best = -1; i64 best_gain = 0;
      for (int p = 0; p < k; ++p) {
        if (p == pv || pw[p] + h.cwgt[v] > (i64)cap) continue;
        i64 gn = leave_bonus - (degw - gain[p]);
        if (best == -1 || gn > best_gain) { best_gain = gn; best = p; }
      }
      if (best != -1) {
        for (i64 e = h.cellptr[v]; e < h.cellptr[v + 1]; ++e) {
          i32* r = pc.row(h.cellnets[e]);
          r[pv]--; r[best]++;
        }
        pw[pv] -= h.cwgt[v]; pw[best] += h.cwgt[v];
        part[v] = best; ++moves;
      }
    }
    if (moves == 0) break;
  }
}

void partition_hypergraph_ml(const Hypergraph& h0, int k, double imbalance,
                             int seed, std::vector<i32>& part) {
  const bool timing = std::getenv("SGCN_TIMING") != nullptr;
  auto now = [] { return std::chrono::steady_clock::now(); };
  auto secs = [](auto a, auto b) {
    return std::chrono::duration<double>(b - a).count();
  };
  auto t0 = now();
  Rng rng((uint64_t)seed);
  std::vector<Hypergraph> levels;
  std::vector<MatchResult> maps;
  levels.push_back(h0);
  // compact the working copy of the finest level too: a column-net
  // hypergraph of an undirected graph has every net duplicated against its
  // mirror, so identical-net merging halves even level-0 gain scans, and
  // the weighted objective it produces is exactly the original km1
  compact_nets(levels[0]);
  const i32 coarse_target = std::max(64, 24 * k);
  // skip nets with more pins than this during matching (cost control)
  while (levels.back().ncells > coarse_target) {
    const Hypergraph& cur = levels.back();
    i64 avg_deg = cur.netpins.empty() ? 1 :
        std::max<i64>(2, (i64)cur.netpins.size() / std::max(1, cur.nnets));
    MatchResult m = hc_matching(cur, rng, 8 * avg_deg);
    if (m.cn > (i32)(0.97 * cur.ncells)) break;
    Hypergraph c = contract_h(cur, m);
    maps.push_back(std::move(m));
    levels.push_back(std::move(c));
  }
  if (timing)
    std::fprintf(stderr,
                 "[sgcnpart] coarsen: %.2fs levels=%zu coarsest=%d "
                 "(nets=%d pins=%zu)\n",
                 secs(t0, now()), levels.size(), levels.back().ncells,
                 levels.back().nnets, levels.back().netpins.size());
  double cap = (1.0 + imbalance) * (double)h0.total_cwgt / k;
  // multi-start at the coarsest level: keep the best refined candidate
  {
    const Hypergraph& hc = levels.back();
    double coarse_cap = cap * 1.10;     // extra slack while coarse; finest
                                        // refinement restores the real cap
    i64 best_km1 = -1;
    std::vector<i32> best_part;
    PinCounts pc; pc.k = k;
    // Column-net hypergraphs keep O(original pins / ~20) pins at the
    // coarsest level (nets rarely become identical), so a coarse trial is
    // O(pins·k·passes), NOT O(coarse cells) — budget the multistart by
    // pins (r5 speed pass; at products scale 8 full trials were ~15% of
    // total wall-clock for marginal quality: uncoarsening sweeps do the
    // bulk of refinement anyway).
    int trials = h0.ncells <= 2000 ? 16 : 8;
    const i64 pins = (i64)hc.netpins.size();
    if (pins > 2'000'000)
      trials = std::max<int>(3, (int)(8 * 2'000'000 / pins));
    for (int trial = 0; trial < trials; ++trial) {
      auto tg = now();
      std::vector<i32> cand;
      greedy_grow_h(hc, k, coarse_cap, cand, rng, trial % 2 == 1);
      auto tr_ = now();
      refine_km1(hc, k, coarse_cap, cand, 8);
      build_pincounts(hc, cand, pc);
      i64 score = km1_total(hc, pc);
      if (timing)
        std::fprintf(stderr,
                     "[sgcnpart]   trial %d: grow=%.2fs refine=%.2fs "
                     "km1=%lld\n", trial, secs(tg, tr_), secs(tr_, now()),
                     (long long)score);
      if (best_km1 < 0 || score < best_km1) {
        best_km1 = score; best_part = std::move(cand);
      }
    }
    part = std::move(best_part);
  }
  if (timing)
    std::fprintf(stderr, "[sgcnpart] coarse multistart: %.2fs\n", secs(t0, now()));
  for (int li = (int)levels.size() - 2; li >= 0; --li) {
    auto tl = now();
    const MatchResult& m = maps[li];
    std::vector<i32> fine(levels[li].ncells);
    for (i32 v = 0; v < levels[li].ncells; ++v) fine[v] = part[m.cmap[v]];
    part = std::move(fine);
    refine_km1(levels[li], k, cap, part, li == 0 ? 6 : 3);
    if (timing)
      std::fprintf(stderr, "[sgcnpart] level %d (n=%d): %.2fs\n", li,
                   levels[li].ncells, secs(tl, now()));
  }
  auto tr = now();
  rebalance_km1(levels[0], k, cap, part);
  refine_km1(levels[0], k, cap, part, 3);
  if (timing)
    std::fprintf(stderr, "[sgcnpart] rebalance+final: %.2fs total=%.2fs\n",
                 secs(tr, now()), secs(t0, now()));
}

// ---------------------------------------------------- recursive bisection
// Direct k-way km1 refinement costs O(deg·k) per move, which at k >= 32 and
// products scale made hp both slow (5 700 s) and ~3% WORSE than gp
// (BASELINE.md round-5 k-sweep).  Recursive bisection — the PaToH/hMETIS
// production strategy — eliminates the k factor: log2(k) levels of 2-way
// partitions, each with the full multilevel machinery at k=2.
//
// The km1 objective decomposes EXACTLY over a bisection with net
// splitting: for a net with pins on both sides, λ over the final k parts
// equals λ_left + λ_right (its sub-nets' part counts), so
//   km1(net) = λ−1 = (λ_left−1) + (λ_right−1) + 1,
// i.e. total km1 = (top-level cut nets) + Σ_side km1(side sub-hypergraph)
// where each side keeps the net restricted to its own pins.  Minimizing
// the 2-way cut then recursing on split nets IS minimizing km1.
// Per-level imbalance halves (ε/2 each level) so the final parts respect
// the caller's cap.  Power-of-two k only (even splits); other k use the
// direct k-way driver.
void partition_hypergraph_rb(const Hypergraph& h, int k, double imbalance,
                             int seed, std::vector<i32>& part) {
  if (k == 1) { part.assign(h.ncells, 0); return; }
  // split the imbalance budget GEOMETRICALLY over the remaining levels:
  // (1+ε_level)^levels == 1+ε exactly, so the final parts respect the
  // caller's cap without the additive-halving scheme's two failure modes
  // (deep levels starved below one cell of slack — refinement frozen —
  // and compounded overshoot at large ε).  The per-level slack is floored
  // at one max cell weight so a feasible move always exists.
  const int levels = [] (int kk) {
    int l = 0; while (kk > 1) { kk >>= 1; ++l; } return l; } (k);
  double eps_level = std::pow(1.0 + imbalance, 1.0 / levels) - 1.0;
  const i64 max_cw = h.cwgt.empty() ? 1 :
      *std::max_element(h.cwgt.begin(), h.cwgt.end());
  if (h.total_cwgt > 0)
    eps_level = std::max(eps_level, 2.0 * (double)max_cw / h.total_cwgt);
  std::vector<i32> top;
  // the k==2 base case gets the level budget like any other level (the
  // recursion has already consumed the rest of ε above it; when called
  // directly with k==2, levels==1 makes eps_level == imbalance)
  partition_hypergraph_ml(h, 2, eps_level, seed, top);
  if (k == 2) { part = top; return; }
  const double eps_rem =
      std::pow(1.0 + imbalance, (levels - 1.0) / levels) - 1.0;
  part.assign(h.ncells, -1);
  for (int side = 0; side < 2; ++side) {
    // extract the side's sub-hypergraph: cells of this side, nets
    // restricted to their pins on this side (< 2 pins -> dropped, they
    // can no longer be cut), weights carried
    std::vector<i32> cells;                    // sub id -> parent id
    std::vector<i32> sub_of(h.ncells, -1);
    for (i32 v = 0; v < h.ncells; ++v)
      if (top[v] == side) {
        sub_of[v] = (i32)cells.size();
        cells.push_back(v);
      }
    Hypergraph s;
    s.ncells = (i32)cells.size();
    s.cwgt.resize(s.ncells);
    for (i32 sv = 0; sv < s.ncells; ++sv) s.cwgt[sv] = h.cwgt[cells[sv]];
    s.total_cwgt = std::accumulate(s.cwgt.begin(), s.cwgt.end(), (i64)0);
    s.netptr.push_back(0);
    for (i32 j = 0; j < h.nnets; ++j) {
      i64 kept = 0;
      for (i64 p = h.netptr[j]; p < h.netptr[j + 1]; ++p)
        if (sub_of[h.netpins[p]] >= 0) {
          s.netpins.push_back(sub_of[h.netpins[p]]);
          ++kept;
        }
      if (kept < 2) {
        s.netpins.resize(s.netpins.size() - kept);   // drop
      } else {
        s.netptr.push_back((i64)s.netpins.size());
        s.nwgt.push_back(h.nwgt.empty() ? 1 : h.nwgt[j]);
      }
    }
    s.nnets = (i32)s.nwgt.size();
    rebuild_cellnets(s);
    std::vector<i32> sub_part;
    partition_hypergraph_rb(s, k / 2, eps_rem, seed + 104729 + side,
                            sub_part);
    const int off = side * (k / 2);
    for (i32 sv = 0; sv < s.ncells; ++sv)
      part[cells[sv]] = off + sub_part[sv];
  }
}

// Restart budget: whole-multilevel restarts are the "more V-cycles" quality
// lever, but they scale linearly in the instance size, so the budget is
// size-capped (the VERDICT-r3 scale path: one restart at products scale keeps
// the 2.45M-cell run inside a single-core time budget).  SGCN_RESTARTS
// overrides for experiments.
int restart_budget(i64 n) {
  if (const char* env = std::getenv("SGCN_RESTARTS")) {
    int r = std::atoi(env);
    if (r > 0) return r;
  }
  return n <= 2000 ? 12 : n <= 20000 ? 6 : n <= 1000000 ? 3 : 1;
}

// The k>1 body of sgcn_partition_hypergraph, extracted so the cache-aware
// entry point (sgcn_partition_hypergraph_cache) reuses the identical
// driver: restarts, RB-vs-direct selection, post-RB polish, and the
// graph-seeded portfolio — byte-for-byte the behavior the plain ABI had.
void hypergraph_driver(const Hypergraph& h, int k, double imbalance,
                       int seed, std::vector<i32>& part) {
  const i32 ncells = h.ncells;
  const i32 nnets = h.nnets;
  // restarts of the whole multilevel procedure (different coarsening and
  // seeding draws); keep the best final km1 — the "more V-cycles /
  // restarts" quality lever of the PaToH quality preset.  Small instances
  // are cheap enough to search harder; huge ones get one pass.
  const int restarts = restart_budget(ncells);
  i64 best = -1;
  std::vector<i32> cand;
  PinCounts pc;
  pc.k = k;
  // high power-of-two k: recursive bisection (see
  // partition_hypergraph_rb) replaces the direct k-way driver, whose
  // O(deg·k) refinement measured slower AND worse at k >= 32;
  // SGCN_HP_RB=1 forces RB wherever k is a power of two, =0 disables
  const char* rb_env = std::getenv("SGCN_HP_RB");
  const bool pow2 = (k & (k - 1)) == 0;
  const bool use_rb = pow2 && rb_env != nullptr ? rb_env[0] == '1'
                      : pow2 && k >= 32;
  // Post-RB polish runs on a compact_nets'd COPY of the fine hypergraph
  // (ADVICE r5): a column-net hypergraph of an undirected graph carries
  // every net twice (mirror pairs), so identical-net merging halves the
  // O(deg·k) gain scans of the direct k-way passes while the weighted
  // km1 objective — and therefore every move decision's gain — stays
  // exactly the original km1 (the ml path already refines compacted
  // levels for the same reason).  Built once, reused across restarts;
  // cells are untouched by compaction, so the part vector carries over.
  Hypergraph hpol;
  if (use_rb) {
    hpol = h;
    compact_nets(hpol);
  }
  for (int r = 0; r < restarts; ++r) {
    if (use_rb)
      partition_hypergraph_rb(h, k, imbalance, seed + 7919 * r, cand);
    else
      partition_hypergraph_ml(h, k, imbalance, seed + 7919 * r, cand);
    double cap = (1.0 + imbalance) * (double)h.total_cwgt / k;
    if (use_rb) {
      // one direct k-way polish pass: RB never saw cross-side moves
      rebalance_km1(hpol, k, cap, cand);
      refine_km1(hpol, k, cap, cand, 2);
    }
    build_pincounts(h, cand, pc);
    i64 score = km1_total(h, pc);
    if (best < 0 || score < best) { best = score; part = cand; }
  }
  // Portfolio restart (small square instances): seed from the graph-model
  // (edge-cut) partitioner's basin and refine under km1.  On small
  // near-symmetric matrices the graph search sometimes finds a better
  // basin than column-net coarsening; km1 refinement keeps the
  // connectivity objective in charge, so the hypergraph partitioner never
  // loses to the graph one on its own metric.  Gated by size so the
  // products-scale run stays lean (hp wins outright there anyway,
  // bench_artifacts/partition_comm_sweep.json).
  if (ncells == nnets && ncells <= 200000) {
    Graph g;
    g.n = ncells;
    std::vector<i64> keys;
    keys.reserve(2 * h.cellnets.size());
    for (i32 c = 0; c < ncells; ++c)
      for (i64 e = h.cellptr[c]; e < h.cellptr[c + 1]; ++e) {
        i64 j = h.cellnets[e];
        if (j == c) continue;
        keys.push_back((i64)c * nnets + j);
        keys.push_back(j * (i64)nnets + c);
      }
    std::sort(keys.begin(), keys.end());
    keys.erase(std::unique(keys.begin(), keys.end()), keys.end());
    g.xadj.assign(ncells + 1, 0);
    g.adj.resize(keys.size());
    g.wgt.assign(keys.size(), 1.0f);
    for (i64 key : keys) g.xadj[key / nnets + 1]++;
    for (i32 v = 0; v < ncells; ++v) g.xadj[v + 1] += g.xadj[v];
    for (size_t e = 0; e < keys.size(); ++e)
      g.adj[e] = (i32)(keys[e] % nnets);
    g.vwgt = h.cwgt;                 // balance on cell weights carries over
    g.total_vwgt = h.total_cwgt;
    double cap = (1.0 + imbalance) * (double)h.total_cwgt / k;
    // same restart budget as the standalone graph partitioner, but each
    // candidate is scored on km1 after connectivity refinement
    for (int r = 0; r < restarts; ++r) {
      partition_graph_ml(g, k, imbalance, seed + 31337 + 7919 * r, cand);
      rebalance_km1(h, k, cap, cand);
      refine_km1(h, k, cap, cand, 6);
      build_pincounts(h, cand, pc);
      i64 score = km1_total(h, pc);
      if (score < best) { best = score; part = cand; }
    }
  }
}

// ------------------------------------------------- cache-aware km1 (replicas)
// Hot-halo replication (CaPGNN-style): the training system promotes the
// top-B boundary rows to persistent replicas on their consumer chips, so a
// net whose source vertex is replicated STOPS costing km1 — its rows ship
// once per refresh instead of once per exchange.  The cache-aware objective
// of a partition is therefore km1 minus the contribution of the best-B
// replica candidates, where candidates are ranked by (λ−1)·pins — the
// hypergraph face of the plan-time λ·degree ranking (the owner part is a
// pin here, so plan-λ = λ−1; pins ≈ consumer edges).  Deterministic
// tie-break on net id, matching the plan side's vertex-id tie-break.
struct CacheObjective {
  i64 obj = 0;                 // km1 with the selected nets' cost removed
  std::vector<i32> nets;       // the selected (replicated) nets
};

CacheObjective cache_objective(const Hypergraph& h,
                               const std::vector<i32>& part, int k,
                               i32 budget) {
  PinCounts pc;
  pc.k = k;
  build_pincounts(h, part, pc);
  CacheObjective out;
  std::vector<std::pair<i64, i32>> scored;   // (-score, net): top-B order
  std::vector<i64> contrib(h.nnets, 0);
  for (i32 j = 0; j < h.nnets; ++j) {
    const i32* r = pc.row(j);
    int lambda = 0;
    for (int p = 0; p < k; ++p) lambda += r[p] > 0;
    if (lambda < 2) continue;
    const i64 w = h.nwgt.empty() ? 1 : h.nwgt[j];
    contrib[j] = w * (i64)(lambda - 1);
    out.obj += contrib[j];
    const i64 pins = h.netptr[j + 1] - h.netptr[j];
    scored.push_back({-((i64)(lambda - 1) * pins), j});
  }
  const i32 b = (i32)std::min<i64>(budget, (i64)scored.size());
  std::partial_sort(scored.begin(), scored.begin() + b, scored.end());
  out.nets.reserve(b);
  for (i32 i = 0; i < b; ++i) {
    out.obj -= contrib[scored[i].second];
    out.nets.push_back(scored[i].second);
  }
  return out;
}

// Co-optimize a partition with the replica budget: alternate (a) select the
// current top-B replica nets, (b) refine under a weight vector with those
// nets ZEROED (their pins move freely — the cut stops fighting the cache),
// (c) re-score with a FRESH selection.  The incoming partition is the
// first candidate, so the result's cache objective is <= the cache-blind
// driver's by construction (monotone best-keep).
i64 cache_cooptimize(const Hypergraph& h, int k, double imbalance,
                     i32 budget, std::vector<i32>& part) {
  const double cap = (1.0 + imbalance) * (double)h.total_cwgt / k;
  Hypergraph hz = h;
  if (hz.nwgt.empty()) hz.nwgt.assign(h.nnets, 1);
  std::vector<i32> best = part;
  i64 best_obj = cache_objective(h, part, k, budget).obj;
  for (int round = 0; round < 3; ++round) {
    CacheObjective sel = cache_objective(h, part, k, budget);
    std::vector<i64> saved;
    saved.reserve(sel.nets.size());
    for (i32 j : sel.nets) {
      saved.push_back(hz.nwgt[j]);
      hz.nwgt[j] = 0;
    }
    rebalance_km1(hz, k, cap, part);
    refine_km1(hz, k, cap, part, 3);
    for (size_t i = 0; i < sel.nets.size(); ++i)
      hz.nwgt[sel.nets[i]] = saved[i];
    const i64 obj = cache_objective(h, part, k, budget).obj;
    if (obj < best_obj) {
      best_obj = obj;
      best = part;
    }
  }
  part = std::move(best);
  return best_obj;
}

}  // namespace

// ===================================================================== C ABI
extern "C" {

// Multilevel k-way graph partition, edge-cut objective.
// xadj[n+1], adjncy/adjwgt[xadj[n]], vwgt[n] (nullable -> 1s).
// Returns 0 on success; part_out[n], edgecut_out optional.
int sgcn_partition_graph(i32 n, const i64* xadj, const i32* adjncy,
                         const float* adjwgt, const i64* vwgt, int k,
                         double imbalance, int seed, i32* part_out,
                         i64* edgecut_out) {
  if (n <= 0 || k <= 0) return 1;
  Graph g;
  g.n = n;
  g.xadj.assign(xadj, xadj + n + 1);
  g.adj.assign(adjncy, adjncy + xadj[n]);
  if (adjwgt) g.wgt.assign(adjwgt, adjwgt + xadj[n]);
  else g.wgt.assign(xadj[n], 1.0f);
  if (vwgt) g.vwgt.assign(vwgt, vwgt + n);
  else g.vwgt.assign(n, 1);
  g.total_vwgt = std::accumulate(g.vwgt.begin(), g.vwgt.end(), (i64)0);
  std::vector<i32> part;
  if (k == 1) part.assign(n, 0);
  else {
    // multilevel restarts, best final cut kept (same policy as the
    // hypergraph side; closes the gp-vs-hp quality gap of VERDICT r3)
    const int restarts = restart_budget(n);
    i64 best = -1;
    std::vector<i32> cand;
    for (int r = 0; r < restarts; ++r) {
      partition_graph_ml(g, k, imbalance, seed + 7919 * r, cand);
      i64 score = edge_cut(g, cand);
      if (best < 0 || score < best) { best = score; part = cand; }
    }
  }
  std::copy(part.begin(), part.end(), part_out);
  if (edgecut_out) *edgecut_out = edge_cut(g, part);
  return 0;
}

// Multilevel column-net hypergraph partition, connectivity-1 (km1) objective.
// cells 0..ncells-1 with cellptr/cellnets adjacency into nets 0..nnets-1;
// cwgt nullable (-> 1s). part_out[ncells], km1_out optional.
int sgcn_partition_hypergraph(i32 ncells, i32 nnets, const i64* cellptr,
                              const i32* cellnets, const i64* cwgt, int k,
                              double imbalance, int seed, i32* part_out,
                              i64* km1_out) {
  if (ncells <= 0 || k <= 0) return 1;
  Hypergraph h = from_cells(ncells, nnets, cellptr, cellnets, cwgt);
  std::vector<i32> part;
  if (k == 1) part.assign(ncells, 0);
  else hypergraph_driver(h, k, imbalance, seed, part);
  std::copy(part.begin(), part.end(), part_out);
  if (km1_out) {
    PinCounts pc; pc.k = k;
    build_pincounts(h, part, pc);
    *km1_out = km1_total(h, pc);
  }
  return 0;
}

// Cache-aware flavor (hot-halo replication, docs/replication.md): same
// driver, then co-optimize the cut with the replica budget — a net whose
// source vertex is replicated costs 0, so refinement under the zeroed
// weights moves pins the cache already pays for.  ``km1_cache_out`` gets
// the cache-aware objective (km1 minus the selected top-B nets'
// contribution, selection by (λ−1)·pins); by construction it is <= the
// same objective evaluated on the cache-blind driver's partition at the
// same seed/balance (the blind partition is the first candidate kept).
// ``replica_budget <= 0`` degenerates to the plain driver with
// km1_cache_out == km1_out.
int sgcn_partition_hypergraph_cache(i32 ncells, i32 nnets,
                                    const i64* cellptr, const i32* cellnets,
                                    const i64* cwgt, int k, double imbalance,
                                    int seed, i32 replica_budget,
                                    i32* part_out, i64* km1_out,
                                    i64* km1_cache_out) {
  if (ncells <= 0 || k <= 0) return 1;
  Hypergraph h = from_cells(ncells, nnets, cellptr, cellnets, cwgt);
  std::vector<i32> part;
  i64 cache = 0;
  if (k == 1) part.assign(ncells, 0);
  else {
    hypergraph_driver(h, k, imbalance, seed, part);
    if (replica_budget > 0)
      cache = cache_cooptimize(h, k, imbalance, replica_budget, part);
  }
  std::copy(part.begin(), part.end(), part_out);
  i64 km1 = 0;
  {
    PinCounts pc; pc.k = k;
    build_pincounts(h, part, pc);
    km1 = km1_total(h, pc);
  }
  if (k > 1 && replica_budget <= 0)
    cache = km1;
  if (km1_out) *km1_out = km1;
  if (km1_cache_out) *km1_cache_out = cache;
  return 0;
}

// Buffer-scanning MatrixMarket coordinate reader used by the native CLI
// (role of the reference's C readers, Parallel-GCN/main.c:609-648,
// GCN-HP/main.cpp:366-405).  NOTE the Python path (sgcn_tpu/io/mtx.py) uses
// scipy's multithreaded fast_matrix_market parser, which measured faster
// than this single-threaded scanner — this exists so `sgcnpart` has no
// Python dependency, not as the Python loader.
// Line-aware: comments allowed anywhere, extra per-line tokens (e.g. the
// imaginary part of complex files) ignored.  Symmetric/skew storage
// expanded, pattern values = 1.0.  Outputs malloc'd arrays owned by the
// caller (release with sgcn_free).  Returns 0 ok, 1 io error, 2 malformed,
// 3 out of memory.
int sgcn_read_mtx(const char* path, i64* nrows_out, i64* ncols_out,
                  i64* nnz_out, i32** row_out, i32** col_out,
                  float** val_out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 1;
  // 64-bit size probe: long is 32-bit on LLP64, so >2 GiB files would
  // overflow a plain ftell there; read in chunks until EOF instead.
  std::vector<char> buf;
  {
#if defined(_WIN32)
    if (_fseeki64(f, 0, SEEK_END) == 0) {
      long long sz = _ftelli64(f);
#else
    if (fseeko(f, 0, SEEK_END) == 0) {
      off_t sz = ftello(f);
#endif
      if (sz > 0) buf.reserve((size_t)sz + 1);   // one allocation, no 2x peak
    }
    std::rewind(f);
    std::vector<char> chunk(1 << 20);   // heap: callers may run on small stacks
    size_t got;
    while ((got = std::fread(chunk.data(), 1, chunk.size(), f)) > 0)
      buf.insert(buf.end(), chunk.data(), chunk.data() + got);
    if (std::ferror(f)) { std::fclose(f); return 1; }
  }
  std::fclose(f);
  const size_t fsize = buf.size();
  buf.push_back('\0');

  const char* p = buf.data();
  const char* end = p + fsize;
  bool symmetric = false, skew = false, pattern = false;
  bool header_done = false;
  long long nr = 0, nc = 0, declared = 0;
  size_t cap = 0, nnz = 0;
  i32* rows = nullptr;
  i32* cols = nullptr;
  float* vals = nullptr;
  auto fail = [&](int rc) {
    std::free(rows); std::free(cols); std::free(vals);
    return rc;
  };

  while (p < end) {
    // start of line: skip blank lines, handle comments anywhere
    while (p < end && (*p == '\n' || *p == '\r')) ++p;
    if (p >= end) break;
    const char* nl = (const char*)memchr(p, '\n', end - p);
    const char* lend = nl ? nl : end;
    if (*p == '%') {
      if (!header_done && (size_t)(lend - p) > 14 &&
          std::strncmp(p, "%%MatrixMarket", 14) == 0) {
        std::string line(p, lend);
        symmetric = line.find("symmetric") != std::string::npos;
        skew = line.find("skew-symmetric") != std::string::npos;
        pattern = line.find("pattern") != std::string::npos;
      }
      p = lend;
      continue;
    }
    char* q;
    if (!header_done) {
      nr = strtoll(p, &q, 10);
      if (q == p) return fail(2);
      p = q;
      nc = strtoll(p, &q, 10);
      if (q == p) return fail(2);
      p = q;
      declared = strtoll(p, &q, 10);
      if (q == p) return fail(2);
      if (nr <= 0 || nc <= 0 || declared < 0) return fail(2);
      cap = (symmetric || skew) ? 2 * (size_t)declared : (size_t)declared;
      if (cap == 0) cap = 1;               // malloc(0) may return NULL
      rows = (i32*)std::malloc(cap * sizeof(i32));
      cols = (i32*)std::malloc(cap * sizeof(i32));
      vals = (float*)std::malloc(cap * sizeof(float));
      if (!rows || !cols || !vals) return fail(3);
      header_done = true;
      p = lend;
      continue;
    }
    long long i = strtoll(p, &q, 10);
    if (q == p) return fail(2);
    p = q;
    long long j = strtoll(p, &q, 10);
    if (q == p) return fail(2);
    p = q;
    double v = 1.0;
    if (!pattern) {
      v = strtod(p, &q);
      if (q == p) return fail(2);
    }
    --i; --j;
    if (i < 0 || j < 0 || i >= nr || j >= nc || nnz >= cap) return fail(2);
    rows[nnz] = (i32)i; cols[nnz] = (i32)j; vals[nnz] = (float)v;
    ++nnz;
    if ((symmetric || skew) && i != j) {
      if (nnz >= cap) return fail(2);
      rows[nnz] = (i32)j; cols[nnz] = (i32)i;
      vals[nnz] = skew ? -(float)v : (float)v;
      ++nnz;
    }
    p = lend;                              // extra tokens (complex) ignored
  }
  if (!header_done) return fail(2);
  *nrows_out = nr; *ncols_out = nc; *nnz_out = (i64)nnz;
  *row_out = rows; *col_out = cols; *val_out = vals;
  return 0;
}

void sgcn_free(void* ptr) { std::free(ptr); }

}  // extern "C"

// ===================================================================== CLI
// sgcnpart -a graph.mtx -k 4 [-m g|h|r] [-o out.part] [-e imbalance] [-s seed]
// Reference CLI analogues: GCN-GP/main.cpp (gcngp), GCN-HP/main.cpp (gcnhgp),
// GPU/graph + GPU/hypergraph partvec generators.
#ifdef SGCNPART_MAIN
#include <chrono>
#include <cmath>
#include <fstream>
#include <sstream>

namespace {

struct Coo { i32 n = 0; std::vector<i32> row, col; std::vector<float> val; };

bool read_mtx(const std::string& path, Coo& out) {
  // thin wrapper over the shared buffer-scanning parser (sgcn_read_mtx)
  i64 nr = 0, nc = 0, nnz = 0;
  i32 *rows = nullptr, *cols = nullptr;
  float* vals = nullptr;
  int rc = sgcn_read_mtx(path.c_str(), &nr, &nc, &nnz, &rows, &cols, &vals);
  if (rc != 0) {
    const char* why = rc == 1 ? "cannot open"
                    : rc == 3 ? "out of memory reading"
                    : "malformed mtx";
    std::fprintf(stderr, "%s %s\n", why, path.c_str());
    return false;
  }
  out.n = (i32)std::max(nr, nc);
  out.row.assign(rows, rows + nnz);
  out.col.assign(cols, cols + nnz);
  out.val.assign(vals, vals + nnz);
  sgcn_free(rows); sgcn_free(cols); sgcn_free(vals);
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  std::string path, out_path;
  int k = 2, seed = 1, replica_budget = 0;
  double imbalance = 0.03;
  char mode = 'h';
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> std::string { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "-a") path = next();
    else if (a == "-k") k = std::stoi(next());
    else if (a == "-m") mode = next()[0];
    else if (a == "-o") out_path = next();
    else if (a == "-e") imbalance = std::stod(next());
    else if (a == "-s") seed = std::stoi(next());
    else if (a == "-B") replica_budget = std::stoi(next());
    else { std::fprintf(stderr, "unknown flag %s\n", a.c_str()); return 2; }
  }
  if (path.empty() || k < 1 ||
      (mode != 'g' && mode != 'h' && mode != 'r') ||
      (replica_budget > 0 && mode != 'h')) {
    std::fprintf(stderr,
        "usage: sgcnpart -a graph.mtx -k K [-m g|h|r] [-o out] [-e imb] "
        "[-s seed] [-B replica_budget (mode h only: cache-aware km1)]\n");
    return 2;
  }
  Coo coo;
  if (!read_mtx(path, coo)) { std::fprintf(stderr, "cannot read %s\n", path.c_str()); return 1; }
  i32 n = coo.n;
  std::vector<i32> part(n, 0);
  i64 metric = 0, metric_cache = 0;
  auto t0 = std::chrono::steady_clock::now();
  if (mode == 'r') {
    Rng rng((uint64_t)seed);
    for (i32 v = 0; v < n; ++v) part[v] = (i32)rng.below(k);
  } else if (mode == 'g') {
    // symmetrize into CSR (graph model), dedup'd: the reader already expands
    // symmetric storage, and general files may list both directions
    std::vector<i64> keys;
    keys.reserve(2 * coo.row.size());
    for (size_t e = 0; e < coo.row.size(); ++e) {
      i64 i = coo.row[e], j = coo.col[e];
      if (i == j) continue;
      keys.push_back(i * (i64)n + j);
      keys.push_back(j * (i64)n + i);
    }
    std::sort(keys.begin(), keys.end());
    keys.erase(std::unique(keys.begin(), keys.end()), keys.end());
    std::vector<i64> xadj(n + 1, 0);
    std::vector<i32> adj(keys.size());
    std::vector<float> wgt(keys.size(), 1.0f);
    for (i64 key : keys) xadj[key / n + 1]++;
    for (i32 v = 0; v < n; ++v) xadj[v + 1] += xadj[v];
    for (size_t e = 0; e < keys.size(); ++e) adj[e] = (i32)(keys[e] % n);
    sgcn_partition_graph(n, xadj.data(), adj.data(), wgt.data(), nullptr, k,
                         imbalance, seed, part.data(), &metric);
  } else {
    // column-net hypergraph: cells = rows, nets = cols, weight = row nnz
    std::vector<i64> cellptr(n + 1, 0);
    for (size_t e = 0; e < coo.row.size(); ++e) cellptr[coo.row[e] + 1]++;
    std::vector<i64> cwgt(n);
    for (i32 v = 0; v < n; ++v) { cwgt[v] = std::max<i64>(1, cellptr[v + 1]); }
    for (i32 v = 0; v < n; ++v) cellptr[v + 1] += cellptr[v];
    std::vector<i32> cellnets(coo.row.size());
    std::vector<i64> pos(cellptr.begin(), cellptr.end() - 1);
    for (size_t e = 0; e < coo.row.size(); ++e)
      cellnets[pos[coo.row[e]]++] = coo.col[e];
    if (replica_budget > 0)
      sgcn_partition_hypergraph_cache(
          n, n, cellptr.data(), cellnets.data(), cwgt.data(), k, imbalance,
          seed, replica_budget, part.data(), &metric, &metric_cache);
    else
      sgcn_partition_hypergraph(n, n, cellptr.data(), cellnets.data(),
                                cwgt.data(), k, imbalance, seed, part.data(),
                                &metric);
  }
  auto t1 = std::chrono::steady_clock::now();
  double secs = std::chrono::duration<double>(t1 - t0).count();
  // part sizes for the balance report
  std::vector<i64> sizes(k, 0);
  for (i32 v = 0; v < n; ++v) sizes[part[v]]++;
  i64 maxs = *std::max_element(sizes.begin(), sizes.end());
  if (replica_budget > 0)
    std::printf("n=%d k=%d mode=%c metric=%lld metric_cache=%lld B=%d "
                "max_part=%lld time_s=%.3f\n",
                n, k, mode, (long long)metric, (long long)metric_cache,
                replica_budget, (long long)maxs, secs);
  else
    std::printf("n=%d k=%d mode=%c metric=%lld max_part=%lld time_s=%.3f\n",
                n, k, mode, (long long)metric, (long long)maxs, secs);
  if (!out_path.empty()) {
    std::ofstream o(out_path);
    for (i32 v = 0; v < n; ++v) o << part[v] << "\n";
  }
  return 0;
}
#endif  // SGCNPART_MAIN
